"""Benchmark: scheduler placement throughput, CPU iterator stack vs
batched TPU kernel, across the BASELINE.md config matrix.

Configs (BASELINE.md "Numbers we must produce"):
  1  100 nodes, service job with 3 task groups (smoke)
  2  1k nodes, batch job, CPU+mem bin-pack only          <- default headline
  3  5k nodes, datacenter + meta constraints, mixed service/batch
  4  10k nodes, 50k existing allocs, ports + distinct_hosts (north star)
  5  system drain storm: system jobs replanned on node drain (CPU path;
     system scheduling is pinned-placement, no search to accelerate)

The CPU baseline runs the reference iterator pipeline (stack.select per
placement, scheduler/stack.go:37); the TPU path runs the same
placements as one batched dense program (ops/binpack.py), B evals
vmapped per dispatch against a shared on-device cluster matrix — the
broker drain-to-batch design from BASELINE.json's north star.

Usage:
  python bench.py            # headline config, ONE JSON line
  python bench.py --config 4 # one config, ONE JSON line
  python bench.py --all      # full matrix, one JSON line per config
"""

import argparse
import json
import random
import sys
import time

sys.path.insert(0, ".")

import numpy as np

HEADLINE_CONFIG = 4  # the north-star 10k-node/50k-alloc scenario


# ------------------------------------------------------------- builders


def build_cluster(n_nodes, datacenters=("dc1",), meta_partitions=0,
                  allocs_per_node=0, seed=0, alloc_skew=0,
                  filler_cpu=(50, 100), filler_mem=(64, 128)):
    """A mock cluster: nodes spread over datacenters, optional 'rack'
    meta partitions (stack_test.go's 64-way partition shape), optional
    pre-existing allocations consuming capacity. alloc_skew > 0 makes
    the pre-load HETEROGENEOUS — each node carries rng.randint(0,
    alloc_skew) filler allocs instead of a uniform count — the
    fragmentation-prone shape the --kernel-ab arm measures on."""
    from nomad_tpu import mock
    from nomad_tpu.state import StateStore
    from nomad_tpu.structs import consts

    rng = random.Random(seed)
    store = StateStore()
    index = 0
    filler = None
    if allocs_per_node or alloc_skew:
        filler = mock.job()
        filler.id = "filler"
        filler.type = "service"
        filler.task_groups[0].tasks[0].resources.networks = []
    for i in range(n_nodes):
        node = mock.node()
        node.datacenter = datacenters[i % len(datacenters)]
        if meta_partitions:
            node.meta["rack"] = f"r{i % meta_partitions}"
        node.compute_class()
        index += 1
        store.upsert_node(index, node)
        n_fill = allocs_per_node
        if alloc_skew:
            n_fill = rng.randint(0, alloc_skew)
        if n_fill:
            allocs = []
            for _ in range(n_fill):
                alloc = mock.alloc()
                alloc.node_id = node.id
                alloc.job_id = filler.id
                alloc.job = filler
                alloc.desired_status = consts.ALLOC_DESIRED_RUN
                alloc.client_status = consts.ALLOC_CLIENT_RUNNING
                # modest footprint so nodes stay schedulable
                for tr in alloc.task_resources.values():
                    tr.cpu = rng.choice(list(filler_cpu))
                    tr.memory_mb = rng.choice(list(filler_mem))
                    tr.networks = []
                alloc.resources = None
                allocs.append(alloc)
            index += 1
            store.upsert_allocs(index, allocs)
    return store, index


def service_job(n_groups=1, constraints=None, networks=True,
                distinct_hosts=False, job_type="service"):
    from nomad_tpu import mock
    from nomad_tpu.structs import Constraint, consts

    job = mock.job()
    job.type = job_type
    tg0 = job.task_groups[0]
    job.task_groups = []
    for gi in range(n_groups):
        tg = tg0.copy()
        tg.name = f"g{gi}"
        if not networks:
            tg.tasks[0].resources.networks = []
        if distinct_hosts:
            tg.constraints.append(
                Constraint(operand=consts.CONSTRAINT_DISTINCT_HOSTS))
        job.task_groups.append(tg)
    for c in constraints or []:
        job.constraints.append(c)
    return job


# ---------------------------------------------------------------- paths


def bench_cpu(store, job, k_placements, evals, tg_cycle=None):
    """Reference pipeline: per-eval stack.select loop."""
    from nomad_tpu.scheduler.context import EvalContext
    from nomad_tpu.scheduler.stack import GenericStack
    from nomad_tpu.scheduler.util import ready_nodes_in_dcs
    from nomad_tpu.structs import Allocation, Plan
    from nomad_tpu.utils.ids import generate_uuid

    snap = store.snapshot()
    groups = job.task_groups
    tg_cycle = tg_cycle or [0] * k_placements
    latencies = []
    placed = 0
    start = time.perf_counter()
    for i in range(evals):
        t0 = time.perf_counter()
        plan = Plan(job=job)
        ctx = EvalContext(snap, plan, rng=random.Random(i))
        stack = GenericStack(job.type == "batch", ctx)
        stack.set_job(job)
        nodes, _ = ready_nodes_in_dcs(snap, job.datacenters)
        stack.set_nodes(nodes)
        for gi in tg_cycle:
            tg = groups[gi]
            option, _ = stack.select(tg)
            if option is None:
                continue
            placed += 1
            plan.append_alloc(
                Allocation(
                    id=generate_uuid(),
                    job_id=job.id,
                    node_id=option.node.id,
                    task_group=tg.name,
                    task_resources=dict(option.task_resources),
                )
            )
        latencies.append(time.perf_counter() - t0)
    elapsed = time.perf_counter() - start
    assert placed == evals * len(tg_cycle), (
        f"cpu path placed {placed}/{evals * len(tg_cycle)}")
    return evals / elapsed, float(np.percentile(latencies, 99))


def bench_tpu(store, job, k_placements, batch, rounds, tg_cycle=None,
              require_all=True):
    """Batched dense program: `batch` evals per dispatch."""
    import jax

    from nomad_tpu.models.matrix import ClusterMatrix
    from nomad_tpu.ops.binpack import (
        PlacementConfig,
        batched_placement_program_shared,
        make_asks,
        make_node_state,
    )

    snap = store.snapshot()
    matrix = ClusterMatrix(snap, job)
    state = make_node_state(
        matrix.capacity, matrix.sched_capacity, matrix.util,
        matrix.bw_avail, matrix.bw_used, matrix.ports_free,
        matrix.job_count, matrix.tg_count, matrix.feasible, matrix.node_ok,
    )
    tg_cycle = tg_cycle or [0] * k_placements
    asks = make_asks(*matrix.build_asks(tg_cycle))

    # The cluster matrix lives on device across dispatches (it changes
    # only when the snapshot does); per dispatch only keys move.
    state = jax.tree.map(jax.device_put, state)
    asks = jax.tree.map(jax.device_put, asks)
    penalty = 5.0 if job.type == "batch" else 10.0
    config = PlacementConfig(anti_affinity_penalty=penalty)

    def dispatch(seed):
        keys = jax.random.split(jax.random.PRNGKey(seed), batch)
        choices, scores, _ = batched_placement_program_shared(
            state, asks, keys, config
        )
        return choices

    warm = np.asarray(dispatch(0))
    if require_all:
        assert (warm[:, : len(tg_cycle)] >= 0).all(), \
            "warmup produced failed placements"

    # Latency: one synchronous round including its result fetch — the
    # submit-to-answer time every eval in that batch observes.
    t0 = time.perf_counter()
    np.asarray(dispatch(1))
    sync_latency = time.perf_counter() - t0

    # Throughput: pipeline the dispatches (JAX async dispatch overlaps
    # them) and fetch all results in one device->host transfer — the
    # broker sidecar streams results the same way.
    start = time.perf_counter()
    outs = [dispatch(r + 2) for r in range(rounds)]
    results = [np.asarray(o) for o in outs]
    elapsed = time.perf_counter() - start
    if require_all:
        for out in results:
            assert (out[:, : len(tg_cycle)] >= 0).all()
    return batch * rounds / elapsed, sync_latency


def bench_tpu_e2e(store, job, k_placements, batch, rounds, tg_cycle=None,
                  workers=None, pre_resolve=True, kernel="greedy",
                  executive=True, executive_threads=4):
    """Honest FULL-PATH dense measurement (VERDICT r4 ask #2): per
    eval — ClusterMatrix build (live shared-base cache), ask
    construction, a coalesced batcher dispatch, exact host-side port
    assignment, and Allocation materialization into a Plan — the same
    per-eval work the production dense scheduler does
    (scheduler/tpu.py _compute_placements), measured against
    bench_cpu's stack.select + plan-append loop. Evals run on a thread
    pool so their place() calls coalesce in the batcher exactly like
    concurrent workers' do.

    Also measures the conflict bill the plan applier would present:
    after each round, an applier-style sequential verification replays
    every eval's placements against shared claimed capacity
    (plan_apply.go:194 semantics for capacity/bandwidth/ports; evals
    model distinct jobs, so distinct_hosts is per-eval and out of
    scope). An eval with any rejected placement would replan — one
    extra dispatch round-trip in production. The claim state resets
    per round so the count isolates IN-DISPATCH conflicts, exactly
    what PlacementConfig.pre_resolve (the device-side eval-axis
    serialization) exists to remove — the live cross-batch residue is
    measured by configs 6/8's pipeline stats instead.

    Returns (rate, p99, stats) where stats carries the batcher delta
    (occupancy = batched_requests/dispatches) plus
    conflicted_evals/evals."""
    from concurrent.futures import ThreadPoolExecutor
    from types import SimpleNamespace

    from nomad_tpu.models.matrix import ClusterMatrix
    from nomad_tpu.ops.binpack import (
        PlacementConfig,
        host_prng_key,
        make_asks,
    )
    from nomad_tpu.scheduler.batcher import PlacementBatcher
    from nomad_tpu.scheduler.tpu import _build_allocation, _offer_networks
    from nomad_tpu.scheduler.util import AllocTuple
    from nomad_tpu.structs import AllocMetric, Plan

    snap = store.snapshot()
    tg_cycle = tg_cycle or [0] * k_placements
    penalty = 5.0 if job.type == "batch" else 10.0
    config = PlacementConfig(anti_affinity_penalty=penalty,
                             pre_resolve=pre_resolve, kernel=kernel)
    # Mirror the live dense scheduler (scheduler/tpu.py): a uniform
    # distinct-hosts ask set takes the one-pass top_k program
    # (greedy-only; other kernels run their own joint solve).
    from nomad_tpu.ops.binpack import uniform_dh_flag

    _probe_asks = ClusterMatrix(snap, job).build_asks(tg_cycle)
    config = config._replace(uniform_dh=(
        kernel == "greedy" and uniform_dh_flag(
            tg_cycle, _probe_asks[5], _probe_asks[6])))
    from nomad_tpu.chaos import chaos
    from nomad_tpu.trace import (
        STAGE_DEVICE_DISPATCH,
        STAGE_MATRIX_BUILD,
        get_recorder,
    )

    recorder = get_recorder()

    batcher = PlacementBatcher()
    sched_stub = SimpleNamespace(eval=SimpleNamespace(id="bench"), job=job)
    # Degraded-mode harness (--chaos): an injected device fault fails a
    # whole batch; each member retries its place() — the live dense
    # scheduler falls back to the host path instead, but the bench's
    # evals must stay on the device path to keep measuring it, so here
    # a retry IS the recovery and gets counted (under a lock: a failed
    # batch fails all its pool threads at once and += is not atomic
    # across a GIL switch).
    import threading

    device_retries = [0]
    retry_lock = threading.Lock()
    if workers is None:
        # The live drain-to-batch path processes a drained group fully
        # concurrently (server/worker.py submits the whole group to the
        # shared eval pool), so the honest mirror runs every eval of a
        # round at once — fragmenting the batch across a smaller pool
        # would pay extra device round-trips production doesn't.
        workers = batch

    from nomad_tpu import profile as _profile

    def one_eval(seed):
        # Trace spans mirror the live dense scheduler's stage
        # attribution (scheduler/tpu.py) so the bench's per-stage p99
        # table reads off the same flight recorder as production.
        eid = f"bench-{seed}"
        t0 = time.perf_counter()
        tm0 = time.monotonic()
        matrix = ClusterMatrix(snap, job)
        asks = make_asks(*matrix.build_asks(tg_cycle))
        recorder.record_span(eid, STAGE_MATRIX_BUILD, tm0)
        tm1 = time.monotonic()
        # Lock-wait attribution onto the dispatch span (the contention
        # observatory's per-thread contended-wait delta across the
        # batcher round-trip).
        wait0 = _profile.thread_wait_ms()
        for attempt in range(3):
            try:
                choices, scores = batcher.place(
                    matrix, asks, host_prng_key(seed), config,
                    span=(eid, ""))
                break
            except Exception:
                if not chaos.enabled or attempt == 2:
                    raise
                with retry_lock:
                    device_retries[0] += 1
        recorder.record_span(
            eid, STAGE_DEVICE_DISPATCH, tm1,
            ann={"lock_wait_ms": round(
                _profile.thread_wait_ms() - wait0, 3)})
        tm2 = time.monotonic()
        choices = np.asarray(choices)
        placed = materialize(seed, matrix, choices, np.asarray(scores))
        recorder.record_span(eid, "host.finalize", tm2)
        recorder.complete(eid)
        return placed, time.perf_counter() - t0, choices

    def materialize(seed, matrix, choices, scores):
        """The per-placement choices -> ports -> Allocation loop both
        arms of the A/B run (one_eval's tail and the executive's
        finalize) — factored so the two arms can never silently
        measure different per-eval work."""
        rng_local = random.Random(seed)
        plan = Plan(job=job)
        net_indexes = {}
        placed = 0
        for j, gi in enumerate(tg_cycle):
            tg = job.task_groups[gi]
            missing = AllocTuple(
                name=f"{job.id}.{tg.name}[{j}]", task_group=tg, alloc=None)
            choice = int(choices[j])
            node = (matrix.nodes[choice]
                    if 0 <= choice < matrix.n_real else None)
            if node is None:
                continue
            metrics = AllocMetric()
            metrics.nodes_evaluated = matrix.n_real
            metrics.nodes_available = matrix.nodes_by_dc
            metrics.score_node(node, "binpack", float(scores[j]))
            task_resources = _offer_networks(
                rng_local, missing, node, net_indexes, matrix)
            if task_resources is None:
                continue
            plan.append_alloc(_build_allocation(
                sched_stub, missing, node, task_resources, metrics))
            placed += 1
        return placed

    def build_eval(seed):
        """Executive mode, host side: one eval's matrix + asks (the
        per-row work the live executive does on/near its loop thread,
        server/executive.py _build_row)."""
        eid = f"bench-{seed}"
        tm0 = time.monotonic()
        matrix = ClusterMatrix(snap, job)
        asks = make_asks(*matrix.build_asks(tg_cycle))
        recorder.record_span(eid, STAGE_MATRIX_BUILD, tm0)
        return (eid, seed, matrix, asks)

    def finalize_eval(row, choices, scores, t_round):
        """Executive mode: exact ports + Allocation materialization for
        one cohort row (the shared `materialize` loop). Per-eval
        latency is round-open -> this row's plan materialized (at
        round open the whole cohort is 'ready', exactly like a
        drained batch)."""
        eid, seed, matrix, _asks = row
        tm2 = time.monotonic()
        choices = np.asarray(choices)
        placed = materialize(seed, matrix, choices, np.asarray(scores))
        recorder.record_span(eid, "host.finalize", tm2)
        recorder.complete(eid)
        return placed, time.perf_counter() - t_round, choices

    pool = ThreadPoolExecutor(
        max_workers=(executive_threads if executive else workers))
    # Separate finalize pool in executive mode: round k+1's builds must
    # not queue behind round k's finalize tail on one FIFO pool — the
    # whole point of the lookahead is the device dispatch (GIL-released
    # XLA) running UNDER the GIL-bound finalize work.
    finalize_pool = (ThreadPoolExecutor(max_workers=executive_threads)
                     if executive else pool)

    def run_round_executive_async(base_seed, n):
        """The scheduler-executive shape (ROADMAP open item 1): eval
        identity is a batch row, not a thread. Rows build on a SMALL
        pool (`executive_threads`; numpy releases the GIL — 4 helps, 64
        was the measured convoy), the whole cohort ships as ONE no-park
        device dispatch (PlacementBatcher.place_cohort), and results
        materialize on the same small pool — returned as futures so the
        NEXT round's build+dispatch overlaps this round's finalize tail
        (the live executive's overlap: `_process_cohort` hands its
        finalize futures back and goes straight to the next drain).
        Nothing ever parks on a batcher event, so the batch-boundary
        convoy (BENCH_r13: width 63/64, runq.batch_park p99 55.1ms)
        cannot form."""
        t_round = time.perf_counter()
        rows = [f.result() for f in [
            pool.submit(build_eval, base_seed + i) for i in range(n)]]
        tm1 = time.monotonic()
        wait0 = _profile.thread_wait_ms()
        for attempt in range(3):
            try:
                results = batcher.place_cohort([
                    (row[2], row[3], host_prng_key(row[1]), config,
                     (row[0], "")) for row in rows])
                break
            except Exception:
                if not chaos.enabled or attempt == 2:
                    raise
                with retry_lock:
                    device_retries[0] += 1
        ann = {"lock_wait_ms": round(
            _profile.thread_wait_ms() - wait0, 3), "cohort": n}
        for row in rows:
            recorder.record_span(row[0], STAGE_DEVICE_DISPATCH, tm1,
                                 ann=ann)
        return [finalize_pool.submit(finalize_eval, row, c, s, t_round)
                for row, (c, s) in zip(rows, results)]

    def run_round(base_seed, n=None):
        count = n if n is not None else batch
        if executive:
            return [f.result()
                    for f in run_round_executive_async(base_seed, count)]
        # Mirror the live dispatch pipeline's fan-out announcement so
        # the batcher holds the dispatch for the whole round's
        # staggered matrix builds.
        batcher.add_cohort(count)
        futs = [pool.submit(one_eval, base_seed + i)
                for i in range(count)]
        return [f.result() for f in futs]

    # Applier-style verification reference: one matrix + ask rows
    # (shared by construction — every eval asks the same tg_cycle).
    vmatrix = ClusterMatrix(snap, job)
    v_res, v_bw, v_ports, _vi, _va, _vj, _vt = vmatrix.build_asks(tg_cycle)

    def verify_round(results):
        """Sequential capacity claims over one round's placements;
        returns (evals that would replan, the round's ADMITTED claimed
        utilization) — the same applier-admission rule feeds both the
        conflict count and the quality columns, so the two can't
        drift."""
        claimed_util = np.zeros_like(vmatrix.util)
        claimed_bw = np.zeros_like(vmatrix.bw_used)
        claimed_ports = np.zeros_like(vmatrix.ports_free)
        conflicted = 0
        for _placed, _t, choices in results:
            bad = False
            for j in range(len(tg_cycle)):
                c = int(choices[j])
                if not (0 <= c < vmatrix.n_real):
                    continue
                ok = (
                    np.all(vmatrix.util[c] + claimed_util[c] + v_res[j]
                           <= vmatrix.capacity[c])
                    and (vmatrix.bw_used[c] + claimed_bw[c] + v_bw[j]
                         <= vmatrix.bw_avail[c])
                    and (vmatrix.ports_free[c] - claimed_ports[c]
                         >= v_ports[j])
                )
                if not ok:
                    bad = True
                    continue
                claimed_util[c] += v_res[j]
                claimed_bw[c] += v_bw[j]
                claimed_ports[c] += v_ports[j]
            conflicted += bad
        return conflicted, claimed_util

    # Warm EVERY batch bucket the dispatcher can produce (plus the
    # full size twice): ragged accumulation means a measured round can
    # fragment into any of the ladder sizes, and one unwarmed shape is
    # a multi-second trace+compile through a remote tunnel — enough to
    # wreck a p99 on its own.
    from nomad_tpu.scheduler.batcher import BATCH_BUCKETS

    # Warmup rounds stay OUT of the stage-attribution table (they
    # measure compile caches, not steady state); restore whatever arm
    # (--no-trace) the CLI selected afterwards.
    _trace_was = recorder.enabled
    recorder.set_enabled(False)
    for i, warm_n in enumerate((batch, batch) + tuple(BATCH_BUCKETS) + (1,)):
        if warm_n <= batch:
            run_round(10_000 + i * 1000, n=warm_n)
    recorder.set_enabled(_trace_was)
    stats0 = batcher.stats()
    latencies = []
    placed_total = 0
    conflicted_evals = 0
    start = time.perf_counter()
    round_results = []
    if executive:
        # One-round lookahead: round k+1's builds + device dispatch
        # (XLA releases the GIL) run under round k's GIL-bound finalize
        # tail — the executive's cohort pipelining, measured the same
        # way the live loop overlaps finalize futures with the next
        # drain.
        pending = None
        for r in range(rounds):
            futs = run_round_executive_async(20_000 + r * batch, batch)
            if pending is not None:
                round_results.append([f.result() for f in pending])
            pending = futs
        round_results.append([f.result() for f in pending])
        for results in round_results:
            for placed, t, _choices in results:
                latencies.append(t)
                placed_total += placed
    else:
        for r in range(rounds):
            results = run_round(20_000 + r * batch)
            round_results.append(results)
            for placed, t, _choices in results:
                latencies.append(t)
                placed_total += placed
    elapsed = time.perf_counter() - start
    # Verification outside the timed window: production pays it on the
    # applier thread, overlapped with the next dispatch.
    first_round_claims = None
    for results in round_results:
        conflicted, claimed = verify_round(results)
        conflicted_evals += conflicted
        if first_round_claims is None:
            first_round_claims = claimed
    stats1 = batcher.stats()
    pool.shutdown(wait=False)
    if finalize_pool is not pool:
        finalize_pool.shutdown(wait=False)
    assert placed_total > 0, "e2e path placed nothing"
    dstats = {k: stats1[k] - stats0[k] for k in stats1}
    n_evals = batch * rounds
    dstats["occupancy"] = (
        dstats["batched_requests"] / dstats["dispatches"]
        if dstats.get("dispatches") else 0.0)
    dstats["conflicts_per_eval"] = conflicted_evals / n_evals
    dstats["device_retries"] = device_retries[0]
    # Device-residency columns (models/resident.py): host->device
    # bytes per dispatched batch in steady state (a resident base
    # rides the cache/delta paths — re-shipping the full [N,R] matrix
    # here is the regression the design removed), and the jit
    # compile-cache GROWTH across the measured (post-warmup) rounds —
    # steady state must be 0; --check refuses dense numbers otherwise.
    dstats["transfer_bytes_per_batch"] = (
        dstats.get("upload_bytes", 0) / max(dstats.get("dispatches", 0), 1))
    dstats["jit_recompiles"] = dstats.get("jit_cache_size", 0)
    # Placement-quality columns (nomad_tpu/kernels/quality): score the
    # committed cluster state one round of this workload produces —
    # base utilization plus the round's verified sequential claims
    # (verify_round: exactly what the applier would admit) — against
    # the job's own ask. queueing_delay_ms here is the harness
    # measurement of the quality contract's "p99 time placement work
    # spent queued": this path has no broker, so the queue is the
    # batcher — place() round-trip p99 minus the jitted solve's p99
    # (both from the flight recorder; 0 when --no-trace disabled it).
    # The live configs measure the same contract at THEIR queue, the
    # broker (broker.wait p99 via the quality board).
    from nomad_tpu.kernels.quality import quality_from_arrays

    q = quality_from_arrays(vmatrix.util + first_round_claims,
                            vmatrix.capacity, vmatrix.node_ok, v_res[0])
    dstats["fragmentation"] = q["fragmentation"]
    dstats["binpack_score"] = q["binpack_score"]
    stages = recorder.stage_stats()
    dd = stages.get("device.dispatch", {}).get("p99_ms", 0.0)
    sv = stages.get("device.solve", {}).get("p99_ms", 0.0)
    dstats["queueing_delay_ms"] = max(0.0, dd - sv)
    return (n_evals / elapsed, float(np.percentile(latencies, 99)),
            dstats)


# -------------------------------------------------------------- configs


def config_1():
    """100-node smoke: service job, 3 task groups."""
    store, _ = build_cluster(100)
    job = service_job(n_groups=3, networks=False)
    cycle = [0, 1, 2] * 2  # 6 placements across the 3 groups
    cpu_rate, cpu_p99 = bench_cpu(store, job, len(cycle), evals=50,
                                  tg_cycle=cycle)
    tpu_rate, tpu_p99 = bench_tpu(store, job, len(cycle), batch=2048,
                                  rounds=8, tg_cycle=cycle)
    e2e_rate, e2e_p99, ds = bench_tpu_e2e(store, job, len(cycle), batch=64,
                                          rounds=4, tg_cycle=cycle)
    return {
        "name": "100 nodes, service x3 task groups",
        "cpu": cpu_rate, "cpu_p99_ms": cpu_p99 * 1000,
        "kernel": tpu_rate, "kernel_p99_ms": tpu_p99 * 1000,
        "e2e": e2e_rate, "e2e_p99_ms": e2e_p99 * 1000,
        "occupancy": ds["occupancy"],
        "retries_per_eval": ds["conflicts_per_eval"],
        **_quality_cols(ds),
    }


def _quality_cols(ds):
    """The placement-quality columns every config reports
    (kernels/quality.py: fragmentation / bin-pack / queueing)."""
    return {
        "fragmentation": ds.get("fragmentation", 0.0),
        "binpack_score": ds.get("binpack_score", 0.0),
        "queueing_delay_ms": ds.get("queueing_delay_ms", 0.0),
    }


def config_2():
    """1k nodes, batch, CPU+mem only."""
    store, _ = build_cluster(1000)
    job = service_job(networks=False, job_type="batch")
    job.task_groups[0].count = 8
    cpu_rate, cpu_p99 = bench_cpu(store, job, 8, evals=30)
    tpu_rate, tpu_p99 = bench_tpu(store, job, 8, batch=2048, rounds=8)
    e2e_rate, e2e_p99, ds = bench_tpu_e2e(store, job, 8, batch=64, rounds=4)
    return {
        "name": "1k nodes x 8 allocs/eval (cpu+mem bin-pack)",
        "cpu": cpu_rate, "cpu_p99_ms": cpu_p99 * 1000,
        "kernel": tpu_rate, "kernel_p99_ms": tpu_p99 * 1000,
        "e2e": e2e_rate, "e2e_p99_ms": e2e_p99 * 1000,
        "occupancy": ds["occupancy"],
        "retries_per_eval": ds["conflicts_per_eval"],
        **_quality_cols(ds),
    }


def config_3():
    """5k nodes, dc + meta constraints, mixed service/batch."""
    from nomad_tpu.structs import Constraint

    store, _ = build_cluster(
        5000, datacenters=("dc1", "dc2", "dc3", "dc4"), meta_partitions=64)
    cons = [Constraint(ltarget="${meta.rack}", operand="regexp",
                       rtarget="^r(1?[0-9]|2[0-9]|3[01])$")]  # racks 0-31
    svc = service_job(constraints=cons, networks=False)
    svc.datacenters = ["dc1", "dc2"]
    bat = service_job(constraints=cons, networks=False, job_type="batch")
    bat.datacenters = ["dc3", "dc4"]

    cpu_s, cpu_p99_s = bench_cpu(store, svc, 8, evals=10)
    cpu_b, cpu_p99_b = bench_cpu(store, bat, 8, evals=10)
    tpu_s, tpu_p99_s = bench_tpu(store, svc, 8, batch=1024, rounds=4)
    tpu_b, tpu_p99_b = bench_tpu(store, bat, 8, batch=1024, rounds=4)
    e2e_s, e2e_p99_s, ds_s = bench_tpu_e2e(store, svc, 8, batch=32, rounds=4)
    e2e_b, e2e_p99_b, ds_b = bench_tpu_e2e(store, bat, 8, batch=32, rounds=4)
    # mixed workload: aggregate rate = half service + half batch
    return {
        "name": "5k nodes, dc + rack-regexp constraints, mixed svc/batch",
        "cpu": 2.0 / (1.0 / cpu_s + 1.0 / cpu_b),
        "cpu_p99_ms": max(cpu_p99_s, cpu_p99_b) * 1000,
        "kernel": 2.0 / (1.0 / tpu_s + 1.0 / tpu_b),
        "kernel_p99_ms": max(tpu_p99_s, tpu_p99_b) * 1000,
        "e2e": 2.0 / (1.0 / e2e_s + 1.0 / e2e_b),
        "e2e_p99_ms": max(e2e_p99_s, e2e_p99_b) * 1000,
        "occupancy": (ds_s["occupancy"] + ds_b["occupancy"]) / 2,
        "retries_per_eval": (ds_s["conflicts_per_eval"]
                             + ds_b["conflicts_per_eval"]) / 2,
        "fragmentation": (ds_s["fragmentation"]
                          + ds_b["fragmentation"]) / 2,
        "binpack_score": (ds_s["binpack_score"]
                          + ds_b["binpack_score"]) / 2,
        "queueing_delay_ms": max(ds_s["queueing_delay_ms"],
                                 ds_b["queueing_delay_ms"]),
    }


def config_4(executive=True):
    """North star: 10k nodes, 50k existing allocs, dynamic ports +
    distinct_hosts. The e2e column runs full 64-lane batches with
    in-batch conflict pre-resolution, plus a pre-resolve-OFF A/B so the
    retries column shows what the device-side serialization buys.
    Since PR 12 the e2e arms run the scheduler-executive shape (cohort
    rows + one no-park dispatch) by default; `--executive-ab` pairs it
    against the legacy 64-thread worker shape."""
    store, _ = build_cluster(10_000, datacenters=("dc1", "dc2"),
                             allocs_per_node=5)
    job = service_job(networks=True, distinct_hosts=True)
    job.datacenters = ["dc1", "dc2"]
    job.task_groups[0].count = 8
    # 20 CPU evals: at 5 the column was so short (~0.15 s) that host
    # load swung the headline ratio ±40% run to run.
    cpu_rate, cpu_p99 = bench_cpu(store, job, 8, evals=20)
    tpu_rate, tpu_p99 = bench_tpu(store, job, 8, batch=512, rounds=4)
    e2e_rate, e2e_p99, ds = bench_tpu_e2e(store, job, 8, batch=64, rounds=4,
                                          executive=executive)
    _ab_rate, _ab_p99, ds_off = bench_tpu_e2e(
        store, job, 8, batch=64, rounds=2, pre_resolve=False,
        executive=executive)
    return {
        "name": "10k nodes, 50k allocs, ports + distinct_hosts",
        "cpu": cpu_rate, "cpu_p99_ms": cpu_p99 * 1000,
        "kernel": tpu_rate, "kernel_p99_ms": tpu_p99 * 1000,
        "e2e": e2e_rate, "e2e_p99_ms": e2e_p99 * 1000,
        "occupancy": ds["occupancy"],
        "retries_per_eval": ds["conflicts_per_eval"],
        "retries_per_eval_nopre": ds_off["conflicts_per_eval"],
        "device_retries": ds["device_retries"] + ds_off["device_retries"],
        "transfer_bytes_per_batch": ds["transfer_bytes_per_batch"],
        "jit_recompiles": ds["jit_recompiles"],
        **_quality_cols(ds),
    }


def _system_drain_storm(n_nodes, n_jobs, rack_partition):
    """System drain storm: every system job replans when nodes drain.
    System scheduling pins each placement to its node (no search), so
    the dense path ("system-tpu", scheduler/tpu.py
    DenseSystemScheduler) replaces the per-node iterator stack with one
    vectorized feasibility+fit pass per eval.

    At blueprint scale (10k x 200, BASELINE.json config 5) each system
    job is constrained to its rack partition (n_nodes/n_jobs nodes) —
    each eval still scans ALL nodes for feasibility (the storm cost
    that scales), while placement counts stay bounded the way real
    rack-scoped system jobs are."""
    from nomad_tpu import mock
    from nomad_tpu.scheduler.testing import Harness
    from nomad_tpu.structs import Constraint, consts

    def build():
        harness = Harness()
        store = harness.state
        index = 0
        for i in range(n_nodes):
            node = mock.node()
            if rack_partition:
                node.meta["rack"] = f"r{i % n_jobs}"
            node.compute_class()
            index += 1
            store.upsert_node(index, node)
        jobs = []
        for j in range(n_jobs):
            job = mock.system_job()
            job.id = f"sys-{j}"
            if rack_partition:
                job.constraints.append(Constraint(
                    ltarget="${meta.rack}", operand="=", rtarget=f"r{j}"))
            job.task_groups[0].tasks[0].resources.networks = []
            job.task_groups[0].tasks[0].resources.cpu = 5
            job.task_groups[0].tasks[0].resources.memory_mb = 8
            index += 1
            store.upsert_job(index, job)
            jobs.append(job)
        # Drain 10% of nodes -> server creates one eval per system job
        # (node_endpoint.go:812 createNodeEvals).
        for node in store.nodes()[: n_nodes // 10]:
            index += 1
            store.update_node_drain(index, node.id, True)
        harness._next_index = index + 1
        evals = []
        for job in jobs:
            ev = mock.eval()
            ev.job_id = job.id
            ev.type = consts.JOB_TYPE_SYSTEM
            ev.triggered_by = consts.EVAL_TRIGGER_NODE_UPDATE
            evals.append(ev)
        return harness, evals

    def run(scheduler_name):
        harness, evals = build()
        latencies = []
        start = time.perf_counter()
        for ev in evals:
            t0 = time.perf_counter()
            harness.process(scheduler_name, ev)
            latencies.append(time.perf_counter() - t0)
        elapsed = time.perf_counter() - start
        return (len(evals) / elapsed, float(np.percentile(latencies, 99)),
                harness)

    cpu_rate, cpu_p99, _h = run("system")
    dense_rate, dense_p99, h_dense = run("system-tpu")
    # Quality columns from the COMMITTED post-storm store (the harness
    # applies plans sequentially — exactly the oracle's commit).
    from nomad_tpu.kernels.quality import quality_from_store

    q = quality_from_store(h_dense.state.snapshot(),
                           h_dense.state.job_by_id("sys-0"))
    return cpu_rate, cpu_p99, dense_rate, dense_p99, q


def _drain_migration_arm(n_nodes, n_jobs, allocs_per_job, budget=8,
                         drain_frac=0.1, seed=4242):
    """Service-job drain migration on the dense path (the churn PR's
    config-5 extension): place service allocs, drain a slice of the
    cluster, and drive the displaced set through the migration budget
    (nomad_tpu/migrate) — follow-up migration evals included — to a
    fully re-placed cluster. Reports:

    - migrations_per_s: committed displaced-alloc evictions+re-places
      per second of storm wall clock (allocs_per_job must exceed the
      budget: a job eval's migrate set is bounded by its own alloc
      count, and the arm asserts the deferral machinery engaged);
    - disruption_p99_ms: per displaced alloc, drain-to-replacement-
      committed latency (the wave that re-placed it), p99.

    The governor's high-water mark is asserted <= budget — numbers
    from an unbounded thundering herd would not be measuring the
    dense drain path this config claims to."""
    from nomad_tpu import mock
    from nomad_tpu.migrate import configure as migrate_configure
    from nomad_tpu.migrate import get_governor
    from nomad_tpu.scheduler.testing import Harness
    from nomad_tpu.structs import consts
    from nomad_tpu.structs.eval import new_eval

    h = Harness(seed=seed)
    nodes = []
    for _ in range(n_nodes):
        node = mock.node()
        node.compute_class()
        h.state.upsert_node(h.next_index(), node)
        nodes.append(node)
    jobs = []
    for j in range(n_jobs):
        job = mock.job()
        job.id = f"mig-{j}"
        job.task_groups[0].count = allocs_per_job
        task = job.task_groups[0].tasks[0]
        task.resources.cpu = 20
        task.resources.memory_mb = 16
        task.resources.networks = []
        h.state.upsert_job(h.next_index(), job)
        jobs.append(h.state.job_by_id(job.id))
    for job in jobs:
        h.process("service-tpu",
                  new_eval(job, consts.EVAL_TRIGGER_JOB_REGISTER))

    from nomad_tpu.migrate import DEFAULT_MAX_PARALLEL

    migrate_configure(migrate_max_parallel=budget)
    try:
        get_governor().reset_stats()
        # Drain the MOST-OCCUPIED nodes: BestFit concentrates the fleet
        # onto few nodes, so draining by creation order can displace
        # nothing (a vacuous measurement). Draining where the allocs live
        # also guarantees per-eval migrate sets larger than the budget —
        # the deferral/wave machinery this arm exists to measure.
        occupancy = {}
        for a in h.state.allocs():
            if not a.terminal_status():
                occupancy[a.node_id] = occupancy.get(a.node_id, 0) + 1
        by_load = sorted(occupancy, key=occupancy.get, reverse=True)
        n_drain = max(1, int(n_nodes * drain_frac))
        drained = set(by_load[:n_drain])
        drained |= {n.id for n in nodes[: n_drain - len(drained)]}
        displaced = {a.id for a in h.state.allocs()
                     if a.node_id in drained and not a.terminal_status()}
        assert displaced, "drain arm displaced nothing: not measuring"
        for nid in drained:
            h.state.update_node_drain(h.next_index(), nid, True)

        affected = [j for j in jobs
                    if any(a.node_id in drained
                           for a in h.state.allocs_by_job(j.id))]
        pending = [new_eval(j, consts.EVAL_TRIGGER_NODE_UPDATE)
                   for j in affected]
        seen_created = len(h.create_evals)
        disruption = {}
        t_drain = time.perf_counter()
        while pending:
            for ev in pending:
                h.process("service-tpu", ev)
                t_done = time.perf_counter()
                for a in h.state.allocs_by_eval(ev.id):
                    prev = a.previous_allocation
                    if prev in displaced and prev not in disruption:
                        disruption[prev] = t_done - t_drain
            created = h.create_evals[seen_created:]
            seen_created = len(h.create_evals)
            pending = [e for e in created
                       if e.triggered_by == consts.EVAL_TRIGGER_MIGRATION]
        elapsed = time.perf_counter() - t_drain

        migrated = [a for a in h.state.allocs()
                    if a.id in displaced
                    and a.desired_status == consts.ALLOC_DESIRED_STOP]
        g = get_governor().stats()
        assert migrated, "drain arm migrated nothing: not measuring"
        assert g["high_water"] <= max(budget, 1), g
        # The budget must have actually engaged (per-eval displacement
        # exceeds it by construction above) — a zero deferral count means
        # the numbers describe an unpressured path.
        assert g["deferred_total"] > 0, g
        live_by_job = {
            j.id: [a for a in h.state.allocs_by_job(j.id)
                   if not a.terminal_status()] for j in jobs}
        assert all(len(v) == allocs_per_job for v in live_by_job.values()), {
            k: len(v) for k, v in live_by_job.items()}
        assert all(a.node_id not in drained
                   for v in live_by_job.values() for a in v)
        p99 = (float(np.percentile(list(disruption.values()), 99))
               if disruption else 0.0)
        return {
            "migrations": len(migrated),
            "migrations_per_s": len(migrated) / elapsed if elapsed else 0.0,
            "disruption_p99_ms": p99 * 1000,
            "migration_budget": budget,
            "migration_high_water": g["high_water"],
            "migration_deferred": g["deferred_total"],
        }
    finally:
        # The governor is process-global: restore the default so a
        # later config/arm in the same run measures its own budget,
        # not whichever arm ran last (run_preempt_ab does the same).
        migrate_configure(migrate_max_parallel=DEFAULT_MAX_PARALLEL)


def config_5():
    """Blueprint-scale drain storm (BASELINE.json config 5): 10k nodes
    x 200 rack-scoped system jobs, 10% drained — plus the service-side
    migration arm (1k nodes) driving displaced allocs through the
    dense path under the migration budget."""
    cpu_rate, cpu_p99, dense_rate, dense_p99, q = _system_drain_storm(
        10_000, 200, rack_partition=True)
    mig = _drain_migration_arm(1000, 20, 24)
    return {
        "name": ("drain storm: 10k nodes x 200 system jobs (rack-scoped),"
                 " 10% drained (host stack vs dense pass) + service "
                 "migration arm (1k nodes, budgeted)"),
        "cpu": cpu_rate, "cpu_p99_ms": cpu_p99 * 1000,
        "e2e": dense_rate, "e2e_p99_ms": dense_p99 * 1000,
        **_quality_cols(q),
        **mig,
    }


def config_5s():
    """Smoke-scale drain storm (kept for quick runs): 1k x 50,
    unconstrained (every job spans every node), with a small service
    migration arm."""
    cpu_rate, cpu_p99, dense_rate, dense_p99, q = _system_drain_storm(
        1000, 50, rack_partition=False)
    mig = _drain_migration_arm(400, 12, 20)
    return {
        "name": ("drain storm smoke: 1k nodes x 50 system jobs, 10% "
                 "drained (host stack vs dense pass) + migration arm"),
        "cpu": cpu_rate, "cpu_p99_ms": cpu_p99 * 1000,
        "e2e": dense_rate, "e2e_p99_ms": dense_p99 * 1000,
        **_quality_cols(q),
        **mig,
    }


def _live_pipeline(n_nodes, n_jobs, allocs_per_job, lone_jobs=12,
                   allocs_per_node=0, networks=False,
                   distinct_hosts=False, warm_jobs=40):
    """End-to-end control plane: the REAL server pipeline (broker ->
    workers -> drain-to-batch -> scheduler -> plan queue -> pipelined
    applier -> FSM) with CPU vs TPU factories on identical clusters.
    This measures the BASELINE.json acceptance criterion directly:
    evals/sec at identical plan-apply success rate.

    Two regimes per factory set:
    - STORM: workers paused while all jobs register, then released
      against a deep broker — the drain-to-batch path coalesces evals
      into shared device dispatches (server/worker.py dequeue_many +
      scheduler/batcher.py overlay dispatch).
    - LONE: sequential single-eval registrations on an idle broker —
      with dense factories configured, latency-aware routing
      (dense_min_batch) must send these to the host path, so the p99
      should match the CPU column's.

    Returns per-factory rates plus the TPU run's batcher-stat delta
    (incl. the per-dispatch host/transfer/RTT breakdown)."""
    from nomad_tpu import mock
    from nomad_tpu.scheduler.batcher import get_batcher
    from nomad_tpu.server import Server, ServerConfig
    from nomad_tpu.structs import Constraint, consts

    rng = random.Random(11)

    def wait_evals(server, evals, deadline_s):
        deadline = time.perf_counter() + deadline_s
        while time.perf_counter() < deadline:
            st = [server.fsm.state.eval_by_id(e) for e in evals]
            if all(s is not None and s.status in
                   (consts.EVAL_STATUS_COMPLETE,
                    consts.EVAL_STATUS_FAILED) for s in st):
                return
            time.sleep(0.02)

    def make_job(jid):
        job = mock.job()
        job.id = jid
        job.type = "service"
        job.task_groups[0].count = allocs_per_job
        tg = job.task_groups[0]
        if not networks:
            tg.tasks[0].resources.networks = []
        if distinct_hosts:
            tg.constraints.append(
                Constraint(operand=consts.CONSTRAINT_DISTINCT_HOSTS))
        tg.tasks[0].resources.cpu = 20
        tg.tasks[0].resources.memory_mb = 16
        return job

    def run(factories):
        from nomad_tpu.kernels.quality import get_board

        get_board().reset()  # per-arm attribution, not cross-run
        server = Server(ServerConfig(
            num_schedulers=4, scheduler_factories=factories,
            # PR 12: the live dense path runs the scheduler executive
            # (cohort drain + no-park dispatch); inert for the CPU arm
            # (no dense factories). --executive-ab pairs it against the
            # legacy worker/pipeline shape.
            scheduler_executive=True,
            eval_nack_timeout=60.0))
        server.start()
        batcher = get_batcher()
        try:
            filler = None
            if allocs_per_node:
                filler = mock.job()
                filler.id = "filler"
                filler.type = "service"
                filler.task_groups[0].tasks[0].resources.networks = []
            for _ in range(n_nodes):
                node = mock.node()
                node.compute_class()
                server.log.apply("node_register", {"node": node})
                if allocs_per_node:
                    fills = []
                    for _ in range(allocs_per_node):
                        alloc = mock.alloc()
                        alloc.node_id = node.id
                        alloc.job_id = filler.id
                        alloc.job = filler
                        alloc.desired_status = consts.ALLOC_DESIRED_RUN
                        alloc.client_status = consts.ALLOC_CLIENT_RUNNING
                        for tr in alloc.task_resources.values():
                            tr.cpu = rng.choice([50, 100])
                            tr.memory_mb = rng.choice([64, 128])
                            tr.networks = []
                        alloc.resources = None
                        fills.append(alloc)
                    server.log.apply(
                        "alloc_update", {"allocs": fills})

            # WARMUP (unmeasured): TWO storm waves sized like the
            # measured one, so every program the storm will run is
            # compiled first — wave 1 hits the full-upload compact
            # programs across the B buckets, wave 2 (running against
            # the allocs wave 1 committed) hits the fused base-delta
            # variants. A live server is long-running — shapes compile
            # once per bucket and cache (utils/jaxcache persists them
            # across processes), so the steady state is what to
            # measure. Without wave 2, fused-delta compiles landed
            # inside the measured storm and dominated its wall-clock.
            for wave in ("warmA", "warmB"):
                warm = [make_job(f"{wave}-{j}")
                        for j in range(max(warm_jobs, n_jobs))]
                for w in server.workers:
                    w.set_pause(True)
                server.executive.set_pause(True)
                wevals = [server.job_register(job)[0] for job in warm]
                for w in server.workers:
                    w.set_pause(False)
                server.executive.set_pause(False)
                wait_evals(server, wevals, 600)
                for job in warm:
                    server.job_deregister(job.id)
                # Settle: dereg evals must drain before the next wave.
                deadline = time.perf_counter() + 120
                while time.perf_counter() < deadline:
                    s = server.broker.stats()
                    if not s["total_ready"] and not s["total_unacked"]:
                        break
                    time.sleep(0.05)

            jobs = [make_job(f"e2e-{j}") for j in range(n_jobs)]
            stats0 = batcher.stats()
            # STORM: fill the broker while workers (and the executive
            # drain) are parked, then release — the regime the cohort
            # drain exists for.
            for w in server.workers:
                w.set_pause(True)
            server.executive.set_pause(True)
            evals = [server.job_register(job)[0] for job in jobs]
            start = time.perf_counter()
            for w in server.workers:
                w.set_pause(False)
            server.executive.set_pause(False)
            wait_evals(server, evals, 300)
            storm_elapsed = time.perf_counter() - start
            placed = sum(len(server.fsm.state.allocs_by_job(j.id))
                         for j in jobs)
            success = placed / (n_jobs * allocs_per_job)

            # LONE: idle broker, one eval at a time, per-eval latency.
            lat = []
            for j in range(lone_jobs):
                job = make_job(f"lone-{j}")
                t0 = time.perf_counter()
                ev = server.job_register(job)[0]
                wait_evals(server, [ev], 60)
                lat.append(time.perf_counter() - t0)
            stats1 = batcher.stats()
            dstats = {k: stats1[k] - stats0[k] for k in stats1}
            # The dispatch pipeline + applier live per-server: their
            # stats ARE this run's deltas.
            dstats["pipeline"] = server.dispatch.stats()
            dstats["executive"] = server.stats()["scheduler_executive"]
            dstats["applier"] = server.plan_applier.stats()
            # Overload counters (nomad_tpu/admission): a non-overload
            # config that shed or expired evals measured a server
            # protecting itself, not the dense path — --check gates
            # dense-path numbers on this column staying zero.
            dstats["broker"] = server.broker.stats()
            # Placement-quality scoreboard (kernels/quality.py): the
            # dense run's committed-plan medians + broker-wait p99.
            dstats["placement_quality"] = server.stats()[
                "placement_quality"]
            return (n_jobs / storm_elapsed, success,
                    float(np.percentile(lat, 99)), dstats)
        finally:
            server.shutdown()

    cpu_rate, cpu_success, cpu_lone_p99, _ = run({})
    tpu_rate, tpu_success, tpu_lone_p99, dstats = run(
        {"service": "service-tpu", "batch": "batch-tpu"})
    assert abs(cpu_success - tpu_success) < 1e-9, (
        f"success-rate mismatch: cpu={cpu_success} tpu={tpu_success}")
    return (cpu_rate, cpu_success, cpu_lone_p99,
            tpu_rate, tpu_success, tpu_lone_p99, dstats)


def _trivial_rtt_us() -> float:
    """Round-trip of a near-empty jitted program: through a remote
    device tunnel this measures pure transport RTT — the floor any
    dispatch pays regardless of payload or compute."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def probe(x):
        return x + 1

    probe(jnp.float32(0)).block_until_ready()  # compile
    samples = []
    for i in range(5):
        t0 = time.perf_counter()
        probe(jnp.float32(i)).block_until_ready()
        samples.append(time.perf_counter() - t0)
    return float(np.median(samples) * 1e6)


def _breakdown_str(dstats) -> str:
    """Per-dispatch cost breakdown: host stacking / h->d payload /
    issue / device round-trip, plus the transport floor."""
    n = max(dstats.get("dispatches", 0), 1)
    return (
        f"per-dispatch: stack {dstats.get('stack_us', 0) / n:.0f}us, "
        f"payload {dstats.get('payload_bytes', 0) / n / 1024:.0f}KB, "
        f"issue {dstats.get('issue_us', 0) / n:.0f}us, "
        f"sync {dstats.get('sync_us', 0) / n:.0f}us; "
        f"uploads {dstats.get('base_uploads', 0)} full "
        f"({dstats.get('upload_bytes', 0) / 1024:.0f}KB total) + "
        f"{dstats.get('base_delta_updates', 0)} delta, "
        f"{dstats.get('upload_us', 0) / 1000:.0f}ms; "
        f"trivial-RTT floor {_trivial_rtt_us():.0f}us"
    )


def config_6():
    """Live pipeline at storm scale: 1k nodes x 120 service jobs."""
    n_nodes, n_jobs, allocs_per_job = 1000, 120, 4
    (cpu_rate, cpu_success, cpu_lone_p99,
     tpu_rate, tpu_success, tpu_lone_p99, dstats) = _live_pipeline(
        n_nodes, n_jobs, allocs_per_job)
    return _live_result(
        f"end-to-end pipeline, {n_nodes} nodes x {n_jobs} jobs x "
        f"{allocs_per_job} allocs, 4 workers",
        cpu_rate, cpu_success, cpu_lone_p99,
        tpu_rate, tpu_success, tpu_lone_p99, dstats)


def config_8():
    """North-star LIVE regime (BASELINE.md config 6 notes): 10k nodes,
    50k existing allocs, ports + distinct_hosts, through the REAL
    control plane."""
    n_nodes, n_jobs, allocs_per_job = 10_000, 60, 8
    (cpu_rate, cpu_success, cpu_lone_p99,
     tpu_rate, tpu_success, tpu_lone_p99, dstats) = _live_pipeline(
        n_nodes, n_jobs, allocs_per_job, lone_jobs=6, allocs_per_node=5,
        networks=True, distinct_hosts=True, warm_jobs=16)
    return _live_result(
        f"north-star live pipeline, {n_nodes} nodes, {n_nodes * 5} "
        f"allocs, ports+distinct_hosts, {n_jobs} jobs x {allocs_per_job},"
        " 4 workers",
        cpu_rate, cpu_success, cpu_lone_p99,
        tpu_rate, tpu_success, tpu_lone_p99, dstats)


def _live_result(name, cpu_rate, cpu_success, cpu_lone_p99,
                 tpu_rate, tpu_success, tpu_lone_p99, dstats):
    """Per-rep live-run columns. Everything run-dependent goes into
    NUMERIC columns so run_config medianizes it — stats baked into the
    name string would silently report rep 1 only, the exact
    single-shot trap the median rework exists to close. (The per-rep
    batcher cost breakdown still prints on stderr for debugging.)"""
    occupancy = (dstats["batched_requests"] / dstats["dispatches"]
                 if dstats.get("dispatches") else 0.0)
    pipe = dstats.get("pipeline", {})
    exe = dstats.get("executive", {})
    if exe.get("enabled"):
        # The scheduler executive superseded the pipeline for this run:
        # its cohort columns fill the same slots (occupancy = evals per
        # cohort; conflicts = refresh-index'd plans; the requeue
        # machinery does not exist on the no-park path).
        done = max(exe.get("acked", 0) + exe.get("nacked", 0), 1)
        pipe = {
            "occupancy": exe.get("occupancy", 0.0),
            "largest_batch": exe.get("largest_cohort", 0),
            "plan_conflicts": exe.get("plan_conflicts", 0),
            "requeues": 0,
            "inline_retries": exe.get("plan_conflicts", 0),
            "retries_per_eval": exe.get("plan_conflicts", 0) / done,
            "prefetch_bytes": 0,
        }
    applier = dstats.get("applier", {})
    print(f"# {name} [rep detail] batcher: "
          f"{dstats.get('dispatches', 0)} dispatches x {occupancy:.1f} "
          f"evals, {dstats.get('compact_dispatches', 0)} compact; "
          + _breakdown_str(dstats), file=sys.stderr)
    return {
        "name": name,
        "cpu": cpu_rate,
        "cpu_p99_ms": cpu_lone_p99 * 1000,
        "e2e": tpu_rate,
        "e2e_p99_ms": tpu_lone_p99 * 1000,
        "success_cpu": cpu_success,
        "success_tpu": tpu_success,
        "occupancy": occupancy,
        "pipeline_occupancy": pipe.get("occupancy", 0.0),
        "pipeline_largest_batch": pipe.get("largest_batch", 0),
        "plan_conflicts": pipe.get("plan_conflicts", 0),
        "requeues": pipe.get("requeues", 0),
        "inline_retries": pipe.get("inline_retries", 0),
        "applier_plans_rejected": applier.get("plans_rejected", 0),
        "applier_plans_evaluated": applier.get("plans_evaluated", 0),
        "retries_per_eval": pipe.get("retries_per_eval", 0.0),
        "shed": (dstats.get("broker", {}).get("shed", 0)
                 + dstats.get("broker", {}).get("expired", 0)),
        "transfer_bytes_per_batch": (
            dstats.get("upload_bytes", 0)
            / max(dstats.get("dispatches", 0), 1)),
        "jit_recompiles": dstats.get("jit_cache_size", 0),
        "prefetch_bytes": pipe.get("prefetch_bytes", 0),
        "executive_fast_evals": exe.get("fast_evals", 0),
        "executive_legacy_evals": exe.get("legacy_evals", 0),
        "cohort_dispatches": dstats.get("cohort_dispatches", 0),
        **_live_quality_cols(dstats.get("placement_quality", {})),
    }


def _live_quality_cols(pq):
    """Quality columns for the live configs, read off the server's
    placement_quality snapshot: the ACTIVE kernel's medians (one
    kernel per run) + the broker-wait queueing p99."""
    kernels = pq.get("kernels", {})
    q = next(iter(kernels.values()), {}) if kernels else {}
    return {
        "fragmentation": q.get("fragmentation", 0.0),
        "binpack_score": q.get("binpack_score", 0.0),
        "queueing_delay_ms": pq.get("queueing_delay_ms", 0.0),
    }


CONFIGS = {1: config_1, 2: config_2, 3: config_3, 4: config_4, 5: config_5,
           6: config_6, 7: config_5s, 8: config_8}

# Default repetitions: ±30-40% run-to-run swings (BASELINE.md) make a
# single shot meaningless — the headline gates on the MEDIAN of
# interleaved CPU/TPU reps (VERDICT r5 weak #2). Each rep runs its CPU
# and TPU columns back to back, so drift hits both.
DEFAULT_REPS = 5


def _median_iqr(vals):
    med = float(np.median(vals))
    iqr = float(np.percentile(vals, 75) - np.percentile(vals, 25))
    return med, iqr


def run_config(n, reps=DEFAULT_REPS):
    from nomad_tpu.profile import get_profiler
    from nomad_tpu.trace import get_recorder

    get_recorder().reset()  # per-config stage attribution, not cross-config
    get_profiler().reset()  # per-config contention columns likewise
    runs = [CONFIGS[n]() for _ in range(reps)]
    return _summarize(n, runs, reps)


def run_config_trace_ab(n, reps=DEFAULT_REPS):
    """run_config with an INTERLEAVED traced/untraced arm per rep: each
    rep runs the config with the flight recorder on, then immediately
    again with it off, and the overhead is the MEDIAN of per-rep
    e2e ratios — pairing cancels host-load drift exactly like the
    cpu/tpu columns' interleaving (two sequential 3-rep arms measured
    ±12% 'overhead' in BOTH directions on an idle box). Returns
    (summary-of-traced-runs, median ratio)."""
    from nomad_tpu.trace import get_recorder

    rec = get_recorder()
    rec.reset()
    runs = []
    ratios = []
    untraced_rates = []
    try:
        for _ in range(reps):
            rec.set_enabled(True)
            r = CONFIGS[n]()
            runs.append(r)
            rec.set_enabled(False)
            u = CONFIGS[n]()
            ratios.append(r["e2e"] / u["e2e"])
            untraced_rates.append(u["e2e"])
    finally:
        rec.set_enabled(True)
    out = _summarize(n, runs, reps)
    ratio, _ = _median_iqr(ratios)
    out["trace_overhead"] = {
        "traced_e2e": out["columns"]["e2e"]["median"],
        "untraced_e2e": round(float(np.median(untraced_rates)), 3),
        "ratio": round(float(ratio), 4),
        "per_rep_ratios": [round(float(x), 4) for x in ratios],
    }
    out["metric"] += (
        f"; trace overhead: paired-ratio median x{ratio:.3f} "
        f"(traced {out['trace_overhead']['traced_e2e']:.1f} vs untraced "
        f"{out['trace_overhead']['untraced_e2e']:.1f} evals/s)")
    return out, float(ratio)


def run_config_profile_ab(n, reps=DEFAULT_REPS):
    """run_config with an INTERLEAVED observatory-on/fully-dark arm
    per rep (the --profile-off arm, paired): each rep runs the config
    with the contention observatory AND the flight recorder on — what
    production runs — then immediately again with BOTH off, and the
    overhead is the MEDIAN of per-rep e2e ratios (same pairing
    discipline as the trace A/B, gating the whole always-on
    observability stack at once). The dark arm also keeps its spans
    out of the recorder, so the stage table the gap attribution reads
    covers exactly the runs the contention histograms cover. Returns
    (summary-of-profiled-runs, median ratio). The summary additionally
    carries the contention attribution of the device.dispatch tail:
    the p99-p50 gap against the top wait sites (per-lock waits + the
    batch-park run-queue delay) — the measured answer to BENCH_r10's
    GIL-queuing inference, captured as BENCH_r13."""
    from nomad_tpu.profile import get_profiler
    from nomad_tpu.trace import get_recorder

    rec = get_recorder()
    rec.reset()
    prof = get_profiler()
    prof.reset()
    runs = []
    ratios = []
    off_rates = []
    try:
        for _ in range(reps):
            prof.configure(enabled=True)
            rec.set_enabled(True)
            r = CONFIGS[n]()
            runs.append(r)
            prof.configure(enabled=False)
            rec.set_enabled(False)
            u = CONFIGS[n]()
            ratios.append(r["e2e"] / u["e2e"])
            off_rates.append(u["e2e"])
    finally:
        prof.configure(enabled=True)
        rec.set_enabled(True)
    out = _summarize(n, runs, reps)
    ratio, _ = _median_iqr(ratios)
    out["profile_overhead"] = {
        "profiled_e2e": out["columns"]["e2e"]["median"],
        "unprofiled_e2e": round(float(np.median(off_rates)), 3),
        "ratio": round(float(ratio), 4),
        "per_rep_ratios": [round(float(x), 4) for x in ratios],
    }
    out["contention_attribution"] = _gap_attribution(out)
    att = out["contention_attribution"]
    out["metric"] += (
        f"; profile overhead: paired-ratio median x{ratio:.3f}; "
        f"dispatch p99-p50 gap {att['gap_ms']:.1f}ms, top sites cover "
        f"{att['attributed_frac']:.0%}")
    return out, float(ratio)


def _gap_attribution(out):
    """Where the device.dispatch tail comes from: the p99-p50 gap of
    the dispatch stage vs the top contention sites' p99s — per-lock
    contended waits plus the batch-park run-queue delay (the direct
    measurement of 'GIL queuing of 64 eval threads around the batch
    boundary'). attributed_frac >= 0.5 is the acceptance bar: the
    observatory must EXPLAIN the gap it was built to measure."""
    stages = out.get("stage_table", {})
    dd = stages.get("device.dispatch", {})
    gap = max(0.0, dd.get("p99_ms", 0.0) - dd.get("p50_ms", 0.0))
    prof = out.get("profile", {})
    sites = [
        {"site": site, "p99_ms": s["wait_p99_ms"], "kind": "lock_wait"}
        for site, s in prof.get("lock_sites", {}).items()
    ]
    for site, p99 in prof.get("runq_p99_ms", {}).items():
        sites.append({"site": f"runq.{site}", "p99_ms": p99,
                      "kind": "runq_delay"})
    sites.sort(key=lambda s: -s["p99_ms"])
    top = sites[:3]
    attributed = sum(s["p99_ms"] for s in top)
    return {
        "device_dispatch_p50_ms": dd.get("p50_ms", 0.0),
        "device_dispatch_p99_ms": dd.get("p99_ms", 0.0),
        "gap_ms": round(gap, 3),
        "top_sites": top,
        "attributed_ms": round(attributed, 3),
        "attributed_frac": round(attributed / gap, 4) if gap else 0.0,
    }


def _summarize(n, runs, reps):
    name = runs[0]["name"]
    cols = {}
    for key in runs[0]:
        if key == "name":
            continue
        vals = [float(r[key]) for r in runs if key in r]
        med, iqr = _median_iqr(vals)
        cols[key] = {"median": round(med, 3), "iqr": round(iqr, 3),
                     "n": len(vals)}
    # Ratios pair per-rep so host-load drift cancels; the headline
    # multiplier is their MEDIAN, never a single shot.
    e2e_x, _ = _median_iqr([r["e2e"] / r["cpu"] for r in runs])
    med_e2e = cols["e2e"]["median"]
    out = {
        "metric": (
            f"[config {n}] {name}; median-of-{reps}: "
            f"cpu={cols['cpu']['median']:.1f} evals/s "
            f"(iqr {cols['cpu']['iqr']:.1f}), e2e={med_e2e:.1f} "
            f"(iqr {cols['e2e']['iqr']:.1f}), e2e_x={e2e_x:.2f}"
        ),
        "value": round(med_e2e, 1),
        "unit": "evals/sec",
        "n": reps,
        "iqr": cols["e2e"]["iqr"],
        "e2e_x": round(e2e_x, 2),
        "vs_baseline": round(e2e_x, 2),
        # Parity is CLAIMED only when the median clears it.
        "parity_on_median": bool(e2e_x >= 1.0),
        "columns": cols,
    }
    if "kernel" in cols:
        kernel_x, _ = _median_iqr([r["kernel"] / r["cpu"] for r in runs])
        out["kernel_x"] = round(kernel_x, 2)
        out["metric"] += f", kernel_x={kernel_x:.1f}"
    if "occupancy" in cols:
        out["occupancy"] = cols["occupancy"]["median"]
        out["metric"] += f"; occupancy={out['occupancy']:.1f} lanes"
    if "retries_per_eval" in cols:
        out["retries_per_eval"] = cols["retries_per_eval"]["median"]
        out["metric"] += f", retries/eval={out['retries_per_eval']:.3f}"
    if "retries_per_eval_nopre" in cols:
        out["retries_per_eval_nopre"] = cols["retries_per_eval_nopre"][
            "median"]
        out["metric"] += (
            f" (pre-resolve OFF: {out['retries_per_eval_nopre']:.3f})")
    if "fragmentation" in cols:
        out["metric"] += (
            f"; quality: frag={cols['fragmentation']['median']:.3f}, "
            f"binpack={cols['binpack_score']['median']:.3f}, "
            f"queue_p99={cols['queueing_delay_ms']['median']:.1f}ms")
    # Per-stage latency attribution from the flight recorder
    # (nomad_tpu/trace): where each eval's time went across the reps —
    # the in-system answer to "what is the p99 made of". Empty when
    # --no-trace disabled the recorder.
    from nomad_tpu.trace import get_recorder

    stages = get_recorder().stage_stats()
    if stages:
        out["stage_p99_ms"] = {
            k: v["p99_ms"] for k, v in sorted(stages.items())}
        out["stage_table"] = stages
        top = sorted(
            ((k, v["p99_ms"]) for k, v in stages.items() if k != "e2e"),
            key=lambda kv: -kv[1])[:3]
        out["metric"] += "; stage p99 " + ", ".join(
            f"{k}={v:.1f}ms" for k, v in top)
    out.update(_profile_cols())
    if "lock_wait_p99_ms" in out:
        out["metric"] += (
            f"; contention: lock_wait_p99={out['lock_wait_p99_ms']:.2f}ms"
            f", gil_overshoot_p99={out['gil_overshoot_p99_ms']:.2f}ms"
            f", convoy_width={out['convoy_width']}")
    return out


def _profile_cols():
    """Contention-observatory columns for every config (the satellite
    triple: combined contended lock-wait p99, GIL sleep-overshoot p99,
    and the widest batch-boundary convoy), plus the per-site wait
    table BENCH_r13's gap attribution reads. Empty when --profile-off
    disabled the observatory."""
    from nomad_tpu.profile import get_profiler
    from nomad_tpu.utils.metrics import HIST_BUCKETS, hist_percentile

    prof = get_profiler()
    if not prof.enabled:
        return {}
    # Combined wait p99 across every profiled site: one number for the
    # "how contended was this run" column; the per-site table carries
    # the attribution.
    merged = [0] * HIST_BUCKETS
    count = 0
    sites = {}
    for site, (c, total, buckets) in prof.lock_site_buckets("wait").items():
        count += c
        for i, v in enumerate(buckets):
            if v:
                merged[i] += v
        sites[site] = {
            "contended": c,
            "wait_total_ms": round(total, 3),
            "wait_p99_ms": round(hist_percentile(buckets, c, 0.99), 4),
        }
    gil = prof.gil.stats()
    convoys = prof.convoy_table()
    out = {
        "lock_wait_p99_ms": round(
            hist_percentile(merged, count, 0.99), 4) if count else 0.0,
        "gil_overshoot_p99_ms": gil.get("p99_ms", 0.0),
        "convoy_width": convoys["max_width"],
    }
    extra = {
        "lock_sites": dict(sorted(
            sites.items(),
            key=lambda kv: -kv[1]["wait_total_ms"])[:8]),
        "runq_p99_ms": {site: s.get("p99_ms", 0.0)
                        for site, s in prof.runq_table().items()},
        "convoys": convoys["convoys"],
    }
    out["profile"] = extra
    return out


def run_chaos(seed, reps=1):
    """Degraded-mode A/B of config 4 (the north-star cluster shape):
    one clean pass, then the same pass under a mild seeded fault
    schedule — device-dispatch latency jitter plus a forced device
    fault burst — reporting occupancy and retries/eval side by side so
    BENCH_r07.json records what the dense path delivers while faults
    fire. Refuses to emit numbers if any scheduled fault never fired
    (a schedule that missed its path measured nothing: typo guard)."""
    from nomad_tpu.chaos import FaultSpec, chaos

    clean = [CONFIGS[HEADLINE_CONFIG]() for _ in range(reps)]
    schedule = [
        # Mild: a congested tunnel adds ~20ms to a quarter of device
        # dispatches...
        FaultSpec("batcher.dispatch", "delay", delay=0.02, prob=0.25,
                  count=64),
        # ...and two dispatches fail outright (whole-batch retry).
        FaultSpec("binpack.device", "error", count=2, start=6),
    ]
    chaos.arm(seed, schedule)
    try:
        degraded = [CONFIGS[HEADLINE_CONFIG]() for _ in range(reps)]
        unfired = chaos.unfired()
        fired = len(chaos.firing_log())
    finally:
        chaos.disarm()
    if unfired:
        for spec in unfired:
            print(f"bench: scheduled fault never fired: {spec.to_dict()}",
                  file=sys.stderr)
        print("bench: REFUSING to emit chaos numbers — the schedule did "
              "not exercise its sites (typo or unreachable path)",
              file=sys.stderr)
        sys.exit(2)

    def med(runs, key):
        return float(np.median([r[key] for r in runs if key in r]))

    return {
        "metric": (
            f"[config {HEADLINE_CONFIG} +chaos seed={seed}] degraded-mode"
            f" A/B: clean e2e={med(clean, 'e2e'):.1f} evals/s occ="
            f"{med(clean, 'occupancy'):.1f}; chaos e2e="
            f"{med(degraded, 'e2e'):.1f} occ="
            f"{med(degraded, 'occupancy'):.1f}, "
            f"{fired} faults fired"
        ),
        "chaos_seed": seed,
        "faults_fired": fired,
        "clean": {
            "e2e": round(med(clean, "e2e"), 1),
            "occupancy": round(med(clean, "occupancy"), 2),
            "retries_per_eval": round(med(clean, "retries_per_eval"), 4),
            "device_retries": int(med(clean, "device_retries")),
        },
        "chaos": {
            "e2e": round(med(degraded, "e2e"), 1),
            "occupancy": round(med(degraded, "occupancy"), 2),
            "retries_per_eval": round(med(degraded, "retries_per_eval"), 4),
            "device_retries": int(med(degraded, "device_retries")),
        },
    }


def _overload_server(protection, cap):
    """Live server for one overload arm. Protection ON bounds the
    service ready queue, stamps deadlines, and arms the admission
    gate; OFF is the unbounded pre-PR-5 behaviour kept reachable for
    the A/B."""
    from nomad_tpu.server import Server, ServerConfig

    # eval_batch_size 8 (not the default 64): the pipeline's intake
    # backpressure engages at 2 full batches, so this keeps the
    # saturation bound (16) + ready cap at the storm's scale — the
    # protection being measured, not a queue too deep to ever fill.
    if protection:
        cfg = ServerConfig(
            num_schedulers=4,
            scheduler_factories={"service": "service-tpu"},
            eval_batch_size=8,
            eval_ready_caps={"service": cap},
            eval_deadline_ttl=15.0,
            eval_nack_timeout=60.0)
    else:
        cfg = ServerConfig(
            num_schedulers=4,
            scheduler_factories={"service": "service-tpu"},
            eval_batch_size=8,
            eval_ready_cap=0,
            admission_enabled=False,
            breaker_enabled=False,
            eval_nack_timeout=60.0)
    server = Server(cfg)
    server.start()
    return server


def _overload_job(jid, priority=None):
    from nomad_tpu import mock

    job = mock.job()
    job.id = jid
    job.type = "service"
    if priority is not None:
        job.priority = priority
    job.task_groups[0].count = 4
    tg = job.task_groups[0]
    tg.tasks[0].resources.networks = []
    tg.tasks[0].resources.cpu = 20
    tg.tasks[0].resources.memory_mb = 16
    return job


def _overload_wait(server, eval_ids, deadline_s=300.0):
    from nomad_tpu.structs import consts

    deadline = time.perf_counter() + deadline_s
    state = server.fsm.state
    while time.perf_counter() < deadline:
        evs = [state.eval_by_id(e) for e in eval_ids]
        if all(e is not None and e.status in
               (consts.EVAL_STATUS_COMPLETE,
                consts.EVAL_STATUS_FAILED) for e in evs):
            return
        time.sleep(0.02)
    raise TimeoutError("overload arm did not settle")


def _overload_storm(server, rate, n_submit, rng):
    """Submit `n_submit` jobs paced at 3x the measured capacity
    `rate`, polling completions as they land; returns goodput
    (accepted evals/s), shed_rate, accepted-eval p99 (ms), and the
    broker-depth samples taken at each submission."""
    from nomad_tpu.structs import consts

    interval = 1.0 / (3.0 * rate)
    pending = {}  # eval_id -> submit time
    latencies = {}  # eval_id -> (seconds, triggered_by)
    depths = []
    state = server.fsm.state
    broker0 = server.broker.stats()

    def poll():
        done = []
        for eid, t0 in pending.items():
            ev = state.eval_by_id(eid)
            if ev is not None and ev.status in (
                    consts.EVAL_STATUS_COMPLETE, consts.EVAL_STATUS_FAILED):
                latencies[eid] = (time.perf_counter() - t0, ev.triggered_by)
                done.append(eid)
        for eid in done:
            del pending[eid]

    start = time.perf_counter()
    last_poll = 0.0
    for i in range(n_submit):
        target = start + i * interval
        while True:
            now = time.perf_counter()
            if now >= target:
                break
            if now - last_poll >= 0.02:  # completion scans are O(pending)
                poll()
                last_poll = now
            time.sleep(0.002)
        job = _overload_job(f"ovl-{i}", priority=rng.choice([20, 50, 80]))
        ev_id, _ = server.job_register(job)
        pending[ev_id] = time.perf_counter()
        depths.append(server.broker.ready_count())
    submit_elapsed = time.perf_counter() - start
    deadline = time.perf_counter() + 300.0
    while pending and time.perf_counter() < deadline:
        poll()
        time.sleep(0.02)
    if pending:
        raise TimeoutError(f"{len(pending)} overload evals never settled")
    end = time.perf_counter()

    shed_trigs = (consts.EVAL_TRIGGER_SHED, consts.EVAL_TRIGGER_EXPIRED)
    accepted = [lat for lat, trig in latencies.values()
                if trig not in shed_trigs]
    n_shed = n_submit - len(accepted)
    # Depth trend over the submission window, quarter-mean smoothed:
    # batch drains dip the raw samples a few evals between polls, but
    # an unbounded queue's quarter means climb monotonically while a
    # capped one's plateau at the cap.
    q = max(1, len(depths) // 4)
    quarter_means = [round(sum(depths[i * q:(i + 1) * q]) / q, 1)
                     for i in range(4)]
    return {
        "submitted": n_submit,
        "offered_rate": round(3.0 * rate, 1),
        "achieved_rate": round(n_submit / submit_elapsed, 1),
        "shed_rate": round(n_shed / n_submit, 4),
        "goodput": round(len(accepted) / (end - start), 1),
        "accepted_p99_ms": round(
            float(np.percentile(accepted, 99)) * 1000, 1),
        "depth_max": max(depths),
        "depth_final": depths[-1],
        "depth_quarter_means": quarter_means,
        "depth_monotonic_growth": bool(
            all(b > a for a, b in zip(quarter_means, quarter_means[1:]))),
        # Storm-window deltas, not server lifetime.
        "broker_shed": server.broker.stats()["shed"] - broker0["shed"],
        "broker_expired": (server.broker.stats()["expired"]
                           - broker0["expired"]),
    }


def run_overload(seed, n_nodes=400, probe_jobs=24, window_s=6.0, cap=16):
    """Overload A/B for the live pipeline (the soak's quantitative
    twin, tests/test_overload_soak.py): measure capacity with a
    capacity-sized storm, then submit at 3x that rate — once with
    protection ON (bounded service queue at `cap`, deadlines,
    admission), once with everything OFF. Protection ON should hold
    goodput near capacity with a bounded accepted-eval p99 and a
    capped queue; OFF shows the queue growing monotonically with the
    p99 inflating alongside it."""
    import random as _random

    from nomad_tpu import mock

    def seed_cluster(server):
        for _ in range(n_nodes):
            node = mock.node()
            node.compute_class()
            server.log.apply("node_register", {"node": node})

    def warm(server):
        # Two waves so both the full-upload and base-delta program
        # variants compile outside the measured windows (the
        # _live_pipeline warm-up discipline). Deregs mint NO evals —
        # a burst of dereg evals against the ON arm's capped queue
        # would shed, polluting the storm's counters.
        for wave in ("wA", "wB"):
            jobs = [_overload_job(f"{wave}-{j}") for j in range(probe_jobs)]
            evs = [server.job_register(job)[0] for job in jobs]
            _overload_wait(server, evs)
            for job in jobs:
                server.job_deregister(job.id, create_eval=False)

    def capacity(server):
        # Sustained-rate probe sized like the storm, not one batch: a
        # handful of jobs drains in a single device dispatch and reads
        # 3-5x the steady-state rate, which would turn "3x capacity"
        # into a meaningless instant burst.
        n = max(probe_jobs, 60)
        jobs = [_overload_job(f"capy-{j}") for j in range(n)]
        t0 = time.perf_counter()
        evs = [server.job_register(job)[0] for job in jobs]
        _overload_wait(server, evs)
        return n / (time.perf_counter() - t0)

    # Capacity is measured on the UNbounded arm (a capped queue would
    # shed the probe itself) and reused for the ON arm — both arms see
    # the identical offered load.
    off_server = _overload_server(protection=False, cap=0)
    try:
        seed_cluster(off_server)
        warm(off_server)
        rate = capacity(off_server)
        # A SUSTAINED overload window, not an instant burst: 3x the
        # measured rate held for ~window_s seconds (bounded so a fast
        # box cannot explode the job count).
        storm_jobs = int(min(900, max(120, 3.0 * rate * window_s)))
        off = _overload_storm(off_server, rate,
                              storm_jobs, _random.Random(seed))
    finally:
        off_server.shutdown()

    on_server = _overload_server(protection=True, cap=cap)
    try:
        seed_cluster(on_server)
        warm(on_server)
        on = _overload_storm(on_server, rate,
                             storm_jobs, _random.Random(seed))
        on["breaker_state"] = on_server.stats()["admission"][
            "breaker"]["state"]
    finally:
        on_server.shutdown()

    return {
        "metric": (
            f"[overload seed={seed}] {n_nodes} nodes, capacity "
            f"{rate:.1f} evals/s, storm at 3x: protection-ON "
            f"goodput={on['goodput']:.1f} shed_rate={on['shed_rate']:.2f} "
            f"accepted-p99={on['accepted_p99_ms']:.0f}ms "
            f"depth<= {on['depth_max']}; OFF "
            f"goodput={off['goodput']:.1f} shed_rate={off['shed_rate']:.2f} "
            f"p99={off['accepted_p99_ms']:.0f}ms depth-> "
            f"{off['depth_max']} "
            f"(monotonic={off['depth_monotonic_growth']})"
        ),
        "overload_seed": seed,
        "capacity_evals_per_s": round(rate, 1),
        "service_queue_cap": cap,
        "protection_on": on,
        "protection_off": off,
    }


def _read_storm_server(mux_enabled, scoped, n_watchers):
    """Live server + HTTP front end for one read-storm arm. Mux ON +
    scoped is the shipping read plane (parked continuations, zero
    handler threads, per-scope wakes); OFF + global is the pre-PR-19
    baseline kept reachable for the A/B — a thread per blocking query,
    woken by ANY commit."""
    from nomad_tpu.api import HTTPServer
    from nomad_tpu.server import Server, ServerConfig

    cfg = ServerConfig(
        num_schedulers=1,
        eval_nack_timeout=60.0,
        read_mux_enabled=mux_enabled,
        read_scoped_index=scoped,
        read_mux_max_parked=max(4096, 4 * n_watchers))
    server = Server(cfg)
    server.start()
    http = HTTPServer(server)
    http.start()
    host, port = http.addr.split("//")[1].split(":")
    return server, http, host, int(port)


def _read_storm_park(host, port, path):
    import socket as _socket

    s = _socket.create_connection((host, port), timeout=90)
    s.sendall(f"GET {path} HTTP/1.1\r\nHost: bench\r\n\r\n".encode())
    return s


def _read_storm_recv(sock, timeout=15.0):
    """Read one HTTP response off a parked socket; returns
    (status_ok, payload_bytes). Reads headers + Content-Length bytes
    rather than draining to EOF — the mux serve thunk closes the
    connection but the thread-park baseline answers over keep-alive
    and would block an EOF reader until the socket times out."""
    sock.settimeout(timeout)
    buf = b""
    while b"\r\n\r\n" not in buf:
        chunk = sock.recv(65536)
        if not chunk:
            break
        buf += chunk
    head, _, payload = buf.partition(b"\r\n\r\n")
    clen = 0
    for line in head.split(b"\r\n")[1:]:
        key, _, val = line.partition(b":")
        if key.strip().lower() == b"content-length":
            clen = int(val.strip())
    while len(payload) < clen:
        chunk = sock.recv(65536)
        if not chunk:
            break
        payload += chunk
    try:
        status = int(head.split(b"\r\n", 1)[0].split()[1])
    except (IndexError, ValueError):
        status = 0
    return status == 200, payload


def _read_storm_mode_ab(addr, n=150):
    """Stale-vs-consistent read latency A/B against the same (leader)
    server: `?stale` serves straight from the local snapshot, while
    `?consistent` first waits for the FSM to reach the last known
    commit index (a no-op barrier on the leader, a real wait on a
    follower)."""
    import urllib.request

    out = {}
    for mode in ("stale", "consistent"):
        lat = []
        for _ in range(n):
            t0 = time.perf_counter()
            with urllib.request.urlopen(
                    f"{addr}/v1/jobs?{mode}", timeout=10.0) as resp:
                resp.read()
            lat.append(time.perf_counter() - t0)
        out[mode] = {
            "p50_ms": round(float(np.percentile(lat, 50)) * 1000, 2),
            "p99_ms": round(float(np.percentile(lat, 99)) * 1000, 2),
        }
    return out


def _read_storm_plain_reads(addr, n=200, stop=None, lat=None):
    """`n` non-blocking /v1/jobs reads (or until `stop` is set when
    given); appends latencies (s) to `lat` and returns it."""
    import urllib.request

    lat = [] if lat is None else lat
    for _ in range(n):
        if stop is not None and stop.is_set():
            break
        t0 = time.perf_counter()
        with urllib.request.urlopen(f"{addr}/v1/jobs", timeout=10.0) as r:
            r.read()
        lat.append(time.perf_counter() - t0)
        if stop is not None:
            time.sleep(0.002)
    return lat


def _read_storm_arm(mux_enabled, scoped, n_watchers, wait_s, rounds):
    """One read-storm arm: park `n_watchers` blocking queries on
    disjoint alloc_job scopes, measure the parked-thread footprint,
    then run `rounds` waves of scope writes (one writer client per
    10 watchers) with a concurrent plain reader, and time each
    wake-to-serve. The untouched sockets are then polled for spurious
    responses — on the scoped arm that must be none; on the
    global-index baseline EVERY commit satisfies every watcher, so
    the ratio reads ~1.0. The stale/consistent A/B runs on the mux
    arm under the still-parked load."""
    import select
    import threading as _threading

    from nomad_tpu import mock

    server, http, host, port = _read_storm_server(
        mux_enabled, scoped, n_watchers)
    socks = []
    out = {"mux_enabled": mux_enabled, "scoped_index": scoped,
           "watchers": n_watchers}
    try:
        state = server.fsm.state
        # Seed one commit so the first scope write lands at index >= 2:
        # a write AT the watchers' ?index=1 is correctly not-newer and
        # must not wake anyone — keep it out of the measurement.
        server.log.apply("node_register", {"node": mock.node()})
        idle = _read_storm_plain_reads(http.addr)
        out["read_idle_p99_ms"] = round(
            float(np.percentile(idle, 99)) * 1000, 2)
        thread_floor = _threading.active_count()
        for i in range(n_watchers):
            socks.append(_read_storm_park(
                host, port,
                f"/v1/job/rs-{i}/allocations?index=1&wait={wait_s}"))

        deadline = time.perf_counter() + 30.0
        if mux_enabled:
            while (server.read_mux.stats()["parked"] < n_watchers
                   and time.perf_counter() < deadline):
                time.sleep(0.02)
            if server.read_mux.stats()["parked"] < n_watchers:
                raise TimeoutError("read-storm watchers never parked")
            # Handler threads unwind once the socket is detached; give
            # the last few a moment before reading the footprint.
            settle = time.perf_counter() + 10.0
            while (_threading.active_count() > thread_floor + 8
                   and time.perf_counter() < settle):
                time.sleep(0.05)
        else:
            # Thread-park baseline: every watcher HOLDS its handler
            # thread, so the footprint itself is the settle signal.
            while (_threading.active_count() - thread_floor < n_watchers
                   and time.perf_counter() < deadline):
                time.sleep(0.02)
        out["parked_thread_delta"] = (_threading.active_count()
                                      - thread_floor)

        n_writers = max(1, n_watchers // 10)
        wlock = _threading.Lock()
        results = {}

        def wake_client(slot):
            a = mock.alloc()
            a.job_id = f"rs-{slot}"
            with wlock:
                t0 = time.perf_counter()
                state.upsert_allocs(state.latest_index() + 1, [a])
            try:
                ok, _payload = _read_storm_recv(socks[slot])
            except OSError:
                ok = False
            results[slot] = (time.perf_counter() - t0, ok)

        # Plain reads keep flowing while the write waves run — the
        # read-under-churn column the idle figure baselines.
        churn_stop = _threading.Event()
        churn_lat = []
        reader = _threading.Thread(
            target=_read_storm_plain_reads, name="rs-reader",
            args=(http.addr, 100000, churn_stop, churn_lat))
        reader.start()
        woken = 0
        try:
            for r in range(rounds):
                clients = [
                    _threading.Thread(target=wake_client,
                                      args=(r * n_writers + j,),
                                      name=f"rs-client-{r}-{j}")
                    for j in range(n_writers)]
                for t in clients:
                    t.start()
                for t in clients:
                    t.join(timeout=30.0)
                woken += n_writers
        finally:
            churn_stop.set()
            reader.join(timeout=15.0)
        out["read_churn_p99_ms"] = round(
            float(np.percentile(churn_lat, 99)) * 1000, 2) if churn_lat \
            else None

        lat = [s for s, ok in results.values() if ok]
        out["write_clients"] = n_writers
        out["wakes"] = woken
        out["wake_failures"] = woken - len(lat)
        out["wake_to_serve_p50_ms"] = round(
            float(np.percentile(lat, 50)) * 1000, 2) if lat else None
        out["wake_to_serve_p99_ms"] = round(
            float(np.percentile(lat, 99)) * 1000, 2) if lat else None

        # Spurious check, client side: the remaining sockets watch
        # scopes nothing wrote — any readable one got a response whose
        # body cannot have changed (a spurious wake). Scoped arm: must
        # be none. Global-index arm: every commit satisfied every
        # watcher, so expect ~all of them. Settle first, THEN count:
        # select returns on the FIRST readable fd, and the thread-park
        # baseline answers at its 1s re-check boundary — an immediate
        # select would tally only the earliest arrivals.
        time.sleep(1.5)
        remaining = socks[woken:]
        readable, _, _ = select.select(remaining, [], [], 0.2)
        out["spurious_responses"] = len(readable)
        out["spurious_ratio"] = round(
            len(readable) / max(1, len(remaining)), 4)
        if mux_enabled:
            out["mode_ab"] = _read_storm_mode_ab(http.addr)
            out["mux"] = server.read_mux.stats()
        return out
    finally:
        for s in socks:
            try:
                s.close()
            except OSError:
                pass
        http.stop()
        server.shutdown()


def run_read_storm(n_watchers=200, check=False):
    """Read-plane storm A/B (the quantitative twin of
    tests/test_readplane.py's storm): park N blocking queries on
    disjoint scopes with one write client per 10 watchers, mux ON vs
    the thread-park baseline. ON must hold an O(1) parked-thread
    footprint and zero spurious wakes; OFF shows the thread-per-
    watcher scaling the mux removes. With --check, refuses numbers
    when the spurious ratio exceeds 1% or the mux footprint scales
    with the watcher count."""
    on = _read_storm_arm(True, True, n_watchers, wait_s=60, rounds=5)
    base_watchers = n_watchers
    off = _read_storm_arm(False, False, base_watchers, wait_s=30,
                          rounds=1)

    churn_x = (round(on["read_churn_p99_ms"] / on["read_idle_p99_ms"], 2)
               if on.get("read_churn_p99_ms") and on.get("read_idle_p99_ms")
               else None)
    out = {
        "metric": (
            f"[read-storm n={n_watchers}] mux+scoped ON: parked-thread "
            f"delta {on['parked_thread_delta']} (O(1)), wake p99 "
            f"{on['wake_to_serve_p99_ms']}ms, spurious "
            f"{on['spurious_ratio']:.4f}, churn/idle read p99 x"
            f"{churn_x}; thread-park global-index OFF: delta "
            f"{off['parked_thread_delta']} (~1/watcher), spurious "
            f"{off['spurious_ratio']:.4f}"
        ),
        "watchers": n_watchers,
        "read_churn_over_idle_p99": churn_x,
        "mux_on": on,
        "threadpark_off": off,
    }
    if check:
        if on["spurious_ratio"] > 0.01 or on["mux"]["spurious"] > 0:
            print(f"bench: REFUSING read-storm numbers: spurious wake "
                  f"ratio {on['spurious_ratio']} (client) / "
                  f"{on['mux']['spurious']} (mux) exceeds the 1% "
                  f"budget — scope routing is waking watchers whose "
                  f"scope did not move", file=sys.stderr)
            sys.exit(2)
        if on["parked_thread_delta"] > 8:
            print(f"bench: REFUSING read-storm numbers: mux arm held "
                  f"{on['parked_thread_delta']} extra threads with "
                  f"{n_watchers} parked watchers — the parked-watcher "
                  f"footprint must be O(1), not O(watchers)",
                  file=sys.stderr)
            sys.exit(2)
        if off["parked_thread_delta"] < base_watchers // 2:
            print(f"bench: REFUSING read-storm numbers: the thread-"
                  f"park baseline held only "
                  f"{off['parked_thread_delta']} threads for "
                  f"{base_watchers} watchers — the A/B's OFF arm is "
                  f"not measuring the pre-mux behaviour",
                  file=sys.stderr)
            sys.exit(2)
        if on["wake_failures"] or off["wake_failures"]:
            print(f"bench: REFUSING read-storm numbers: "
                  f"{on['wake_failures']} (ON) / "
                  f"{off['wake_failures']} (OFF) written scopes never "
                  f"served their watcher", file=sys.stderr)
            sys.exit(2)
    return out


def _shed_gate(out, n):
    """--check: a NON-overload config that shed or expired evals was
    measured while the server protected itself — its dense-path
    numbers describe a degraded run, not the pipeline. Refuse."""
    shed = out.get("columns", {}).get("shed", {}).get("median", 0)
    if shed > 0:
        print(f"bench: REFUSING to report config {n}: shed_rate > 0 "
              f"(median {shed} evals shed/expired) in a non-overload "
              f"config — raise eval_ready_cap / deadline TTL or fix "
              f"the regression that slowed the drain", file=sys.stderr)
        sys.exit(2)


def _recompile_gate(out, n):
    """--check: steady-state jit recompiles after warmup invalidate
    dense-path numbers — the measured rounds paid trace+compile stalls
    a long-running server would not (a shape-bucket leak, an unhashable
    static arg, a drifting padding ladder). Refuse."""
    rec = out.get("columns", {}).get("jit_recompiles", {}).get("median")
    if rec:
        print(f"bench: REFUSING to report config {n}: steady-state "
              f"jit_recompiles = {rec} after warmup — the dense path "
              f"recompiled mid-measurement (shape bucket leak?); fix "
              f"the bucket ladder or extend warmup", file=sys.stderr)
        sys.exit(2)


def run_resident_ab(reps=DEFAULT_REPS, configs=(None,)):
    """Device-resident state ON/OFF A/B -> BENCH_r10/r14: ON is the
    shipping default (universe matrix + node-axis deltas + prefetch),
    OFF reverts to the ready-subset rebuild-per-snapshot path. Reports
    both arms' full summaries (stage p99 tables included) plus the
    headline deltas per config. Since PR 12 the A/B carries an
    ON >= OFF acceptance flag per config: BENCH_r10 measured the
    inversion (ON 579 < OFF 636 on a static cluster — the delta
    machinery ran under 64-thread contention); on the executive's
    no-park shape the bookkeeping is cheaper than OFF's re-uploads and
    the inversion must stay flipped (--check refuses otherwise)."""
    from nomad_tpu.models import resident

    configs = tuple(HEADLINE_CONFIG if c is None else c for c in configs)
    per_config = {}
    for n in configs:
        resident.configure(enabled=True)
        on = run_config(n, reps=reps)
        try:
            resident.configure(enabled=False)
            off = run_config(n, reps=reps)
        finally:
            resident.configure(enabled=True)
        per_config[n] = {
            "resident_on": on, "resident_off": off,
            "on_ge_off": bool(on["value"] >= off["value"]),
        }
    headline = per_config[configs[0]]
    on, off = headline["resident_on"], headline["resident_off"]
    on_dd = on.get("stage_p99_ms", {}).get("device.dispatch", 0.0)
    off_dd = off.get("stage_p99_ms", {}).get("device.dispatch", 0.0)
    return {
        "metric": (
            f"[config {configs[0]} resident A/B] ON: "
            f"e2e={on['value']:.1f} evals/s (e2e_x {on['e2e_x']:.2f}), "
            f"device.dispatch p99 {on_dd:.1f}ms, "
            f"transfer/batch {on['columns']['transfer_bytes_per_batch']['median']:.0f}B, "
            f"recompiles {on['columns']['jit_recompiles']['median']:.0f}; "
            f"OFF: e2e={off['value']:.1f} (e2e_x {off['e2e_x']:.2f}), "
            f"device.dispatch p99 {off_dd:.1f}ms"
            + "".join(
                f"; config {n}: ON {'>=' if pc['on_ge_off'] else '<'} OFF"
                for n, pc in per_config.items())
        ),
        "resident_on": on,
        "resident_off": off,
        "configs": {str(n): {"on_ge_off": pc["on_ge_off"]}
                    for n, pc in per_config.items()},
        "on_ge_off_every_config": all(
            pc["on_ge_off"] for pc in per_config.values()),
    }


def config_frag_heavy(kernel="greedy"):
    """Fragmentation-heavy A/B workload (the --kernel-ab second arm):
    200 nodes with SKEWED light pre-load (0-3 filler allocs per node —
    heterogeneous headroom) taking 16 LARGE asks per eval (~40% of a
    node on cpu and mem, so 2 fit and a third strands the remainder).
    This is the shape where the greedy tie-break noise scatters
    placements across near-tie nodes and strands headroom; the convex
    kernel's joint solve sees all 16 asks and the load landscape at
    once and packs a deliberate node set."""
    # CHUNKY skewed pre-load (~half an ask per filler): node headrooms
    # land at 1.2x-2.6x the ask, so which headroom CLASS a kernel
    # fills decides how much capacity strands — the axis BestFit (and
    # its tie-break noise) cannot see.
    store, _ = build_cluster(200, alloc_skew=3, seed=17,
                             filler_cpu=(600, 800),
                             filler_mem=(1200, 1600))
    job = service_job(networks=False)
    job.task_groups[0].count = 16
    tg = job.task_groups[0].tasks[0]
    tg.resources.cpu = 1500
    tg.resources.memory_mb = 3000
    # batch=8: one 8-eval pre-resolved batch claims ~25% of the
    # cluster — contended enough that packing choices matter, not so
    # full that every node strands and the kernels converge.
    e2e_rate, e2e_p99, ds = bench_tpu_e2e(
        store, job, 16, batch=8, rounds=3, kernel=kernel)
    return {
        "name": "frag-heavy: 200 nodes skewed pre-load, 16x 40%-asks",
        "e2e": e2e_rate, "e2e_p99_ms": e2e_p99 * 1000,
        "occupancy": ds["occupancy"],
        "jit_recompiles": ds["jit_recompiles"],
        **_quality_cols(ds),
    }


def config_4_kernel(kernel="greedy"):
    """Config 4's cluster shape with a pinned kernel (the --kernel-ab
    first arm): the north-star 10k-node scenario, e2e only."""
    store, _ = build_cluster(10_000, datacenters=("dc1", "dc2"),
                             allocs_per_node=5)
    job = service_job(networks=True, distinct_hosts=True)
    job.datacenters = ["dc1", "dc2"]
    job.task_groups[0].count = 8
    e2e_rate, e2e_p99, ds = bench_tpu_e2e(
        store, job, 8, batch=64, rounds=3, kernel=kernel)
    return {
        "name": "10k nodes, 50k allocs, ports + distinct_hosts",
        "e2e": e2e_rate, "e2e_p99_ms": e2e_p99 * 1000,
        "occupancy": ds["occupancy"],
        "jit_recompiles": ds["jit_recompiles"],
        **_quality_cols(ds),
    }


KERNEL_AB_ARMS = {"config4": config_4_kernel, "frag_heavy": config_frag_heavy}
KERNEL_AB_KERNELS = ("greedy", "convex")


def run_kernel_ab(reps=3, check=False):
    """Throughput + quality A/B of the registered kernels (greedy vs
    convex) on config 4's shape and the fragmentation-heavy arm ->
    BENCH_r11.json. Interleaved reps (greedy then convex back to back
    per rep) so host drift hits both; medians reported. With --check,
    every kernel must first pass the oracle differential rig
    (kernels/differential.py) — red rigs refuse to report — and
    steady-state jit recompiles must stay 0."""
    from nomad_tpu.trace import get_recorder

    if check:
        from nomad_tpu.kernels.differential import run_differential

        for kernel in KERNEL_AB_KERNELS:
            report = run_differential(kernel)
            if not report["green"]:
                for v in report["violations"]:
                    print(f"bench: {v}", file=sys.stderr)
                print(f"bench: REFUSING to report kernel numbers: "
                      f"kernel {kernel!r} failed the oracle "
                      f"differential rig ({len(report['violations'])} "
                      f"violations across {report['cases']} cases)",
                      file=sys.stderr)
                sys.exit(2)
            print(f"bench: kernel {kernel!r} oracle differential green "
                  f"({report['cases']} cases)", file=sys.stderr)

    arms = {}
    for arm_name, builder in KERNEL_AB_ARMS.items():
        runs = {k: [] for k in KERNEL_AB_KERNELS}
        for _ in range(reps):
            for kernel in KERNEL_AB_KERNELS:
                get_recorder().reset()
                runs[kernel].append(builder(kernel=kernel))
        per_kernel = {}
        for kernel, rr in runs.items():
            cols = {}
            for key in rr[0]:
                if key == "name":
                    continue
                med, iqr = _median_iqr([float(r[key]) for r in rr])
                cols[key] = {"median": round(med, 4),
                             "iqr": round(iqr, 4)}
            per_kernel[kernel] = cols
        g, c = per_kernel["greedy"], per_kernel["convex"]
        speed_ratio = (c["e2e"]["median"] / g["e2e"]["median"]
                       if g["e2e"]["median"] else 0.0)
        arms[arm_name] = {
            "name": runs["greedy"][0]["name"],
            "kernels": per_kernel,
            "convex_vs_greedy": {
                "speed_ratio": round(speed_ratio, 3),
                "fragmentation_delta": round(
                    c["fragmentation"]["median"]
                    - g["fragmentation"]["median"], 4),
                "binpack_delta": round(
                    c["binpack_score"]["median"]
                    - g["binpack_score"]["median"], 4),
                # The acceptance bar: quality improves (lower frag or
                # higher binpack) at >= 0.5x greedy's throughput.
                "quality_improved": bool(
                    c["fragmentation"]["median"]
                    < g["fragmentation"]["median"] - 1e-9
                    or c["binpack_score"]["median"]
                    > g["binpack_score"]["median"] + 1e-9),
                "speed_ok": bool(speed_ratio >= 0.5),
            },
        }
        if check:
            for kernel in KERNEL_AB_KERNELS:
                rec = per_kernel[kernel]["jit_recompiles"]["median"]
                if rec:
                    print(f"bench: REFUSING kernel-ab numbers: kernel "
                          f"{kernel!r} recompiled mid-measurement on "
                          f"arm {arm_name!r} (jit_recompiles={rec})",
                          file=sys.stderr)
                    sys.exit(2)

    accepted = any(a["convex_vs_greedy"]["quality_improved"]
                   and a["convex_vs_greedy"]["speed_ok"]
                   for a in arms.values())
    summary = "; ".join(
        f"{name}: convex {a['convex_vs_greedy']['speed_ratio']:.2f}x "
        f"speed, frag {a['kernels']['convex']['fragmentation']['median']:.3f}"
        f" vs {a['kernels']['greedy']['fragmentation']['median']:.3f}, "
        f"binpack {a['kernels']['convex']['binpack_score']['median']:.3f}"
        f" vs {a['kernels']['greedy']['binpack_score']['median']:.3f}"
        for name, a in arms.items())
    return {
        "metric": f"[kernel-ab greedy vs convex, median-of-{reps}] "
                  + summary,
        "arms": arms,
        "acceptance_quality_at_half_speed": accepted,
    }


def _preempt_storm(preemption_on, seed, n_nodes=16, storm_x=3):
    """One priority-storm arm: a full cluster of prio-20 allocs, then
    high-priority (60) demand at `storm_x` times what the cluster can
    hold even WITH preemption. ON places to capacity by evicting
    lowest-priority allocs through the dense preempt pass; OFF sheds
    per the PR 5 policy (blocked evals, zero evictions)."""
    from nomad_tpu import mock
    from nomad_tpu.migrate import configure as migrate_configure
    from nomad_tpu.migrate import get_governor
    from nomad_tpu.ops.binpack import jit_cache_size
    from nomad_tpu.scheduler.testing import Harness
    from nomad_tpu.structs import consts
    from nomad_tpu.structs.eval import new_eval

    migrate_configure(preemption_enabled=preemption_on,
                      preempt_priority_threshold=50,
                      pressure_probe=lambda: "red")
    get_governor().reset_stats()
    h = Harness(seed=seed)
    for _ in range(n_nodes):
        node = mock.node()
        node.resources.cpu = 1000
        node.resources.memory_mb = 4096
        node.compute_class()
        h.state.upsert_node(h.next_index(), node)
    low = mock.job()
    low.id = "low-prio"
    low.priority = 20
    low.task_groups[0].count = n_nodes
    t = low.task_groups[0].tasks[0]
    t.resources.cpu = 600
    t.resources.memory_mb = 256
    t.resources.networks = []
    h.state.upsert_job(h.next_index(), low)
    h.process("service-tpu", new_eval(h.state.job_by_id(low.id),
                                      consts.EVAL_TRIGGER_JOB_REGISTER))

    # capacity with preemption = 1 high alloc per node; storm at 3x
    per_job = 4
    n_high = (n_nodes * storm_x) // per_job
    requested = n_high * per_job
    t0 = time.perf_counter()
    for j in range(n_high):
        job = mock.job()
        job.id = f"high-{j}"
        job.priority = 60
        job.task_groups[0].count = per_job
        t = job.task_groups[0].tasks[0]
        t.resources.cpu = 500
        t.resources.memory_mb = 128
        t.resources.networks = []
        h.state.upsert_job(h.next_index(), job)
        h.process("service-tpu", new_eval(
            h.state.job_by_id(job.id), consts.EVAL_TRIGGER_JOB_REGISTER))
    elapsed = time.perf_counter() - t0

    placed = sum(
        1 for a in h.state.allocs()
        if a.job_id.startswith("high-") and not a.terminal_status())
    evicted = [a for a in h.state.allocs_by_job(low.id)
               if a.desired_status == consts.ALLOC_DESIRED_EVICT]
    # every eviction must have committed through the raft funnel
    # (Harness.submit_plan IS the oracle's funnel): each evicted store
    # record traces to exactly one plan's preemption leg.
    staged_ids = []
    for plan in h.plans:
        for victims in plan.node_preemptions.values():
            staged_ids.extend(v.id for v in victims)
    blocked = sum(1 for e in h.create_evals
                  if e.status == consts.EVAL_STATUS_BLOCKED)
    return {
        "requested": requested,
        "placed": placed,
        "placed_frac": placed / requested if requested else 0.0,
        "evictions": len(evicted),
        "evictions_staged_in_plans": len(staged_ids),
        "evictions_funnel_ok": (
            sorted(staged_ids) == sorted(a.id for a in evicted)),
        "blocked_evals": blocked,
        "evals_per_s": n_high / elapsed if elapsed else 0.0,
        "jit_cache_size": jit_cache_size(),
    }


def run_preempt_ab(reps=3, check=False):
    """Preemption ON/OFF A/B under a 3x priority storm -> the
    BENCH_r12 arm. ON must place the cluster's preemption capacity
    with every eviction committing exactly once through the raft
    funnel; OFF must shed per the PR 5 policy unchanged (blocked
    evals, zero evictions). With --check, refuses to report if ANY
    eviction lacks a raft-funnel terminal (a store evict record with
    no staging plan, or a staged victim that never committed), or if
    the preemption leg recompiled after warmup."""
    from nomad_tpu.migrate import configure as migrate_configure

    arms = {"on": [], "off": []}
    try:
        for rep in range(reps):
            arms["on"].append(_preempt_storm(True, seed=9000 + rep))
            arms["off"].append(_preempt_storm(False, seed=9500 + rep))
    finally:
        migrate_configure(preemption_enabled=False,
                          pressure_probe=lambda: "green")

    if check:
        for rep, r in enumerate(arms["on"]):
            if not r["evictions_funnel_ok"]:
                print(f"bench: REFUSING preempt-ab numbers: rep {rep} "
                      f"has evictions without a raft-funnel terminal "
                      f"(staged {r['evictions_staged_in_plans']} vs "
                      f"committed {r['evictions']})", file=sys.stderr)
                sys.exit(2)
        # warmup = rep 0; later reps must add no compiled programs
        sizes = [r["jit_cache_size"] for r in arms["on"]]
        if len(set(sizes[1:])) > 1:
            print(f"bench: REFUSING preempt-ab numbers: preemption leg "
                  f"recompiled after warmup (jit cache {sizes})",
                  file=sys.stderr)
            sys.exit(2)

    def med(rr, key):
        m, _ = _median_iqr([float(r[key]) for r in rr])
        return m

    on, off = arms["on"], arms["off"]
    out = {
        "metric": (f"[preempt-ab 3x priority storm, median-of-{reps}] "
                   f"ON: placed {med(on, 'placed'):.0f}/"
                   f"{on[0]['requested']} with "
                   f"{med(on, 'evictions'):.0f} evictions "
                   f"(funnel_ok={all(r['evictions_funnel_ok'] for r in on)})"
                   f"; OFF: placed {med(off, 'placed'):.0f}, "
                   f"{med(off, 'evictions'):.0f} evictions, "
                   f"{med(off, 'blocked_evals'):.0f} blocked"),
        "preemption_on": {k: med(on, k) for k in on[0] if k != "metric"},
        "preemption_off": {k: med(off, k) for k in off[0]},
        "acceptance": {
            "on_places_capacity": bool(med(on, "placed") >= 16),
            "on_funnel_exactly_once": all(
                r["evictions_funnel_ok"] for r in on),
            "off_sheds_unchanged": bool(
                med(off, "placed") == 0 and med(off, "evictions") == 0
                and med(off, "blocked_evals") > 0),
        },
    }
    return out


def _defrag_churn_arm(defrag_on, seed, n_nodes=200, churn_steps=12,
                      budget=16, max_moves=16, rounds_per_step=3):
    """One defrag-ab arm: a config-5-shaped churning SERVICE workload
    (mixed 600/300 asks on 1000-cap nodes; each step client-completes
    a random slice of small allocs and the reconciler refills the
    holes through the dense path — the scatter that fragments), with
    the defrag loop ON or OFF between steps. Deterministic: the
    Harness drives the scheduler, the REAL DefragLoop drives the
    waves (governor claims, budget cap, stale gate and all), and the
    arm's stub server processes each wave eval synchronously through
    the dense factory then commits its terminal to the store so the
    loop's watch releases the slots.

    Returns the fragmentation trajectory (cluster_fragmentation — the
    solver's own objective, measured identically in both arms), the
    governor high-water vs budget, the displaced-alloc funnel sweep
    (every moved alloc staged in EXACTLY one plan's eviction leg and
    carrying a desired-stop terminal, with exactly one replacement),
    warm/cold solve cost, and the jit program count after warmup."""
    import random as _random

    import types as _types

    from nomad_tpu.defrag import DefragLoop, cluster_fragmentation
    from nomad_tpu.migrate import configure as migrate_configure
    from nomad_tpu.migrate import DEFAULT_MAX_PARALLEL, get_governor
    from nomad_tpu.ops.binpack import jit_cache_size
    from nomad_tpu.scheduler.testing import (
        Harness,
        churn_stop_small_allocs,
        seed_consolidation_cluster,
    )
    from nomad_tpu.server.config import ServerConfig
    from nomad_tpu.structs import consts
    from nomad_tpu.structs.eval import new_eval as _new_eval

    rng = _random.Random(seed)
    h = Harness(seed=seed)
    # The SHARED fragmentation fixture (scheduler/testing.py) — the
    # defrag differential rig builds the identical workload shape, so
    # the rig and this trajectory never judge different clusters.
    seed_consolidation_cluster(h, n_nodes, factory="service-tpu")

    migrate_configure(migrate_max_parallel=budget)
    harness = h

    class _ArmServer:
        """The Server slice the loop touches; wave evals process
        synchronously through the dense factory and commit their
        terminal to the store (the dev-server applier analog)."""

        def __init__(self):
            self.config = ServerConfig(
                defrag_enabled=defrag_on, defrag_interval=10_000.0,
                defrag_min_gain=0.001, defrag_max_moves_per_wave=max_moves)
            self.fsm = _types.SimpleNamespace(state=harness.state)
            self.admission = _types.SimpleNamespace(level=lambda: "green")

        def is_leader(self):
            return True

        def eval_update(self, evals):
            for ev in evals:
                harness.state.upsert_evals(
                    harness.next_index(), [ev.copy()])
                harness.process("service-tpu", ev)
                done = ev.copy()
                done.status = consts.EVAL_STATUS_COMPLETE
                harness.state.upsert_evals(harness.next_index(), [done])

    loop = DefragLoop(_ArmServer())
    trajectory = []
    jit_warm = None
    try:
        get_governor().reset_stats()
        clock = [0.0]
        trajectory.append(cluster_fragmentation(
            h.state.snapshot(), ["dc1"]))
        for step in range(churn_steps):
            # churn: client-complete a slice of small allocs ...
            stops = churn_stop_small_allocs(h, rng, 0.10)
            # ... and refill the holes (the reconciler's job)
            refill_jobs = sorted({a.job_id for a in stops})
            for jid in refill_jobs:
                job = h.state.job_by_id(jid)
                h.process("service-tpu", _new_eval(
                    job, consts.EVAL_TRIGGER_NODE_UPDATE))
            if defrag_on:
                # each tick: one watch (releases the previous wave —
                # the stub's eval_update processed + terminalized it
                # synchronously) + one round
                for _ in range(rounds_per_step):
                    clock[0] += 20_000.0
                    loop.tick(now=clock[0])
                if step == 1:
                    # warmup = the cold + first-warm programs; any
                    # later growth is a steady-state recompile
                    jit_warm = jit_cache_size()
            trajectory.append(cluster_fragmentation(
                h.state.snapshot(), ["dc1"]))
        # final settle tick: release the last wave's slots
        clock[0] += 20_000.0
        loop.configure(enabled=False)
        loop.tick(now=clock[0])
        st = loop.stats()
        g = get_governor().stats()

        # Funnel sweep over every defrag eviction the arm staged: each
        # moved alloc appears in exactly ONE plan's eviction leg,
        # carries a desired-stop terminal in the store, and has exactly
        # one replacement alloc chained to it.
        staged_count = {}
        for plan in h.plans:
            for updates in plan.node_update.values():
                for victim in updates:
                    if victim.desired_description == "alloc is being migrated":
                        staged_count[victim.id] = (
                            staged_count.get(victim.id, 0) + 1)
        funnel_ok = True
        for alloc_id, count in staged_count.items():
            stored = h.state.alloc_by_id(alloc_id)
            replacements = [
                a for a in h.state.allocs()
                if a.previous_allocation == alloc_id]
            if (count != 1 or stored is None
                    or stored.desired_status != consts.ALLOC_DESIRED_STOP
                    or len(replacements) != 1):
                funnel_ok = False
        # every wave eval reached a terminal in the store
        for ev in h.state.evals():
            if ev.triggered_by == consts.EVAL_TRIGGER_DEFRAG \
                    and not ev.terminal_status():
                funnel_ok = False

        jit_end = jit_cache_size()
        return {
            "defrag": bool(defrag_on),
            "frag_start": round(trajectory[0], 4),
            "frag_final": round(trajectory[-1], 4),
            "frag_mean": round(float(np.mean(trajectory)), 4),
            "trajectory": [round(f, 4) for f in trajectory],
            "rounds": st["rounds"],
            "waves": st["waves"],
            "moves": st["moves_proposed"],
            "moves_completed": st["moves_completed"],
            "no_gain_rounds": st["no_gain_rounds"],
            "stale_discards": st["stale_discards"],
            "migration_budget": budget,
            "migration_high_water": g["high_water"],
            "governor_in_flight_end": g["in_flight"],
            "displaced_funnel_ok": bool(funnel_ok),
            "displaced_evictions": len(staged_count),
            "first_cold_solve_ms": st["first_cold_solve_ms"],
            "min_warm_solve_ms": st["min_warm_solve_ms"],
            "cold_solves": st["cold_solves"],
            "warm_solves": st["warm_solves"],
            "jit_after_warmup": jit_warm if jit_warm is not None else jit_end,
            "jit_end": jit_end,
            "jit_recompiles": (jit_end - jit_warm)
            if (defrag_on and jit_warm is not None) else 0,
        }
    finally:
        migrate_configure(migrate_max_parallel=DEFAULT_MAX_PARALLEL)


def run_defrag_ab(reps=2, check=False):
    """Continuous-defragmentation ON/OFF A/B -> BENCH_r15: identical
    seeded churn in both arms, the ON arm running the real DefragLoop
    between churn steps. Acceptance: the ON arm ends with measurably
    lower fragmentation than OFF, migration high-water <= the budget,
    every displaced alloc carries an exactly-once raft-funnel
    terminal, steady-state recompiles 0, and warm-started steady-state
    solves are measurably cheaper than the cold first solve. With
    --check, refuses to report numbers violating the funnel/recompile/
    budget contracts."""
    arms = {"on": [], "off": []}
    for rep in range(reps):
        arms["on"].append(_defrag_churn_arm(True, seed=15_000 + rep))
        arms["off"].append(_defrag_churn_arm(False, seed=15_000 + rep))

    if check:
        for rep, r in enumerate(arms["on"]):
            if not r["displaced_funnel_ok"]:
                print(f"bench: REFUSING defrag-ab numbers: rep {rep} "
                      "has a displaced alloc without an exactly-once "
                      "raft-funnel terminal", file=sys.stderr)
                sys.exit(2)
            if r["jit_recompiles"] > 0:
                print(f"bench: REFUSING defrag-ab numbers: rep {rep} "
                      f"recompiled after warmup "
                      f"({r['jit_after_warmup']} -> {r['jit_end']})",
                      file=sys.stderr)
                sys.exit(2)
            if r["migration_high_water"] > r["migration_budget"]:
                print(f"bench: REFUSING defrag-ab numbers: rep {rep} "
                      f"exceeded the migration budget "
                      f"(high-water {r['migration_high_water']} > "
                      f"{r['migration_budget']})", file=sys.stderr)
                sys.exit(2)
            if r["governor_in_flight_end"] != 0:
                print(f"bench: REFUSING defrag-ab numbers: rep {rep} "
                      f"leaked {r['governor_in_flight_end']} governor "
                      "slots", file=sys.stderr)
                sys.exit(2)

    def med(rr, key):
        m, _ = _median_iqr([float(r[key]) for r in rr])
        return m

    on, off = arms["on"], arms["off"]
    on_final = med(on, "frag_final")
    off_final = med(off, "frag_final")
    out = {
        "metric": (f"[defrag-ab churning service workload, "
                   f"median-of-{reps}] ON: final frag {on_final:.4f} "
                   f"(mean {med(on, 'frag_mean'):.4f}, "
                   f"{med(on, 'waves'):.0f} waves, "
                   f"{med(on, 'moves'):.0f} moves, high-water "
                   f"{med(on, 'migration_high_water'):.0f}/"
                   f"{on[0]['migration_budget']}); OFF: final frag "
                   f"{off_final:.4f} (mean {med(off, 'frag_mean'):.4f})"
                   f"; warm solve {med(on, 'min_warm_solve_ms'):.1f}ms"
                   f" vs cold {med(on, 'first_cold_solve_ms'):.0f}ms"),
        "defrag_on": {k: (on[0][k] if k == "trajectory"
                          else med(on, k) if isinstance(on[0][k],
                                                        (int, float))
                          else on[0][k])
                      for k in on[0]},
        "defrag_off": {k: (off[0][k] if k == "trajectory"
                           else med(off, k) if isinstance(off[0][k],
                                                          (int, float))
                           else off[0][k])
                       for k in off[0]},
        "acceptance": {
            "on_final_frag_below_off": bool(on_final < off_final),
            "frag_final_on_vs_off": [on_final, off_final],
            "migration_high_water_within_budget": all(
                r["migration_high_water"] <= r["migration_budget"]
                for r in on),
            "displaced_funnel_exactly_once": all(
                r["displaced_funnel_ok"] for r in on),
            "steady_state_recompiles_zero": all(
                r["jit_recompiles"] == 0 for r in on),
            "warm_solve_cheaper_than_cold": all(
                0 < r["min_warm_solve_ms"] < r["first_cold_solve_ms"]
                for r in on if r["warm_solves"] > 0),
        },
    }
    return out


GANG_STEP_MS = 1000.0  # sim-time per churn step (the DL-trace clock)


def _gang_churn_arm(gang_on, seed, n_racks=8, rack_size=4, steps=12,
                    k=6, arrivals_per_step=2, dl_lifetime=3):
    """One gang-ab arm: a DL-trace-shaped workload, Tesserae-style —
    large gangs (k=6 trainers, 1000cpu/1200mem each) ARRIVING OVER
    CHURN on a racked topology cluster (racks of 4 × 3000cpu/3000mem
    nodes, so an empty rack holds 8 members and a churned one may not
    hold 6), with small filler services fragmenting racks between
    arrivals. ON places each DL job as a slice gang (all-K on one
    rack); OFF places the identical asks as plain independent groups.

    Deterministic (Harness + dense factory, sim-time clock): each step
    churns a slice of filler (stop + refill — the scatter that
    fragments), lands ``arrivals_per_step`` new DL jobs, COMPLETES DL
    jobs placed ``dl_lifetime`` steps ago (training runs finish — the
    steady-state recycle that keeps gangs arriving onto partially-free
    slices instead of a saturated wall), and re-evaluates every
    not-fully-placed DL job (the blocked-eval re-run analog).
    ``gang_wait`` for a job = steps from arrival until ALL k members
    are live, in GANG_STEP_MS units — the queueing axis Tesserae says
    gang packing dominates.

    Returns gang_wait_p99_ms / slice_frag trajectory / contiguity /
    the partial-commit sweep (ON: every DL job's live member count is
    0 or exactly k at EVERY step — one partial observation anywhere
    poisons the arm) and the jit program count after warmup."""
    import random as _random

    from nomad_tpu import mock
    from nomad_tpu.gang import reset_gang_stats
    from nomad_tpu.kernels.quality import slice_frag_from_store
    from nomad_tpu.ops.binpack import jit_cache_size
    from nomad_tpu.scheduler.testing import Harness, seed_harness_cluster
    from nomad_tpu.structs import Gang, consts
    from nomad_tpu.structs.eval import new_eval as _new_eval

    rng = _random.Random(seed)
    reset_gang_stats()

    nodes = []
    for i in range(n_racks * rack_size):
        node = mock.node()
        node.resources.cpu = 3000
        node.resources.memory_mb = 3000
        node.meta["rack"] = f"r{i // rack_size}"
        node.meta["ici"] = f"r{i // rack_size}-i{(i % rack_size) // 2}"
        node.compute_class()
        nodes.append(node)
    h = Harness(seed=seed)
    seed_harness_cluster(h, nodes=nodes)
    node_rack = {n.id: n.meta["rack"] for n in nodes}

    def make_filler(idx):
        job = mock.job()
        job.id = f"filler-{idx}"
        tg = job.task_groups[0]
        tg.count = 2
        t = tg.tasks[0]
        t.resources.cpu = 600
        t.resources.memory_mb = 500
        t.resources.networks = []
        return job

    def make_dl(idx):
        job = mock.job()
        job.id = f"dl-{idx}"
        tg = job.task_groups[0]
        tg.count = k
        if gang_on:
            tg.gang = Gang(slice="rack")
        t = tg.tasks[0]
        t.resources.cpu = 1000
        t.resources.memory_mb = 1200
        t.resources.networks = []
        return job

    def register_and_eval(job):
        h.state.upsert_job(h.next_index(), job.copy())
        h.process("service-tpu", _new_eval(
            h.state.job_by_id(job.id), consts.EVAL_TRIGGER_JOB_REGISTER))

    def live_count(jid):
        return len([a for a in h.state.allocs_by_job(jid)
                    if not a.terminal_status()])

    fillers = []
    for i in range(2 * n_racks):
        job = make_filler(i)
        register_and_eval(job)
        fillers.append(job)

    pending = []  # (job, arrived_step)
    placed = {}  # job id -> (job, arrived_step, placed_step)
    placed_racks = {}  # job id -> rack set AT PLACEMENT TIME
    partial_events = 0
    frag_trajectory = []
    jit_warm = None
    arrived_total = 0
    frag_ref = make_dl(-1)  # the slice_frag reference ask/k

    for step in range(steps):
        # departures: DL jobs placed dl_lifetime steps ago complete —
        # the training run finished, the slice frees
        for jid, (dl_job, _arr, pl) in list(placed.items()):
            if pl is not None and step - pl >= dl_lifetime:
                for a in h.state.allocs_by_job(jid):
                    if not a.terminal_status():
                        done = a.copy()
                        done.desired_status = consts.ALLOC_DESIRED_STOP
                        done.client_status = consts.ALLOC_CLIENT_COMPLETE
                        h.state.upsert_allocs(h.next_index(), [done])
                placed[jid] = (dl_job, _arr, pl)

        # churn: client-complete a slice of filler allocs and refill
        # the holes — the scatter that fragments racks
        for job in fillers:
            for a in h.state.allocs_by_job(job.id):
                if not a.terminal_status() and rng.random() < 0.15:
                    stopped = a.copy()
                    stopped.desired_status = consts.ALLOC_DESIRED_STOP
                    stopped.client_status = consts.ALLOC_CLIENT_COMPLETE
                    h.state.upsert_allocs(h.next_index(), [stopped])
        for job in fillers:
            if live_count(job.id) < job.task_groups[0].count:
                h.process("service-tpu", _new_eval(
                    h.state.job_by_id(job.id),
                    consts.EVAL_TRIGGER_NODE_UPDATE))

        # arrivals: new DL jobs this step
        for _ in range(arrivals_per_step):
            job = make_dl(arrived_total)
            arrived_total += 1
            register_and_eval(job)
            pending.append((job, step))

        # blocked-gang re-runs: every not-fully-placed DL job retries
        still = []
        for dl_job, arrived in pending:
            if live_count(dl_job.id) < k and arrived != step:
                h.process("service-tpu", _new_eval(
                    h.state.job_by_id(dl_job.id),
                    consts.EVAL_TRIGGER_NODE_UPDATE))
            n_live = live_count(dl_job.id)
            if gang_on and n_live not in (0, k):
                partial_events += 1
            if n_live >= k:
                placed[dl_job.id] = (dl_job, arrived, step)
                placed_racks[dl_job.id] = {
                    node_rack[a.node_id]
                    for a in h.state.allocs_by_job(dl_job.id)
                    if not a.terminal_status()}
            else:
                still.append((dl_job, arrived))
        pending = still

        if step == 1:
            jit_warm = jit_cache_size()
        frag_trajectory.append(slice_frag_from_store(
            h.state.snapshot(), frag_ref, frag_ref.task_groups[0]))

    # contiguity: fraction of fully-placed DL jobs whose members
    # shared ONE rack at placement time (the ON arm's whole point;
    # OFF reports what scattering costs)
    contiguous = sum(1 for racks in placed_racks.values()
                     if len(racks) == 1)
    waits = [(pl - arr) * GANG_STEP_MS
             for _j, arr, pl in placed.values()]
    # still-unplaced jobs waited the whole remaining trace (censored
    # at the horizon — dropping them would reward never placing)
    waits += [(steps - arr) * GANG_STEP_MS for _j, arr in pending]
    jit_end = jit_cache_size()
    return {
        "gang": bool(gang_on),
        "jobs": arrived_total,
        "jobs_fully_placed": len(placed),
        "jobs_unplaced_at_horizon": len(pending),
        "members_live": sum(live_count(f"dl-{i}")
                            for i in range(arrived_total)),
        "partial_commit_events": partial_events,
        "placed_contiguous_frac": round(contiguous / len(placed), 4)
        if placed else 0.0,
        "gang_wait_p99_ms": round(float(np.percentile(waits, 99)), 1)
        if waits else 0.0,
        "gang_wait_mean_ms": round(float(np.mean(waits)), 1)
        if waits else 0.0,
        "slice_frag_final": round(frag_trajectory[-1], 4),
        "slice_frag_mean": round(float(np.mean(frag_trajectory)), 4),
        "slice_frag_trajectory": [round(f, 4) for f in frag_trajectory],
        "jit_after_warmup": jit_warm if jit_warm is not None else jit_end,
        "jit_end": jit_end,
        "jit_recompiles": (jit_end - jit_warm)
        if jit_warm is not None else 0,
    }


def run_gang_ab(reps=2, check=False):
    """Gang ON/OFF A/B -> BENCH_r16: the identical DL-trace-shaped
    seeded churn in both arms, ON placing slice gangs, OFF the same
    asks as independent groups. Acceptance: ON places every fully-
    placed gang on ONE contiguous rack with ZERO partial-commit
    observations and steady-state recompiles 0; the scoreboard gets
    the gang_wait_p99_ms / slice_frag columns both ways. With --check,
    refuses numbers on any partially-committed gang, a non-contiguous
    placed gang, or a recompile after warmup."""
    arms = {"on": [], "off": []}
    for rep in range(reps):
        arms["on"].append(_gang_churn_arm(True, seed=16_000 + rep))
        arms["off"].append(_gang_churn_arm(False, seed=16_000 + rep))

    if check:
        for rep, r in enumerate(arms["on"]):
            if r["partial_commit_events"]:
                print(f"bench: REFUSING gang-ab numbers: rep {rep} "
                      f"observed {r['partial_commit_events']} "
                      "partially-committed gang state(s) — the one "
                      "thing the subsystem exists to prevent",
                      file=sys.stderr)
                sys.exit(2)
            if r["jobs_fully_placed"] and \
                    r["placed_contiguous_frac"] < 1.0:
                print(f"bench: REFUSING gang-ab numbers: rep {rep} "
                      f"placed a slice gang across racks "
                      f"(contiguous {r['placed_contiguous_frac']})",
                      file=sys.stderr)
                sys.exit(2)
            if r["jit_recompiles"] > 0:
                print(f"bench: REFUSING gang-ab numbers: rep {rep} "
                      f"recompiled after warmup "
                      f"({r['jit_after_warmup']} -> {r['jit_end']})",
                      file=sys.stderr)
                sys.exit(2)

    def med(rr, key):
        m, _ = _median_iqr([float(r[key]) for r in rr])
        return m

    on, off = arms["on"], arms["off"]
    out = {
        "metric": (f"[gang-ab DL-trace churn, median-of-{reps}] "
                   f"ON: {med(on, 'jobs_fully_placed'):.0f}/"
                   f"{on[0]['jobs']} gangs on contiguous slices "
                   f"(contiguous {med(on, 'placed_contiguous_frac'):.2f},"
                   f" wait p99 {med(on, 'gang_wait_p99_ms'):.0f}ms, "
                   f"slice_frag {med(on, 'slice_frag_final'):.4f}, "
                   f"partials {med(on, 'partial_commit_events'):.0f}); "
                   f"OFF: {med(off, 'jobs_fully_placed'):.0f} placed "
                   f"(contiguous {med(off, 'placed_contiguous_frac'):.2f}"
                   f", wait p99 {med(off, 'gang_wait_p99_ms'):.0f}ms, "
                   f"slice_frag {med(off, 'slice_frag_final'):.4f})"),
        "gang_on": {k: (on[0][k] if k == "slice_frag_trajectory"
                        else med(on, k) if isinstance(on[0][k],
                                                      (int, float))
                        else on[0][k])
                    for k in on[0]},
        "gang_off": {k: (off[0][k] if k == "slice_frag_trajectory"
                         else med(off, k) if isinstance(off[0][k],
                                                        (int, float))
                         else off[0][k])
                     for k in off[0]},
        "acceptance": {
            "zero_partial_commits": all(
                r["partial_commit_events"] == 0 for r in on),
            "all_placed_gangs_contiguous": all(
                r["placed_contiguous_frac"] == 1.0
                for r in on if r["jobs_fully_placed"]),
            "steady_state_recompiles_zero": all(
                r["jit_recompiles"] == 0 for r in on),
            "gangs_placed_on": [r["jobs_fully_placed"] for r in on],
        },
    }
    return out


# ------------------------------------------------------------ scale arm

SCALE_SIZES = (10_000, 100_000)
SCALE_ALLOCS_PER_NODE = 5


def _scale_fleet(n_nodes, allocs_per_node=SCALE_ALLOCS_PER_NODE,
                 seed=17):
    """A class-compressible fleet at scale: 2 datacenters x 8 HUGE
    racks (i % 8 — rack meta enters the computed class, so per-8-node
    racks would explode C to N/8) x 2 capacity shapes = 32 signature
    classes regardless of N. Filler allocs ride build_cluster's shape
    (service, no networks, modest footprint) so every node stays
    schedulable."""
    from nomad_tpu import mock
    from nomad_tpu.state import StateStore
    from nomad_tpu.structs import consts

    rng = random.Random(seed)
    store = StateStore()
    index = 0
    filler = mock.job()
    filler.id = "filler"
    filler.type = "service"
    filler.task_groups[0].tasks[0].resources.networks = []
    for i in range(n_nodes):
        node = mock.node()
        node.datacenter = f"dc{i % 2 + 1}"
        node.meta["rack"] = f"r{i % 8}"
        if i % 4 == 0:
            node.resources.cpu //= 2
            node.resources.memory_mb //= 2
        node.compute_class()
        index += 1
        store.upsert_node(index, node)
        if allocs_per_node:
            allocs = []
            for _ in range(allocs_per_node):
                alloc = mock.alloc()
                alloc.node_id = node.id
                alloc.job_id = filler.id
                alloc.job = filler
                alloc.desired_status = consts.ALLOC_DESIRED_RUN
                alloc.client_status = consts.ALLOC_CLIENT_RUNNING
                for tr in alloc.task_resources.values():
                    tr.cpu = rng.choice((25, 50))
                    tr.memory_mb = rng.choice((32, 64))
                    tr.networks = []
                alloc.resources = None
                allocs.append(alloc)
            index += 1
            store.upsert_allocs(index, allocs)
    return store, index


def _scale_arm(n_nodes, rounds=12, seed=17):
    """One scale measurement: the compression plane's contract surface
    at N nodes / 5N allocs. The GATED placement column runs the
    class-granular path (score C class rows, expand the winning class
    to its least-filled member at rounding — the tentpole's design);
    the node-granular dense program is reported as an UNGATED reference
    column (compute-bound: it scales with N by construction, which is
    exactly why the compression plane exists). Adds the gang arm at
    scale (all-K atomicity both ways), the auto-compressed defrag
    solve (exactly-once eviction), per-shard occupancy + device-memory
    columns when a mesh is available, and steady-state recompile
    accounting across the timed rounds."""
    import jax

    from nomad_tpu.defrag.solver import WarmState, compute_defrag_plan
    from nomad_tpu.gang import build_gang_state
    from nomad_tpu.models.classes import best_member_rows
    from nomad_tpu.models.matrix import ClusterMatrix, bucket_size
    from nomad_tpu.ops.binpack import (
        PlacementConfig,
        batched_placement_program_shared,
        host_prng_key,
        jit_cache_size,
        make_asks,
        make_node_state,
        placement_program_jit,
    )
    from nomad_tpu.ops.gang import gang_placement_program_jit
    from nomad_tpu.structs import Gang

    t0 = time.perf_counter()
    store, _ = _scale_fleet(n_nodes, seed=seed)
    snap = store.snapshot()
    job = service_job(networks=False)
    job.datacenters = ["dc1", "dc2"]
    matrix = ClusterMatrix(snap, job)
    build_s = time.perf_counter() - t0
    cidx = matrix.class_index
    out = {
        "nodes": n_nodes,
        "allocs": n_nodes * SCALE_ALLOCS_PER_NODE,
        "classes": int(cidx.n_classes),
        "class_compression_ratio": round(cidx.compression_ratio(), 2),
        "fleet_build_s": round(build_s, 1),
    }

    # ---- compressed placement rounds (the gated column).
    c_pad = bucket_size(cidx.n_classes)
    ask_fields = matrix.build_asks([0] * 8)
    asks = make_asks(*ask_fields)
    ask_res = np.asarray(ask_fields[0])
    config = PlacementConfig(anti_affinity_penalty=10.0)
    batch = 8
    util = matrix.util.copy()

    def class_round(s):
        rows, cls_ok = best_member_rows(
            cidx, util, matrix.capacity, matrix.node_ok)
        g = np.zeros(c_pad, np.int64)
        g[: cidx.n_classes] = rows
        ok = np.zeros(c_pad, bool)
        ok[: cidx.n_classes] = cls_ok
        state = make_node_state(
            matrix.capacity[g], matrix.sched_capacity[g], util[g],
            matrix.bw_avail[g], matrix.bw_used[g], matrix.ports_free[g],
            matrix.job_count[g], matrix.tg_count[g],
            matrix.feasible[g] & ok[:, None], ok)
        keys = jax.random.split(jax.random.PRNGKey(s), batch)
        choices, _scores, _f = batched_placement_program_shared(
            state, asks, keys, config)
        choices = np.asarray(choices)
        # Expand: winning CLASS -> its chosen concrete member row, and
        # commit eval 0's placements so rounds see moving utilization.
        picked = np.where(choices >= 0,
                          g[np.clip(choices, 0, c_pad - 1)], -1)
        for j, row in enumerate(picked[0, :8]):
            if row >= 0:
                util[row] += ask_res[j]
        return picked

    warm = class_round(0)
    assert (warm[:, :8] >= 0).all(), "compressed warmup failed to place"
    # Steady-state recompile accounting brackets ONLY the timed rounds:
    # each later arm (dense / sharded / gang / defrag) legitimately
    # compiles its program once on first entry and brackets its own
    # timed region the same way.
    jit_before = jit_cache_size()
    lat = []
    for r in range(rounds):
        t1 = time.perf_counter()
        class_round(r + 1)
        lat.append(time.perf_counter() - t1)
    recompiles = jit_cache_size() - jit_before
    out["place_p50_ms"] = round(float(np.percentile(lat, 50)) * 1e3, 2)
    out["place_p99_ms"] = round(float(np.percentile(lat, 99)) * 1e3, 2)
    out["class_pad"] = int(c_pad)

    # ---- node-granular dense reference (UNGATED: compute-bound in N).
    state_n = make_node_state(
        matrix.capacity, matrix.sched_capacity, matrix.util,
        matrix.bw_avail, matrix.bw_used, matrix.ports_free,
        matrix.job_count, matrix.tg_count, matrix.feasible,
        matrix.node_ok)
    dev_state = jax.tree.map(jax.device_put, state_n)
    dev_asks = jax.tree.map(jax.device_put, asks)

    def dense_round(s):
        keys = jax.random.split(jax.random.PRNGKey(s), batch)
        return np.asarray(batched_placement_program_shared(
            dev_state, dev_asks, keys, config)[0])

    dense_round(0)
    jit_before = jit_cache_size()
    dlat = []
    for r in range(4):
        t1 = time.perf_counter()
        dense_round(r + 1)
        dlat.append(time.perf_counter() - t1)
    recompiles += jit_cache_size() - jit_before
    out["dense_p50_ms"] = round(float(np.percentile(dlat, 50)) * 1e3, 2)
    out["dense_p99_ms"] = round(float(np.percentile(dlat, 99)) * 1e3, 2)
    out["device_mb"] = round(
        sum(np.asarray(x).nbytes for x in dev_state) / 1e6, 1)

    # ---- sharded arm: node axis over the mesh, occupancy + memory
    # per shard (metadata reads, no extra transfers).
    n_pad = matrix.capacity.shape[0]
    n_dev = jax.device_count()
    if n_dev > 1 and n_pad % n_dev == 0:
        from nomad_tpu.parallel.mesh import (
            make_mesh,
            shard_placement_inputs,
        )
        from nomad_tpu.parallel.shard import per_shard_occupancy

        mesh = make_mesh(n_dev, dp=1)
        st_sh, asks_sh, _key_sh = shard_placement_inputs(
            mesh, state_n, asks, host_prng_key(0))
        out["per_shard_occupancy"] = per_shard_occupancy(tuple(st_sh))
        # Warm with a HOST key — the timed rounds pass one per round,
        # and a committed/uncommitted key mismatch is itself a
        # recompile the gate would (rightly) refuse.
        placement_program_jit(st_sh, asks_sh, host_prng_key(0), config)
        jit_before = jit_cache_size()
        slat = []
        for r in range(3):
            t1 = time.perf_counter()
            np.asarray(placement_program_jit(
                st_sh, asks_sh, host_prng_key(r + 1), config)[0])
            slat.append(time.perf_counter() - t1)
        recompiles += jit_cache_size() - jit_before
        out["sharded_p50_ms"] = round(
            float(np.percentile(slat, 50)) * 1e3, 2)
        out["shards"] = n_dev
    else:
        out["per_shard_occupancy"] = []
        out["shards"] = 1

    # ---- gang arm at scale: slice gangs against the 8 huge racks.
    gang_job = service_job(networks=False)
    gang_job.datacenters = ["dc1", "dc2"]
    tg = gang_job.task_groups[0]
    tg.count = 8
    tg.gang = Gang(slice="rack")
    gm = ClusterMatrix(snap, gang_job)
    gstate, active, (g_res, g_bw, g_ports), gconfig = build_gang_state(
        gm, gang_job, tg)
    choices = np.asarray(gang_placement_program_jit(
        gstate, g_res, g_bw, g_ports, active, host_prng_key(3),
        gconfig)[0])
    placed = choices[: tg.count]
    out["gang_all_k_placed"] = bool((placed >= 0).all())
    impossible = g_res.copy()
    impossible[0] = 1e9  # no node fits one member, let alone K
    rejected = np.asarray(gang_placement_program_jit(
        gstate, impossible, g_bw, g_ports, active, host_prng_key(4),
        gconfig)[0])
    out["gang_reject_atomic"] = bool((rejected == -1).all())

    # ---- defrag arm: the global solve auto-compresses past
    # CLASS_COMPRESS_MIN_NODES; moves must name distinct allocs
    # (exactly-once eviction).
    t1 = time.perf_counter()
    plan = compute_defrag_plan(snap, ["dc1", "dc2"], max_moves=8,
                               min_gain=0.0, warm=WarmState(),
                               movable_cap=256)
    out["defrag_s"] = round(time.perf_counter() - t1, 2)
    out["defrag_compressed"] = bool(plan.compressed)
    out["defrag_classes"] = int(plan.classes)
    out["defrag_moves"] = len(plan.moves)
    out["defrag_exactly_once"] = (
        len({m.alloc_id for m in plan.moves}) == len(plan.moves))

    out["jit_recompiles"] = int(recompiles)
    return out


def run_scale(check=False):
    """The 100k-node / 500k-alloc scale config -> BENCH_r17: compressed
    placement p50/p99 at 10k and 100k (acceptance: the 100k p99 within
    2x the 10k figure — the whole point of scoring C classes instead of
    N nodes), class_compression_ratio / per-shard occupancy /
    device-memory columns, the gang arm at scale, and the
    auto-compressed defrag solve. With --check, refuses numbers on
    steady-state recompiles > 0, compression ratio < 2x, a broken gang
    atomicity flag, a double-evicting defrag move set, or a 100k p99
    past the 2x envelope."""
    arms = {n: _scale_arm(n) for n in SCALE_SIZES}
    a10, a100 = arms[SCALE_SIZES[0]], arms[SCALE_SIZES[1]]
    within_2x = a100["place_p99_ms"] <= 2.0 * a10["place_p99_ms"]
    acceptance = {
        "p99_100k_within_2x_of_10k": bool(within_2x),
        "compression_ratio_ge_2": all(
            a["class_compression_ratio"] >= 2.0 for a in arms.values()),
        "steady_state_recompiles_zero": all(
            a["jit_recompiles"] == 0 for a in arms.values()),
        "gang_atomicity": all(
            a["gang_all_k_placed"] and a["gang_reject_atomic"]
            for a in arms.values()),
        "defrag_compressed_at_100k": a100["defrag_compressed"],
        "defrag_exactly_once": all(
            a["defrag_exactly_once"] for a in arms.values()),
    }
    if check:
        for name, ok in acceptance.items():
            if not ok:
                print(f"bench: REFUSING scale numbers: acceptance "
                      f"'{name}' failed "
                      f"(10k={a10}, 100k={a100})", file=sys.stderr)
                sys.exit(2)
    out = {
        "metric": (
            f"[scale {SCALE_SIZES[1] // 1000}k nodes / "
            f"{SCALE_SIZES[1] * SCALE_ALLOCS_PER_NODE // 1000}k allocs] "
            f"compressed placement p99 "
            f"{a100['place_p99_ms']:.1f}ms at 100k vs "
            f"{a10['place_p99_ms']:.1f}ms at 10k "
            f"({'within' if within_2x else 'OUTSIDE'} 2x; dense "
            f"node-granular reference {a100['dense_p99_ms']:.0f}ms), "
            f"ratio {a100['class_compression_ratio']:.0f}x "
            f"({a100['classes']} classes), "
            f"defrag {'compressed' if a100['defrag_compressed'] else 'dense'} "
            f"{a100['defrag_moves']} moves, recompiles "
            f"{a100['jit_recompiles']}"),
        "scale_10k": a10,
        "scale_100k": a100,
        "acceptance": acceptance,
    }
    return out


def _exec_profile_snapshot():
    """Per-arm convoy/runq/dispatch-gap columns — the exact axes
    BENCH_r13 measured on the pre-executive shape (convoy width 63/64,
    runq.batch_park p99 55.1ms, dispatch p99−p50 gap 44.7ms). Each
    executive-ab arm reads these off a freshly-reset profiler/recorder
    so the paired arms never share histograms."""
    from nomad_tpu.trace import get_recorder

    cols = _profile_cols()
    stages = get_recorder().stage_stats()
    dd = stages.get("device.dispatch", {})
    p50 = dd.get("p50_ms", 0.0)
    p99 = dd.get("p99_ms", 0.0)
    return {
        "convoy_width": cols.get("convoy_width", 0),
        "runq_batch_park_p99_ms": cols.get("profile", {}).get(
            "runq_p99_ms", {}).get("batch_park", 0.0),
        "lock_wait_p99_ms": cols.get("lock_wait_p99_ms", 0.0),
        "dispatch_p50_ms": p50,
        "dispatch_p99_ms": p99,
        "dispatch_gap_ms": round(max(0.0, p99 - p50), 3),
        "device_sync_p99_ms": stages.get("device.solve",
                                         {}).get("p99_ms", 0.0),
    }


def _exec_arm_config4(executive):
    """Config 4's e2e shape, one arm: the measured path BENCH_r13
    profiled. `executive=False` is the legacy 64-thread worker shape
    (the before picture); True is the cohort-row shape."""
    from nomad_tpu.profile import get_profiler
    from nomad_tpu.trace import get_recorder

    get_recorder().reset()
    get_profiler().reset()
    store, _ = build_cluster(10_000, datacenters=("dc1", "dc2"),
                             allocs_per_node=5)
    job = service_job(networks=True, distinct_hosts=True)
    job.datacenters = ["dc1", "dc2"]
    job.task_groups[0].count = 8
    e2e_rate, e2e_p99, ds = bench_tpu_e2e(
        store, job, 8, batch=64, rounds=3, executive=executive)
    return {
        "e2e": e2e_rate, "e2e_p99_ms": e2e_p99 * 1000,
        "occupancy": ds["occupancy"],
        "jit_recompiles": ds["jit_recompiles"],
        "funnel_terminals_ok": 1.0,  # harness shape: no live evals
        **_exec_profile_snapshot(),
    }


def _exec_live_arm(n_nodes, n_jobs, allocs_per_job, executive,
                   drain_frac=0.1, warm_jobs=None):
    """One LIVE executive-vs-workers arm (the configs-5/7 churn shape,
    scaled live-feasible): real server, storm against a parked drain,
    then a drain wave so displaced allocs flow through the executive's
    legacy lane + migration machinery. Returns throughput plus the
    BENCH_r13 contention axes and the two --check gate inputs:
    steady-state recompiles and the raft-funnel terminal sweep (every
    eval in FSM state terminal after settle)."""
    from nomad_tpu import mock
    from nomad_tpu.profile import get_profiler
    from nomad_tpu.scheduler.batcher import get_batcher
    from nomad_tpu.server import Server, ServerConfig
    from nomad_tpu.structs import consts
    from nomad_tpu.trace import get_recorder

    get_recorder().reset()
    get_profiler().reset()
    server = Server(ServerConfig(
        num_schedulers=4,
        scheduler_factories={"service": "service-tpu"},
        scheduler_executive=executive,
        eval_nack_timeout=60.0))
    server.start()

    def pause(flag):
        for w in server.workers:
            w.set_pause(flag)
        server.executive.set_pause(flag)

    def make_job(jid):
        job = mock.job()
        job.id = jid
        job.type = "service"
        job.task_groups[0].count = allocs_per_job
        t = job.task_groups[0].tasks[0]
        t.resources.networks = []
        t.resources.cpu = 20
        t.resources.memory_mb = 16
        return job

    def wait_evals(evs, deadline_s):
        deadline = time.perf_counter() + deadline_s
        while time.perf_counter() < deadline:
            st = [server.fsm.state.eval_by_id(e) for e in evs]
            if all(s is not None and s.terminal_status() for s in st):
                return True
            time.sleep(0.02)
        return False

    try:
        nodes = []
        for _ in range(n_nodes):
            node = mock.node()
            node.compute_class()
            server.log.apply("node_register", {"node": node})
            nodes.append(node)
        # Warm wave (unmeasured), sized LIKE the measured storm so its
        # cohort lands in the same batch bucket — a smaller warm wave
        # leaves the storm's padded program uncompiled and the
        # recompile gate would (rightly) refuse.
        warm = [make_job(f"xwarm-{j}")
                for j in range(warm_jobs or n_jobs)]
        pause(True)
        wevals = [server.job_register(j)[0] for j in warm]
        pause(False)
        assert wait_evals(wevals, 300), "warm wave never settled"
        for j in warm:
            server.job_deregister(j.id)
        deadline = time.perf_counter() + 120
        while time.perf_counter() < deadline:
            s = server.broker.stats()
            if not s["total_ready"] and not s["total_unacked"]:
                break
            time.sleep(0.05)
        jit0 = get_batcher().stats()["jit_cache_size"]

        # Measured storm.
        jobs = [make_job(f"xstorm-{j}") for j in range(n_jobs)]
        pause(True)
        evals = [server.job_register(j)[0] for j in jobs]
        t0 = time.perf_counter()
        pause(False)
        assert wait_evals(evals, 300), "storm never settled"
        storm_elapsed = time.perf_counter() - t0
        # The recompile gate reads the STORM window (the steady-state
        # claim); the drain wave below adds churn-shaped programs the
        # warm wave deliberately does not cover.
        jit_storm = get_batcher().stats()["jit_cache_size"]
        placed = sum(
            1 for j in jobs for a in server.fsm.state.allocs_by_job(j.id)
            if not a.terminal_status())

        # Drain wave: displaced allocs re-place (the churn shape the
        # executive's legacy lane + migration budget own).
        occupancy = {}
        for a in server.fsm.state.allocs():
            if not a.terminal_status():
                occupancy[a.node_id] = occupancy.get(a.node_id, 0) + 1
        by_load = sorted(occupancy, key=occupancy.get, reverse=True)
        drained = set(by_load[: max(1, int(n_nodes * drain_frac))])
        for nid in drained:
            server.node_update_drain(nid, True)
        deadline = time.perf_counter() + 180
        replaced = False
        while time.perf_counter() < deadline:
            live = {j.id: [a for a in server.fsm.state.allocs_by_job(j.id)
                           if not a.terminal_status()] for j in jobs}
            s = server.broker.stats()
            if (all(len(v) == allocs_per_job for v in live.values())
                    and all(a.node_id not in drained
                            for v in live.values() for a in v)
                    and not s["total_ready"] and not s["total_unacked"]
                    and not s["total_waiting"]):
                replaced = True
                break
            time.sleep(0.05)
        jit1 = get_batcher().stats()["jit_cache_size"]
        # Raft-funnel terminal sweep: every eval this arm minted must
        # hold exactly one terminal status in FSM state (the --check
        # refusal input — a pending/unacked eval after settle means a
        # lost terminal). Brief re-check loop: the last no-op
        # follow-up's status write can land milliseconds after the
        # broker reads quiet.
        terminal_ok = False
        deadline = time.perf_counter() + 15
        while time.perf_counter() < deadline and not terminal_ok:
            terminal_ok = all(
                e.terminal_status()
                or e.status == consts.EVAL_STATUS_BLOCKED
                for e in server.fsm.state.evals())
            if not terminal_ok:
                time.sleep(0.05)
        ex = server.stats()["scheduler_executive"]
        return {
            "e2e": n_jobs / storm_elapsed,
            "placed_frac": placed / (n_jobs * allocs_per_job),
            "drain_replaced": float(replaced),
            "jit_recompiles": jit_storm - jit0,
            "jit_drain_wave_programs": jit1 - jit_storm,
            "funnel_terminals_ok": float(terminal_ok),
            "executive_fast_evals": ex["fast_evals"],
            "executive_legacy_evals": ex["legacy_evals"],
            "executive_occupancy": ex["occupancy"],
            **_exec_profile_snapshot(),
        }
    finally:
        server.shutdown()


EXECUTIVE_AB_LIVE_ARMS = {
    # configs 5/7's churn shapes, scaled to live-feasible sizes.
    "config5": (600, 36, 4),
    "config7": (300, 24, 4),
}


def run_executive_ab(reps=2, check=False):
    """Paired executive-vs-workers A/B (the PR 12 tentpole's headline
    rig) -> BENCH_r14.json: config 4's measured e2e shape plus live
    churn arms at configs 5/7's shapes, each rep running both arms back
    to back so host drift cancels. Emits the BENCH_r13 before-picture
    axes per arm — convoy_width, runq.batch_park p99, dispatch p99−p50
    — and with --check refuses executive numbers if steady-state
    recompiles > 0 or any live eval lacks a raft-funnel terminal."""
    arms = {}
    plan = {"config4": None}
    plan.update(EXECUTIVE_AB_LIVE_ARMS)
    for arm_name, shape in plan.items():
        runs = {"executive": [], "workers": []}
        for _ in range(reps):
            for mode, flag in (("executive", True), ("workers", False)):
                if shape is None:
                    runs[mode].append(_exec_arm_config4(flag))
                else:
                    runs[mode].append(_exec_live_arm(*shape, flag))
        per_mode = {}
        for mode, rr in runs.items():
            per_mode[mode] = {
                k: round(_median_iqr([float(r[k]) for r in rr])[0], 4)
                for k in rr[0]}
        ex, wk = per_mode["executive"], per_mode["workers"]
        arms[arm_name] = {
            "modes": per_mode,
            "speed_ratio": round(ex["e2e"] / wk["e2e"], 3)
            if wk["e2e"] else 0.0,
            "convoy_width_before_after": [wk["convoy_width"],
                                          ex["convoy_width"]],
            "runq_batch_park_p99_before_after_ms": [
                wk["runq_batch_park_p99_ms"],
                ex["runq_batch_park_p99_ms"]],
            "dispatch_gap_before_after_ms": [wk["dispatch_gap_ms"],
                                             ex["dispatch_gap_ms"]],
        }
        if check:
            if ex["jit_recompiles"] > 0:
                print(f"bench: REFUSING executive-ab numbers: arm "
                      f"{arm_name!r} recompiled in steady state "
                      f"(jit_recompiles={ex['jit_recompiles']})",
                      file=sys.stderr)
                sys.exit(2)
            if ex["funnel_terminals_ok"] < 1.0:
                print(f"bench: REFUSING executive-ab numbers: arm "
                      f"{arm_name!r} left evals without a raft-funnel "
                      f"terminal after settle", file=sys.stderr)
                sys.exit(2)
    from nomad_tpu.server.config import ServerConfig as _SC

    bound = 2 * _SC().dispatch_max_inflight
    summary = "; ".join(
        f"{name}: x{a['speed_ratio']:.2f} speed, convoy "
        f"{a['convoy_width_before_after'][0]:.0f}->"
        f"{a['convoy_width_before_after'][1]:.0f}, batch_park p99 "
        f"{a['runq_batch_park_p99_before_after_ms'][0]:.1f}->"
        f"{a['runq_batch_park_p99_before_after_ms'][1]:.1f}ms"
        for name, a in arms.items())
    return {
        "metric": f"[executive-ab vs workers, median-of-{reps}] "
                  + summary,
        "arms": arms,
        "convoy_bound": bound,
        "acceptance": {
            # The tentpole's measured claims: the convoy is gone on
            # every arm, and the headline (config 4) shape is faster.
            # Live churn-arm ratios are reported as-is: on a CPU-only
            # host with a sub-ms inline "device", thread-per-eval's
            # fine-grained overlap can still edge out single-cohort
            # storms — the remote-device regime (~100ms RTT/dispatch,
            # the r05/r06 transport analysis) is where fewer, fuller,
            # no-park cohorts win outright.
            "convoy_within_bound": all(
                a["convoy_width_before_after"][1] <= bound
                for a in arms.values()),
            "config4_faster": bool(
                arms["config4"]["speed_ratio"] >= 1.0),
        },
    }


def _convoy_gate(out, n):
    """--check (PR 12): dense-path numbers measured through a wide
    batch-boundary convoy describe the thread-parked legacy shape, not
    the executive pipeline — a convoy wider than 2x the dispatch
    in-flight bound means eval threads piled up on batcher events
    (BENCH_r13's measured pathology). Refuse."""
    from nomad_tpu.server.config import ServerConfig as _SC

    bound = 2 * _SC().dispatch_max_inflight
    cw = out.get("columns", {}).get("convoy_width", {}).get("median", 0)
    if cw and cw > bound:
        print(f"bench: REFUSING to report config {n}: convoy_width "
              f"{cw:.0f} > {bound} (2x dispatch_max_inflight) — eval "
              f"threads convoyed at the batch boundary; run the "
              f"scheduler-executive shape or fix the park regression",
              file=sys.stderr)
        sys.exit(2)


# The dirs the --check gates sweep. Module constants so the ntalint
# self-checks (tests/test_static_analysis.py) can assert the kernels
# subsystem is inside both gates rather than trusting a string copy.
PURITY_GATE_DIRS = ("ops", "scheduler", "kernels", "migrate",
                    "defrag", "gang")
CONCURRENCY_GATE_DIRS = ("nomad_tpu/dispatch/", "nomad_tpu/scheduler/",
                         "nomad_tpu/server/", "nomad_tpu/kernels/",
                         "nomad_tpu/migrate/", "nomad_tpu/defrag/",
                         "nomad_tpu/gang/")
COMPILE_SURFACE_GATE_DIRS = ("nomad_tpu/ops/", "nomad_tpu/kernels/",
                             "nomad_tpu/models/", "nomad_tpu/parallel/")


def ntalint_compile_surface_gate():
    """Compile-surface findings invalidate dense-path numbers before a
    single device call runs: an unbucketed shape or a drifting static
    key IS the recompile storm the jit_recompiles column would catch a
    full bench rep later, and an unregistered jit entry point means
    that column is blind. This gate runs FIRST under --check — pure
    host AST work, so a compile-surface regression fails in ~1s
    instead of after warmup. Whole-tree analysis (whole-program
    rules), findings filtered to the jit-accounted dirs. Returns the
    non-baselined findings."""
    import os

    from nomad_tpu.analysis import (
        analyze_paths,
        apply_baseline,
        load_baseline,
    )
    from nomad_tpu.analysis.compile_surface import (
        RULE_DONATION,
        RULE_KEY_DRIFT,
        RULE_UNBUCKETED,
        RULE_UNREGISTERED,
    )

    root = os.path.dirname(os.path.abspath(__file__))
    findings = analyze_paths(
        [os.path.join(root, "nomad_tpu")],
        rules={RULE_UNBUCKETED, RULE_KEY_DRIFT, RULE_UNREGISTERED,
               RULE_DONATION, "parse-error"})
    new, _stale = apply_baseline(findings, load_baseline())
    return [f for f in new if f.path.startswith(COMPILE_SURFACE_GATE_DIRS)]


def ntalint_purity_gate():
    """Trace-purity findings in the kernel path (ops/, scheduler/,
    kernels/) invalidate dense-path numbers BY CONSTRUCTION: an impure
    call or a host sync inside a jitted program means the benchmark
    measured a host fallback or a trace-time constant, not the device
    path it claims to. Returns the non-baselined findings."""
    import os

    from nomad_tpu.analysis import (
        analyze_paths,
        apply_baseline,
        load_baseline,
    )
    from nomad_tpu.analysis import purity

    root = os.path.dirname(os.path.abspath(__file__))
    # The checker's own constants, not string copies: a renamed rule id
    # must break this gate loudly, not silently filter every finding.
    # parse-error rides along: a file the analyzer could not parse got
    # ZERO purity analysis — "gate clean" would be a lie for it.
    purity_rules = {purity.RULE_IMPURE, purity.RULE_HOST_SYNC,
                    purity.RULE_CLOSURE_MUT, purity.RULE_BRANCH,
                    purity.RULE_STATIC, "parse-error"}
    findings = analyze_paths(
        [os.path.join(root, "nomad_tpu", d) for d in PURITY_GATE_DIRS],
        rules=purity_rules)
    new, _stale = apply_baseline(findings, load_baseline())
    return new


def ntalint_concurrency_gate():
    """Deadlock-cycle / raft-funnel findings in the dispatch, scheduler
    or server paths invalidate dense-path numbers the same way purity
    findings do: a lock-order cycle means the measured throughput is
    one unlucky interleaving away from a frozen pipeline, and a
    raft-funnel violation means the eval terminals the benchmark
    counts can double-commit or never commit. Whole-tree analysis
    (these are whole-program rules — edges through utils/ and models/
    are the point), findings filtered to the gated dirs. Returns the
    non-baselined findings."""
    import os

    from nomad_tpu.analysis import (
        analyze_paths,
        apply_baseline,
        load_baseline,
    )
    from nomad_tpu.analysis.deadlock import RULE_DEADLOCK
    from nomad_tpu.analysis.protocol import RULE_FUNNEL

    root = os.path.dirname(os.path.abspath(__file__))
    findings = analyze_paths(
        [os.path.join(root, "nomad_tpu")],
        rules={RULE_DEADLOCK, RULE_FUNNEL, "parse-error"})
    new, _stale = apply_baseline(findings, load_baseline())
    return [f for f in new if f.path.startswith(CONCURRENCY_GATE_DIRS)]


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--config", type=int, default=HEADLINE_CONFIG,
                        choices=sorted(CONFIGS))
    parser.add_argument("--all", action="store_true")
    parser.add_argument("--reps", type=int, default=DEFAULT_REPS,
                        help="interleaved CPU/TPU repetitions per config;"
                             " medians + IQR are reported")
    parser.add_argument("--check", action="store_true",
                        help="run the ntalint compile-surface gate "
                             "(jit-cache bounding / shape buckets), "
                             "then the trace-purity and concurrency "
                             "gates, before any device warmup; refuse "
                             "to report dense-path numbers on findings")
    parser.add_argument("--chaos", type=int, default=None, metavar="SEED",
                        help="run config 4 clean AND under a mild seeded "
                             "fault schedule (nomad_tpu/chaos); reports "
                             "degraded-mode occupancy + retries/eval "
                             "alongside the clean numbers")
    parser.add_argument("--overload", type=int, default=None,
                        metavar="SEED",
                        help="overload A/B on the live pipeline "
                             "(nomad_tpu/admission): measure capacity, "
                             "storm at 3x, report shed_rate / goodput / "
                             "accepted-eval p99 with protection on vs "
                             "off")
    parser.add_argument("--resident-ab", action="store_true",
                        help="device-resident state ON/OFF A/B on "
                             "config 4 (models/resident.py) — the "
                             "BENCH_r10 arm. With --check, refuses "
                             "numbers unless ON >= OFF on every config "
                             "(the PR 12 inversion-flip gate)")
    parser.add_argument("--executive-ab", action="store_true",
                        help="paired scheduler-executive vs "
                             "thread-per-eval-workers A/B "
                             "(server/executive.py) on config 4's e2e "
                             "shape + live churn arms at configs 5/7's "
                             "shapes, emitting convoy_width / "
                             "runq.batch_park p99 / dispatch p99-p50 "
                             "against the BENCH_r13 before-picture — "
                             "the BENCH_r14 arm. With --check, refuses "
                             "executive numbers on steady-state "
                             "recompiles or missing raft-funnel "
                             "terminals")
    parser.add_argument("--executive-ab-reps", type=int, default=2,
                        help="paired reps per executive-ab arm")
    parser.add_argument("--resident-ab-configs", type=str, default="",
                        help="comma-separated config numbers for the "
                             "resident A/B (default: the headline "
                             "config); the --check ON >= OFF gate "
                             "applies to every listed config")
    parser.add_argument("--kernel-ab", action="store_true",
                        help="placement-kernel A/B (nomad_tpu/kernels):"
                             " greedy vs convex on config 4's shape + "
                             "a fragmentation-heavy arm, throughput "
                             "and quality columns — the BENCH_r11 arm."
                             " With --check, kernels must pass the "
                             "oracle differential rig first")
    parser.add_argument("--kernel-ab-reps", type=int, default=3,
                        help="interleaved reps per kernel-ab arm")
    parser.add_argument("--preempt-ab", action="store_true",
                        help="priority-preemption ON/OFF A/B under a "
                             "3x priority storm (nomad_tpu/migrate + "
                             "ops/preempt.py) — the BENCH_r12 arm. "
                             "With --check, refuses numbers if any "
                             "eviction lacks a raft-funnel terminal")
    parser.add_argument("--preempt-ab-reps", type=int, default=3,
                        help="reps per preempt-ab arm")
    parser.add_argument("--defrag-ab", action="store_true",
                        help="continuous-defragmentation ON/OFF A/B: "
                             "fragmentation trajectory under identical "
                             "seeded churn, waves through the real "
                             "DefragLoop under the migration budget "
                             "(BENCH_r15)")
    parser.add_argument("--defrag-ab-reps", type=int, default=2,
                        help="seeded churn reps per defrag-ab arm")
    parser.add_argument("--gang-ab", action="store_true",
                        help="gang ON/OFF A/B on a DL-trace-shaped "
                             "arm (large slice gangs arriving over "
                             "churn, Tesserae-style), scored on "
                             "gang_wait_p99_ms / slice_frag; with "
                             "--check refuses numbers on any "
                             "partially-committed gang, a "
                             "non-contiguous placed slice gang, or "
                             "steady-state recompiles > 0")
    parser.add_argument("--gang-ab-reps", type=int, default=2,
                        help="seeded churn reps per gang-ab arm")
    parser.add_argument("--scale", action="store_true",
                        help="the 100k-node / 500k-alloc compression-"
                             "plane config (models/classes.py + "
                             "parallel/shard.py) — the BENCH_r17 arm: "
                             "class-granular placement p50/p99 at 10k "
                             "vs 100k, class_compression_ratio / "
                             "per-shard occupancy / device-memory "
                             "columns, gang + defrag arms at scale. "
                             "With --check, refuses numbers on "
                             "steady-state recompiles > 0, ratio < 2x, "
                             "or a 100k p99 past 2x the 10k figure")
    parser.add_argument("--read-storm", action="store_true",
                        help="read-plane storm A/B (nomad_tpu/readplane):"
                             " park N blocking queries on disjoint "
                             "scopes with 1 write client per 10 "
                             "watchers, mux vs thread-park baseline — "
                             "parked-thread footprint, wake-to-serve "
                             "p99, spurious ratio, stale-vs-consistent "
                             "read latency. With --check, refuses "
                             "numbers on spurious > 1% or a mux "
                             "footprint that scales with watchers")
    parser.add_argument("--read-storm-watchers", type=int, default=200,
                        help="parked watchers per read-storm arm")
    parser.add_argument("--no-trace", action="store_true",
                        help="disable the eval-lifecycle flight recorder "
                             "(nomad_tpu/trace) for this run — the A/B "
                             "arm the --check overhead gate compares "
                             "against")
    parser.add_argument("--profile-off", action="store_true",
                        help="disable the contention observatory "
                             "(nomad_tpu/profile) for this run — the "
                             "paired arm --profile-ab compares against")
    parser.add_argument("--profile-ab", action="store_true",
                        help="paired profiler-on/profiler-off A/B on "
                             "one config: contention columns "
                             "(lock_wait_p99_ms / gil_overshoot_p99_ms "
                             "/ convoy_width), the device.dispatch "
                             "p99-p50 gap attribution, and the paired "
                             "overhead ratio — the BENCH_r13 arm. With "
                             "--check, refuses numbers if the median "
                             "paired e2e ratio < 0.95")
    args = parser.parse_args()

    from nomad_tpu.profile import get_profiler
    from nomad_tpu.trace import get_recorder

    if args.no_trace:
        get_recorder().set_enabled(False)
    if args.profile_off:
        get_profiler().configure(enabled=False)
    else:
        # Always-on means the bench measures what production runs:
        # recording enabled and the GIL sampler live.
        get_profiler().ensure_sampler()

    if args.check:
        bad = ntalint_compile_surface_gate()
        if bad:
            for f in bad:
                print(f.render(), file=sys.stderr)
            print(f"bench: REFUSING to report dense-path numbers: "
                  f"{len(bad)} compile-surface finding(s) in ops//"
                  f"kernels//models//parallel/ — the jit cache is no "
                  f"longer statically bounded (fix them or run "
                  f"without --check)", file=sys.stderr)
            sys.exit(2)
        print("bench: ntalint compile-surface gate clean",
              file=sys.stderr)
        bad = ntalint_purity_gate()
        if bad:
            for f in bad:
                print(f.render(), file=sys.stderr)
            print(f"bench: REFUSING to report dense-path numbers: "
                  f"{len(bad)} trace-purity finding(s) in ops//"
                  f"scheduler/ (fix them or run without --check)",
                  file=sys.stderr)
            sys.exit(2)
        print("bench: ntalint trace-purity gate clean", file=sys.stderr)
        bad = ntalint_concurrency_gate()
        if bad:
            for f in bad:
                print(f.render(), file=sys.stderr)
            print(f"bench: REFUSING to report dense-path numbers: "
                  f"{len(bad)} deadlock-cycle/raft-funnel finding(s) "
                  f"in dispatch//scheduler//server/ (fix them or run "
                  f"without --check)", file=sys.stderr)
            sys.exit(2)
        print("bench: ntalint deadlock/raft-funnel gate clean",
              file=sys.stderr)

    if args.check and not args.no_trace and (args.all
                                             or args.chaos is not None):
        # The trace-overhead A/B gate needs paired traced/untraced runs
        # of ONE config; doubling the whole matrix (--all) or the chaos
        # A/B would conflate arms. Say so loudly — a silent skip would
        # read as "gate passed".
        print("bench: NOTE --check's trace-overhead gate only applies "
              "to single-config runs; run `bench.py --check --config "
              f"{HEADLINE_CONFIG}` for the gated traced-vs-untraced "
              "comparison (the purity gate above DID run)",
              file=sys.stderr)

    if args.profile_ab:
        if args.profile_off:
            print("bench: --profile-ab and --profile-off are mutually "
                  "exclusive (the A/B runs both arms itself)",
                  file=sys.stderr)
            sys.exit(2)
        out, ratio = run_config_profile_ab(args.config, reps=args.reps)
        if args.check:
            _shed_gate(out, args.config)
            _recompile_gate(out, args.config)
            _convoy_gate(out, args.config)
            if ratio < 0.95:
                print(json.dumps(out), file=sys.stderr)
                print(f"bench: REFUSING to report — the contention "
                      f"observatory cost {(1 - ratio) * 100:.1f}% of "
                      f"median paired e2e (> 5% budget; per-rep ratios "
                      f"{out['profile_overhead']['per_rep_ratios']})",
                      file=sys.stderr)
                sys.exit(2)
        print(json.dumps(out))
        return

    if args.executive_ab:
        print(json.dumps(run_executive_ab(reps=args.executive_ab_reps,
                                          check=args.check)))
        return

    if args.kernel_ab:
        print(json.dumps(run_kernel_ab(reps=args.kernel_ab_reps,
                                       check=args.check)))
        return

    if args.preempt_ab:
        print(json.dumps(run_preempt_ab(reps=args.preempt_ab_reps,
                                        check=args.check)))
        return

    if args.defrag_ab:
        print(json.dumps(run_defrag_ab(reps=args.defrag_ab_reps,
                                       check=args.check)))
        return

    if args.scale:
        print(json.dumps(run_scale(check=args.check)))
        return

    if args.gang_ab:
        print(json.dumps(run_gang_ab(reps=args.gang_ab_reps,
                                     check=args.check)))
        return

    if args.resident_ab:
        configs = (tuple(int(c) for c in
                         args.resident_ab_configs.split(",") if c)
                   or (None,))
        out = run_resident_ab(reps=args.reps, configs=configs)
        if args.check:
            _shed_gate(out["resident_on"], HEADLINE_CONFIG)
            _recompile_gate(out["resident_on"], HEADLINE_CONFIG)
            _convoy_gate(out["resident_on"], HEADLINE_CONFIG)
            if not out["on_ge_off_every_config"]:
                print("bench: REFUSING resident-ab numbers: resident "
                      "ON < OFF — the delta machinery is paying "
                      "contention again (the BENCH_r10 inversion the "
                      "executive removed); fix the regression",
                      file=sys.stderr)
                sys.exit(2)
        print(json.dumps(out))
        return

    if args.read_storm:
        print(json.dumps(run_read_storm(
            n_watchers=args.read_storm_watchers, check=args.check)))
        return

    if args.chaos is not None:
        print(json.dumps(run_chaos(args.chaos)))
        return

    if args.overload is not None:
        print(json.dumps(run_overload(args.overload)))
        return

    if args.all:
        for n in sorted(CONFIGS):
            out = run_config(n, reps=args.reps)
            if args.check:
                _shed_gate(out, n)
                _recompile_gate(out, n)
                _convoy_gate(out, n)
            print(json.dumps(out))
        return

    if args.check and not args.no_trace:
        # Trace-overhead gate: the always-on recorder must be close to
        # free. Each rep runs traced then untraced back to back and
        # the gate reads the MEDIAN of per-rep ratios — refusing to
        # report if tracing cost more than 5% of median e2e.
        out, ratio = run_config_trace_ab(args.config, reps=args.reps)
        if ratio < 0.95:
            print(json.dumps(out), file=sys.stderr)
            print(f"bench: REFUSING to report — tracing cost "
                  f"{(1 - ratio) * 100:.1f}% of median e2e (> 5% "
                  f"budget; per-rep ratios "
                  f"{out['trace_overhead']['per_rep_ratios']})",
                  file=sys.stderr)
            sys.exit(2)
    else:
        out = run_config(args.config, reps=args.reps)
    if args.check:
        _shed_gate(out, args.config)
        _recompile_gate(out, args.config)
        _convoy_gate(out, args.config)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
