"""Benchmark: scheduler placement throughput, CPU iterator stack vs
batched TPU kernel.

Scenario (BASELINE.md config 2): 1k-node cluster, evals placing a
batch job via CPU+mem bin-packing. The CPU baseline runs the reference
iterator pipeline (stack.select per placement); the TPU path runs the
same placements as one batched dense program (ops/binpack.py), B evals
vmapped per dispatch — the broker drain-to-batch design from
BASELINE.json's north star.

Prints ONE JSON line:
  {"metric": ..., "value": evals_per_sec_tpu, "unit": "evals/sec",
   "vs_baseline": tpu/cpu}
"""

import json
import random
import sys
import time

sys.path.insert(0, ".")

import numpy as np

N_NODES = 1000
K_PLACEMENTS = 8  # allocs placed per eval
CPU_EVALS = 30  # evals timed on the CPU path
TPU_BATCH = 2048  # evals per TPU dispatch
TPU_ROUNDS = 8  # timed dispatches (after warmup)


def build_cluster():
    from nomad_tpu import mock
    from nomad_tpu.state import StateStore

    store = StateStore()
    for i in range(N_NODES):
        node = mock.node()
        store.upsert_node(i + 1, node)
    job = mock.job()
    job.type = "batch"
    job.task_groups[0].count = K_PLACEMENTS
    # config 2 is CPU+mem only: strip the network ask
    job.task_groups[0].tasks[0].resources.networks = []
    store.upsert_job(N_NODES + 1, job)
    return store, job


def bench_cpu(store, job):
    """Reference pipeline: per-eval stack.select loop."""
    from nomad_tpu.scheduler.context import EvalContext
    from nomad_tpu.scheduler.stack import GenericStack
    from nomad_tpu.scheduler.util import ready_nodes_in_dcs
    from nomad_tpu.structs import Plan

    snap = store.snapshot()
    latencies = []
    start = time.perf_counter()
    for i in range(CPU_EVALS):
        t0 = time.perf_counter()
        plan = Plan(job=job)
        ctx = EvalContext(snap, plan, rng=random.Random(i))
        stack = GenericStack(True, ctx)
        stack.set_job(job)
        nodes, _ = ready_nodes_in_dcs(snap, job.datacenters)
        stack.set_nodes(nodes)
        tg = job.task_groups[0]
        for _ in range(K_PLACEMENTS):
            option, _ = stack.select(tg)
            assert option is not None
            from nomad_tpu.structs import Allocation
            from nomad_tpu.utils.ids import generate_uuid

            plan.append_alloc(
                Allocation(
                    id=generate_uuid(),
                    job_id=job.id,
                    node_id=option.node.id,
                    task_group=tg.name,
                    task_resources=dict(option.task_resources),
                )
            )
        latencies.append(time.perf_counter() - t0)
    elapsed = time.perf_counter() - start
    return CPU_EVALS / elapsed, latencies


def bench_tpu(store, job):
    """Batched dense program: TPU_BATCH evals per dispatch."""
    import jax

    from nomad_tpu.models.matrix import ClusterMatrix
    from nomad_tpu.ops.binpack import (
        PlacementConfig,
        batched_placement_program_shared,
        make_asks,
        make_node_state,
    )

    snap = store.snapshot()
    matrix = ClusterMatrix(snap, job)
    state = make_node_state(
        matrix.capacity, matrix.sched_capacity, matrix.util,
        matrix.bw_avail, matrix.bw_used, matrix.ports_free,
        matrix.job_count, matrix.tg_count, matrix.feasible, matrix.node_ok,
    )
    asks = make_asks(*matrix.build_asks([0] * K_PLACEMENTS))

    # The cluster matrix lives on device across dispatches (it changes
    # only when the snapshot does); per dispatch only keys move.
    state = jax.tree.map(jax.device_put, state)
    asks = jax.tree.map(jax.device_put, asks)
    config = PlacementConfig(anti_affinity_penalty=5.0)

    def dispatch(seed):
        keys = jax.random.split(jax.random.PRNGKey(seed), TPU_BATCH)
        choices, scores, _ = batched_placement_program_shared(
            state, asks, keys, config
        )
        return choices

    # Warmup / compile
    warm = np.asarray(dispatch(0))
    assert (warm >= 0).all(), "warmup produced failed placements"

    # Latency: one synchronous round including its result fetch — the
    # submit-to-answer time every eval in that batch observes.
    t0 = time.perf_counter()
    np.asarray(dispatch(1))
    sync_latency = time.perf_counter() - t0

    # Throughput: pipeline the dispatches (JAX async dispatch overlaps
    # them) and fetch all results in one device->host transfer — the
    # broker sidecar streams results the same way.
    start = time.perf_counter()
    outs = [dispatch(r + 2) for r in range(TPU_ROUNDS)]
    results = [np.asarray(o) for o in outs]
    elapsed = time.perf_counter() - start
    for out in results:
        assert (out >= 0).all()
    evals_per_sec = TPU_BATCH * TPU_ROUNDS / elapsed
    return evals_per_sec, sync_latency


def main():
    store, job = build_cluster()

    cpu_rate, cpu_lat = bench_cpu(store, job)
    tpu_rate, tpu_p99 = bench_tpu(store, job)
    cpu_p99 = float(np.percentile(cpu_lat, 99))

    print(
        json.dumps(
            {
                "metric": (
                    f"scheduler placement throughput, {N_NODES} nodes x "
                    f"{K_PLACEMENTS} allocs/eval (cpu+mem bin-pack); "
                    f"cpu={cpu_rate:.1f} evals/s p99={cpu_p99*1000:.1f}ms, "
                    f"tpu p99/batch={tpu_p99*1000:.1f}ms"
                ),
                "value": round(tpu_rate, 1),
                "unit": "evals/sec",
                "vs_baseline": round(tpu_rate / cpu_rate, 2),
            }
        )
    )


if __name__ == "__main__":
    main()
