"""Consul agent HTTP API client plus an in-process fake.

The real client speaks the Consul v1 agent/catalog/kv API over HTTP
(the subset the syncer and discovery need). `FakeConsul` implements the
same Python surface in-process so tests (and consul-less deployments)
run without a consul binary; `FakeConsulServer` serves a `FakeConsul`
over real HTTP so `ConsulAPI`'s wire path is testable too.

Reference: the syncer talks to consul through the official Go client
(command/agent/consul/syncer.go:40-75); the HTTP surface mirrored here
is what that client hits.
"""

from __future__ import annotations

import json
import threading
import urllib.error
import urllib.parse
import urllib.request
from typing import Dict, List, Optional


class ConsulError(Exception):
    pass


class ConsulAPI:
    """Minimal Consul v1 HTTP client (agent services/checks, catalog,
    KV)."""

    def __init__(self, address: str = "127.0.0.1:8500", timeout: float = 5.0,
                 token: str = ""):
        if "://" not in address:
            address = "http://" + address
        self.base = address.rstrip("/")
        self.timeout = timeout
        self.token = token

    def _request(self, method: str, path: str, body: Optional[dict] = None,
                 params: Optional[Dict[str, str]] = None, raw: bool = False):
        url = self.base + path
        if params:
            url += "?" + urllib.parse.urlencode(params)
        data = None
        headers = {"Content-Type": "application/json"}
        if self.token:
            headers["X-Consul-Token"] = self.token
        if body is not None:
            data = json.dumps(body).encode()
        req = urllib.request.Request(url, data=data, method=method,
                                     headers=headers)
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                payload = resp.read()
        except urllib.error.HTTPError as e:
            raise ConsulError(f"consul {method} {path}: {e.code} "
                              f"{e.read().decode(errors='replace')}") from e
        except (urllib.error.URLError, OSError) as e:
            raise ConsulError(f"consul {method} {path}: {e}") from e
        if raw:
            return payload.decode(errors="replace")
        if not payload:
            return None
        try:
            return json.loads(payload)
        except ValueError:
            return payload.decode(errors="replace")

    # ----------------------------------------------------------- agent

    def self_info(self) -> dict:
        return self._request("GET", "/v1/agent/self") or {}

    def services(self) -> Dict[str, dict]:
        return self._request("GET", "/v1/agent/services") or {}

    def checks(self) -> Dict[str, dict]:
        return self._request("GET", "/v1/agent/checks") or {}

    def register_service(self, svc: dict) -> None:
        self._request("PUT", "/v1/agent/service/register", body=svc)

    def deregister_service(self, service_id: str) -> None:
        self._request("PUT", f"/v1/agent/service/deregister/{service_id}")

    def register_check(self, chk: dict) -> None:
        self._request("PUT", "/v1/agent/check/register", body=chk)

    def deregister_check(self, check_id: str) -> None:
        self._request("PUT", f"/v1/agent/check/deregister/{check_id}")

    def update_ttl(self, check_id: str, status: str, output: str = "") -> None:
        self._request("PUT", f"/v1/agent/check/update/{check_id}",
                      body={"Status": status, "Output": output})

    # --------------------------------------------------------- catalog

    def catalog_service(self, name: str, tag: str = "") -> List[dict]:
        params = {"tag": tag} if tag else None
        return self._request("GET", f"/v1/catalog/service/{name}",
                             params=params) or []

    # -------------------------------------------------------------- kv

    def kv_get(self, key: str) -> Optional[str]:
        try:
            # raw=True: the body is the stored value verbatim — parsing
            # it as JSON would rewrite values like "1.50" or "1e3".
            return self._request("GET", f"/v1/kv/{key}",
                                 params={"raw": "1"}, raw=True)
        except ConsulError:
            return None


class FakeConsul:
    """In-process stand-in with `ConsulAPI`'s surface.

    Registered services feed the catalog, TTL updates land in `checks`,
    and `set_kv` seeds the KV store — enough to exercise the syncer,
    discovery, and template KV paths without a consul agent.
    """

    def __init__(self, datacenter: str = "dc1", node_name: str = "fake-node"):
        self.datacenter = datacenter
        self.node_name = node_name
        self._services: Dict[str, dict] = {}
        self._checks: Dict[str, dict] = {}
        self._kv: Dict[str, str] = {}
        self._lock = threading.Lock()

    # ----------------------------------------------------------- agent

    def self_info(self) -> dict:
        return {
            "Config": {
                "Datacenter": self.datacenter,
                "NodeName": self.node_name,
                "Server": False,
                "Version": "0.7.0-fake",
                "Revision": "fake",
            }
        }

    def services(self) -> Dict[str, dict]:
        with self._lock:
            return {k: dict(v) for k, v in self._services.items()}

    def checks(self) -> Dict[str, dict]:
        with self._lock:
            return {k: dict(v) for k, v in self._checks.items()}

    def register_service(self, svc: dict) -> None:
        sid = svc.get("ID") or svc.get("Name", "")
        with self._lock:
            self._services[sid] = {
                "ID": sid,
                "Service": svc.get("Name", ""),
                "Tags": list(svc.get("Tags") or []),
                "Port": int(svc.get("Port") or 0),
                "Address": svc.get("Address", ""),
            }
            for chk in svc.get("Checks") or []:
                self._register_check_locked(chk, service_id=sid)

    def deregister_service(self, service_id: str) -> None:
        with self._lock:
            self._services.pop(service_id, None)
            for cid in [c for c, chk in self._checks.items()
                        if chk.get("ServiceID") == service_id]:
                self._checks.pop(cid, None)

    def _register_check_locked(self, chk: dict, service_id: str = "") -> None:
        cid = chk.get("ID") or chk.get("CheckID") or chk.get("Name", "")
        self._checks[cid] = {
            "CheckID": cid,
            "Name": chk.get("Name", ""),
            "Status": chk.get("Status") or "critical",
            "Output": "",
            "ServiceID": service_id or chk.get("ServiceID", ""),
            "Type": ("ttl" if chk.get("TTL") else
                     "http" if chk.get("HTTP") else
                     "tcp" if chk.get("TCP") else "unknown"),
        }

    def register_check(self, chk: dict) -> None:
        with self._lock:
            self._register_check_locked(chk)

    def deregister_check(self, check_id: str) -> None:
        with self._lock:
            self._checks.pop(check_id, None)

    def update_ttl(self, check_id: str, status: str, output: str = "") -> None:
        with self._lock:
            if check_id not in self._checks:
                raise ConsulError(f"unknown check {check_id}")
            self._checks[check_id]["Status"] = status
            self._checks[check_id]["Output"] = output

    # --------------------------------------------------------- catalog

    def catalog_service(self, name: str, tag: str = "") -> List[dict]:
        with self._lock:
            out = []
            for svc in self._services.values():
                if svc["Service"] != name:
                    continue
                if tag and tag not in svc["Tags"]:
                    continue
                out.append({
                    "Node": self.node_name,
                    "Address": svc["Address"] or "127.0.0.1",
                    "ServiceID": svc["ID"],
                    "ServiceName": svc["Service"],
                    "ServiceAddress": svc["Address"],
                    "ServicePort": svc["Port"],
                    "ServiceTags": svc["Tags"],
                })
            return out

    # -------------------------------------------------------------- kv

    def set_kv(self, key: str, value: str) -> None:
        with self._lock:
            self._kv[key] = value

    def kv_get(self, key: str) -> Optional[str]:
        with self._lock:
            return self._kv.get(key)


class FakeConsulServer:
    """Serves a `FakeConsul` over HTTP so `ConsulAPI`'s wire path can be
    tested end-to-end."""

    def __init__(self, fake: Optional[FakeConsul] = None):
        import http.server

        self.fake = fake or FakeConsul()
        outer = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def log_message(self, *args):  # quiet
                pass

            def _reply(self, obj, raw: Optional[str] = None):
                if raw is not None:
                    body = raw.encode()
                else:
                    body = json.dumps(obj).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _body(self) -> dict:
                n = int(self.headers.get("Content-Length") or 0)
                if not n:
                    return {}
                return json.loads(self.rfile.read(n))

            def do_GET(self):
                path, _, query = self.path.partition("?")
                params = dict(urllib.parse.parse_qsl(query))
                fake = outer.fake
                if path == "/v1/agent/self":
                    return self._reply(fake.self_info())
                if path == "/v1/agent/services":
                    return self._reply(fake.services())
                if path == "/v1/agent/checks":
                    return self._reply(fake.checks())
                if path.startswith("/v1/catalog/service/"):
                    name = path.rsplit("/", 1)[1]
                    return self._reply(
                        fake.catalog_service(name, params.get("tag", "")))
                if path.startswith("/v1/kv/"):
                    val = fake.kv_get(path[len("/v1/kv/"):])
                    if val is None:
                        self.send_error(404)
                        return
                    return self._reply(None, raw=val)
                self.send_error(404)

            def do_PUT(self):
                path = self.path.partition("?")[0]
                fake = outer.fake
                if path == "/v1/agent/service/register":
                    fake.register_service(self._body())
                    return self._reply(None, raw="")
                if path.startswith("/v1/agent/service/deregister/"):
                    fake.deregister_service(path.rsplit("/", 1)[1])
                    return self._reply(None, raw="")
                if path == "/v1/agent/check/register":
                    fake.register_check(self._body())
                    return self._reply(None, raw="")
                if path.startswith("/v1/agent/check/deregister/"):
                    fake.deregister_check(path.rsplit("/", 1)[1])
                    return self._reply(None, raw="")
                if path.startswith("/v1/agent/check/update/"):
                    body = self._body()
                    try:
                        fake.update_ttl(path.rsplit("/", 1)[1],
                                        body.get("Status", ""),
                                        body.get("Output", ""))
                    except ConsulError:
                        self.send_error(404)
                        return
                    return self._reply(None, raw="")
                self.send_error(404)

        import socketserver

        class Server(socketserver.ThreadingMixIn, http.server.HTTPServer):
            daemon_threads = True

        self._httpd = Server(("127.0.0.1", 0), Handler)
        self.addr = f"127.0.0.1:{self._httpd.server_address[1]}"
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="fake-consul")
        self._thread.start()

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
