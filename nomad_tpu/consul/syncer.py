"""Consul syncer: keeps agent + task services/checks registered.

Reference: command/agent/consul/syncer.go:1007 — services are grouped
by origin "domain" (agent, or one per running task), every id we own
carries the `_nomad-` prefix, and a periodic reconcile registers what
is desired and deregisters what is stale (so a restarted consul agent
recovers the full set). Script checks follow check.go: the syncer runs
the command locally on its interval and heartbeats a TTL check with the
exit status; http/tcp checks are registered consul-native so the consul
agent probes them itself.
"""

from __future__ import annotations

import hashlib
import logging
import subprocess
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional

NOMAD_PREFIX = "_nomad"
SYNC_INTERVAL = 5.0


def instance_prefix(instance: str) -> str:
    """Fixed-width hashed instance scope: no instance name can be a
    string prefix of another's scope (names like "web" vs "web-2" would
    collide if embedded raw), so reconcile can never reap across
    scopes."""
    iid = hashlib.sha1((instance or "default").encode()).hexdigest()[:8]
    return f"{NOMAD_PREFIX}-i{iid}-"


@dataclass
class ConsulCheck:
    name: str = ""
    type: str = ""  # http | tcp | script | ttl
    command: str = ""
    args: List[str] = field(default_factory=list)
    path: str = ""
    protocol: str = "http"
    port: int = 0
    interval: float = 10.0
    timeout: float = 5.0
    initial_status: str = ""


@dataclass
class ConsulService:
    name: str = ""
    tags: List[str] = field(default_factory=list)
    port: int = 0
    address: str = ""
    checks: List[ConsulCheck] = field(default_factory=list)

    def service_id(self, domain: str, instance: str = "") -> str:
        key = f"{domain}-{self.name}-{','.join(sorted(self.tags))}-{self.port}"
        digest = hashlib.sha1(key.encode()).hexdigest()[:12]
        return f"{instance_prefix(instance)}{domain}-{self.name}-{digest}"


class _ScriptCheckRunner:
    """Runs a script check on its interval, heartbeating the TTL check
    (check.go CheckRunner)."""

    def __init__(self, api, check_id: str, check: ConsulCheck, log):
        self.api = api
        self.check_id = check_id
        self.check = check
        self.log = log
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name=f"check-{check.name}")

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def _run(self) -> None:
        while not self._stop.wait(max(self.check.interval, 0.05)):
            cmd = [self.check.command] + list(self.check.args)
            try:
                proc = subprocess.run(
                    cmd, capture_output=True, text=True,
                    timeout=max(self.check.timeout, 0.1))
                output = (proc.stdout + proc.stderr)[-4096:]
                # Consul's script-check convention: 0 passing, 1 warning,
                # anything else critical.
                status = {0: "passing", 1: "warning"}.get(
                    proc.returncode, "critical")
            except subprocess.TimeoutExpired:
                status, output = "critical", "check timed out"
            except OSError as e:
                status, output = "critical", str(e)
            try:
                self.api.update_ttl(self.check_id, status, output)
            except Exception as e:  # noqa: BLE001 - consul flaps are soft
                self.log.debug("ttl update for %s failed: %s",
                               self.check_id, e)


class ConsulSyncer:
    """Reconciles desired services/checks against the consul agent."""

    def __init__(self, api, sync_interval: float = SYNC_INTERVAL,
                 address: str = "", instance: str = ""):
        self.api = api
        self.address = address
        # Identity baked into every id we register: reconcile only reaps
        # THIS agent's stale services (e.g. left by a crashed previous
        # run), never another nomad agent's. The reference gets the same
        # isolation from consul-agent locality — each syncer talks to
        # the consul agent on its own node.
        self.instance = instance
        self.sync_interval = sync_interval
        self.logger = logging.getLogger("nomad_tpu.consul.syncer")
        self._desired: Dict[str, Dict[str, dict]] = {}  # domain -> id -> payload
        # domain -> check id -> def (script checks we execute ourselves)
        self._script_checks: Dict[str, Dict[str, ConsulCheck]] = {}
        self._runners: Dict[str, _ScriptCheckRunner] = {}
        self._registered: Dict[str, dict] = {}  # what we believe consul has
        self._lock = threading.Lock()
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------- desired set

    def set_services(self, domain: str, services: List[ConsulService]) -> None:
        """Replace the desired services for one domain; next sync applies
        the diff (syncer.go SetServices)."""
        payloads: Dict[str, dict] = {}
        scripts: Dict[str, ConsulCheck] = {}
        for svc in services:
            sid = svc.service_id(domain, self.instance)
            checks = []
            for i, chk in enumerate(svc.checks):
                cid = f"{sid}-chk{i}"
                base = {"ID": cid, "Name": chk.name or f"service:{svc.name}",
                        "ServiceID": sid}
                if chk.initial_status:
                    base["Status"] = chk.initial_status
                if chk.type == "http":
                    target = svc.address or "127.0.0.1"
                    port = chk.port or svc.port
                    base["HTTP"] = (f"{chk.protocol or 'http'}://{target}:"
                                    f"{port}{chk.path or '/'}")
                    base["Interval"] = f"{chk.interval:g}s"
                    base["Timeout"] = f"{chk.timeout:g}s"
                elif chk.type == "tcp":
                    target = svc.address or "127.0.0.1"
                    base["TCP"] = f"{target}:{chk.port or svc.port}"
                    base["Interval"] = f"{chk.interval:g}s"
                    base["Timeout"] = f"{chk.timeout:g}s"
                else:  # script and explicit ttl checks heartbeat a TTL
                    base["TTL"] = f"{max(chk.interval, 0.1) * 3:g}s"
                    if chk.type == "script":
                        scripts[cid] = chk
                checks.append(base)
            payloads[sid] = {
                "ID": sid,
                "Name": svc.name,
                "Tags": list(svc.tags),
                "Port": svc.port,
                "Address": svc.address,
                "Checks": checks,
            }
        with self._lock:
            if payloads:
                self._desired[domain] = payloads
                self._script_checks[domain] = scripts
            else:
                self._desired.pop(domain, None)
                self._script_checks.pop(domain, None)
            # Drop script runners for checks no longer desired anywhere.
            live = {cid for dom in self._script_checks.values() for cid in dom}
            for cid, runner in list(self._runners.items()):
                if cid not in live:
                    runner.stop()
                    del self._runners[cid]
        self._wake.set()

    def remove_services(self, domain: str) -> None:
        self.set_services(domain, [])

    # ------------------------------------------------------------- loop

    def start(self) -> None:
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, daemon=True, name="consul-syncer")
            self._thread.start()

    def shutdown(self) -> None:
        self._stop.set()
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=3.0)
        with self._lock:
            for runner in self._runners.values():
                runner.stop()
            self._runners.clear()
            registered = list(self._registered)
            self._registered.clear()
        # Best-effort dereg of everything we own (syncer.go Shutdown).
        for sid in registered:
            try:
                self.api.deregister_service(sid)
            except Exception:  # noqa: BLE001
                pass

    def _run(self) -> None:
        while not self._stop.is_set():
            self._wake.wait(timeout=self.sync_interval)
            self._wake.clear()
            if self._stop.is_set():
                return
            try:
                self.sync()
            except Exception as e:  # noqa: BLE001 - consul down is soft
                self.logger.debug("consul sync failed: %s", e)

    # ------------------------------------------------------------- sync

    def sync(self) -> None:
        """One reconcile pass: register missing/changed, deregister
        stale `_nomad-` services (syncer.go syncServices/syncChecks)."""
        with self._lock:
            desired: Dict[str, dict] = {}
            for dom in self._desired.values():
                desired.update(dom)
            scripts: Dict[str, ConsulCheck] = {}
            for dom_scripts in self._script_checks.values():
                scripts.update(dom_scripts)

        have = self.api.services()
        # Register anything missing or drifted.
        for sid, payload in desired.items():
            cur = have.get(sid)
            drifted = (cur is None
                       or cur.get("Port") != payload["Port"]
                       or cur.get("Address", "") != payload["Address"]
                       or sorted(cur.get("Tags") or []) != sorted(payload["Tags"]))
            if drifted:
                self.api.register_service(payload)
            with self._lock:
                self._registered[sid] = payload
        # Deregister OUR stale services (matching instance scope) that
        # nobody wants anymore; other agents' registrations survive.
        prefix = instance_prefix(self.instance)
        for sid in have:
            if sid.startswith(prefix) and sid not in desired:
                self.api.deregister_service(sid)
                with self._lock:
                    self._registered.pop(sid, None)
        # Start runners for script checks now that their TTL checks exist.
        with self._lock:
            for cid, chk in scripts.items():
                if cid not in self._runners:
                    runner = _ScriptCheckRunner(self.api, cid, chk, self.logger)
                    self._runners[cid] = runner
                    runner.start()


# --------------------------------------------------------------- helpers


def task_services(alloc, task, env: Optional[Dict[str, str]] = None
                  ) -> List[ConsulService]:
    """Build the consul services a running task advertises, resolving
    port labels against the alloc's assigned networks (the reference
    maps Service.PortLabel through the task's NetworkResource) and
    interpolating ${NOMAD_*} in names/tags (syncer.go uses the task
    env the same way). Pass the task's real env when available (the
    client does); the fallback env has empty dir paths."""
    from ..client.env import build_task_env
    from ..utils.interpolate import replace_env

    res = (alloc.task_resources or {}).get(task.name)
    labels: Dict[str, int] = {}
    address = ""
    for net in (res.networks if res is not None else []) or []:
        labels.update(net.port_labels())
        address = address or net.ip
    if env is None:
        env = build_task_env(alloc, task, "", "", "")
    out = []
    for svc in task.services or []:
        port = labels.get(svc.port_label, 0)
        checks = [
            ConsulCheck(
                name=c.name, type=c.type, command=c.command,
                args=list(c.args), path=c.path, protocol=c.protocol,
                port=labels.get(c.port_label, port),
                interval=c.interval or 10.0, timeout=c.timeout or 5.0,
                initial_status=c.initial_status,
            )
            for c in svc.checks or []
        ]
        out.append(ConsulService(
            name=replace_env(svc.name, env),
            tags=[replace_env(t, env) for t in svc.tags],
            port=port, address=address, checks=checks,
        ))
    return out


def serf_bootstrap(server, api, service: str = "nomad", tag: str = "serf",
                   interval: float = 15.0, stop=None,
                   self_addr: str = "") -> None:
    """Keep joining gossip peers discovered in the consul catalog until
    the server has peers (server.go:398 setupBootstrapHandler: a server
    that knows nobody bootstraps through consul). The server's own
    catalog entry is filtered out (the reference does the same), so a
    standalone server idles on catalog polls instead of self-joining.
    Runs in the caller's thread; pass a threading.Event as `stop` to
    end it."""
    import time as _time

    while stop is None or not stop.is_set():
        try:
            if len(server.serf_members()) > 1:
                return  # we have peers; gossip takes it from here
            addrs = [a for a in discover_servers(api, service=service, tag=tag)
                     if a != self_addr]
            if addrs:
                server.serf_join(addrs)
                # A join to a stale entry can still "succeed"; only a
                # real peer in the member list ends the bootstrap.
                if len(server.serf_members()) > 1:
                    return
        except Exception:  # noqa: BLE001 - consul down is soft; retry
            pass
        if stop is not None:
            if stop.wait(interval):
                return
        else:
            _time.sleep(interval)


def discover_servers(api, service: str = "nomad",
                     tag: str = "http") -> List[str]:
    """Find nomad servers through the consul catalog
    (client.go:1762 consulDiscovery)."""
    out = []
    for entry in api.catalog_service(service, tag=tag):
        addr = entry.get("ServiceAddress") or entry.get("Address") or ""
        port = entry.get("ServicePort") or 0
        if addr and port:
            out.append(f"{addr}:{port}")
    return out
