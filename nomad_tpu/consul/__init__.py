"""Consul integration: agent API client, service/check syncer, and
server discovery.

Reference: command/agent/consul/syncer.go (service + check registration
and periodic reconcile), command/agent/consul/check.go (script-check
runner heartbeating TTL checks), client/client.go:1762 consulDiscovery
(server bootstrap through the consul catalog).
"""

from .api import ConsulAPI, FakeConsul, FakeConsulServer
from .syncer import (
    ConsulCheck,
    ConsulService,
    ConsulSyncer,
    discover_servers,
    serf_bootstrap,
    task_services,
)

__all__ = [
    "ConsulAPI",
    "FakeConsul",
    "FakeConsulServer",
    "ConsulCheck",
    "ConsulService",
    "ConsulSyncer",
    "discover_servers",
    "serf_bootstrap",
    "task_services",
]
