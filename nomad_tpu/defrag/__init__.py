"""Continuous cluster defragmentation: the leader-side optimizer loop.

The closed loop ROADMAP item 3 asked for, stitched from prior
subsystems rather than invented next to them:

- the **solve** is kernels/convex.py's mirror-descent program run
  GLOBALLY over the device-resident node state (defrag/solver.py),
  warm-started from the previous round's iterate so steady-state
  rounds cost a few gradient steps (CvxCluster's re-solve insight,
  PAPERS.md);
- the **moves** commit through PR 9's churn machinery: the loop claims
  `MigrationGovernor` slots for each wave (so defrag disruption counts
  against — and is capped by — `migrate_max_parallel`, visible in the
  same high-water mark as drain storms), mints per-job
  ``triggered_by=defrag-migration`` evals through the server's raft
  eval funnel, and the generic scheduler stages the marked allocs as
  ordinary budget-exempt migrations: an applier-verified eviction leg
  plus a replacement placement in ONE plan, every displaced alloc
  getting its exactly-once raft-funnel terminal;
- the **gate** is PR 5's admission signal: the loop only optimizes a
  green cluster, backs off at yellow/red (an optimizer must never
  compete with overload), pauses on leadership loss, and discards any
  wave whose solve raced a resident-base rejection purge
  (models/matrix.py base_epoch — chaos site ``defrag.solve_stale``).

One wave is in flight at a time: the loop watches its evals to their
terminal status and releases the governor slots as each lands (chaos
site ``defrag.wave_lost`` forces the dead-wave path: slots released,
nothing leaks). Surfaces: ``server.stats()["defrag"]``,
``/v1/metrics`` ``defrag.*`` gauges, the ``defrag.solve`` trace stage,
and the ``defrag_*`` knobs (ServerConfig + agent HCL + CLI).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, List, Optional

from .solver import (  # noqa: F401 (re-exported rig surface)
    COLD_ITERS,
    MAX_SOLVE_ALLOCS,
    WARM_ITERS,
    DefragPlan,
    Move,
    WarmState,
    cluster_fragmentation,
    compute_defrag_plan,
    frag_score,
    reference_asks,
    solve_cache_size,
)

# How long a wave may stay in flight before the loop declares it dead
# and reclaims its governor slots (a crashed scheduler or a flushed
# broker can orphan a wave's evals; their redelivery/terminal path is
# exactly-once regardless — this bounds only the loop's OWN claim).
WAVE_TIMEOUT = 60.0
# Loop tick: the wait slice between wake-ups (leadership, wave watch,
# and the interval clock are all checked per tick; the tick is NOT the
# optimization cadence — defrag_interval is).
TICK = 0.1
# Pressure backoff multiplier: a yellow/red tick pushes the next round
# out by this many intervals (red compounds per consecutive skip up to
# MAX_BACKOFF intervals).
PRESSURE_BACKOFF = 2.0
MAX_BACKOFF = 8.0


def build_wave_evals(state, moves: List[Move]) -> List:
    """Per-job defrag evals for one wave's move set. Jobs deregistered
    since the solve snapshot drop out (their allocs are dying anyway);
    the eval carries the marked alloc ids and the solver's target per
    alloc (a preference, not a mandate — scheduler/generic.py)."""
    from ..structs import Evaluation, consts
    from ..utils.ids import generate_uuid

    by_job: Dict[str, List[Move]] = {}
    for mv in moves:
        by_job.setdefault(mv.job_id, []).append(mv)
    evals = []
    # Markers void themselves when the loop's wave claim does: an eval
    # surfacing after WAVE_TIMEOUT (backed-up broker, leadership move)
    # would otherwise stage budget-EXEMPT evictions against governor
    # slots nobody holds anymore — silently exceeding
    # migrate_max_parallel exactly when the cluster is struggling.
    expires = time.time() + WAVE_TIMEOUT
    for job_id in sorted(by_job):
        job = state.job_by_id(job_id)
        if job is None or getattr(job, "stop", False):
            continue
        job_moves = by_job[job_id]
        evals.append(Evaluation(
            id=generate_uuid(),
            priority=job.priority,
            type=job.type,
            triggered_by=consts.EVAL_TRIGGER_DEFRAG,
            job_id=job_id,
            job_modify_index=job.job_modify_index,
            status=consts.EVAL_STATUS_PENDING,
            trace_id=generate_uuid(),
            defrag_alloc_ids=[mv.alloc_id for mv in job_moves],
            defrag_targets={mv.alloc_id: mv.to_node
                            for mv in job_moves},
            defrag_wave_expires=expires,
        ))
    return evals


class DefragLoop:
    """The background optimizer thread. Constructed unconditionally by
    the Server (stats surface), started with it; actually optimizes
    only while ``defrag_enabled`` AND this server holds leadership AND
    the admission monitor reads green."""

    def __init__(self, server):
        self.server = server
        self.logger = logging.getLogger("nomad_tpu.defrag")
        cfg = server.config
        self._lock = threading.Lock()
        self.enabled = bool(cfg.defrag_enabled)  # guarded-by: _lock
        self.interval = float(cfg.defrag_interval)  # guarded-by: _lock
        self.min_gain = float(cfg.defrag_min_gain)  # guarded-by: _lock
        self.max_moves = int(cfg.defrag_max_moves_per_wave)  # guarded-by: _lock
        self._warm = WarmState()  # solver-iterate carry (loop thread only)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # In-flight wave: eval id -> governor slots it holds.
        self._wave: Dict[str, int] = {}  # guarded-by: _lock
        self._wave_started = 0.0  # guarded-by: _lock
        self._next_round = 0.0  # guarded-by: _lock (monotonic deadline)
        self._backoff = 1.0  # guarded-by: _lock (pressure compounding)
        # Counters (guarded-by: _lock).
        self.rounds = 0
        self.waves = 0
        self.waves_lost = 0
        self.moves_proposed = 0
        self.moves_completed = 0  # wave evals reaching terminal (slots)
        self.no_gain_rounds = 0
        self.pressure_skips = 0
        self.budget_skips = 0
        self.stale_discards = 0
        self.cold_solves = 0
        self.warm_solves = 0
        self.last_gain = 0.0
        self.last_frag = 0.0
        self.last_movable = 0
        self.last_solve_ms = 0.0
        self.last_cold_solve_ms = 0.0
        self.last_warm_solve_ms = 0.0
        # Acceptance pair for "warm is measurably cheaper than cold":
        # the FIRST cold solve (paying compile + the full iteration
        # budget) vs the cheapest warm steady-state solve. last_* can
        # invert on noise (a late cold solve reuses the compiled
        # program; the first warm solve pays the warm program's own
        # compile).
        self.first_cold_solve_ms = 0.0
        self.min_warm_solve_ms = 0.0

    # ---------------------------------------------------------- config

    def configure(self, enabled: Optional[bool] = None,
                  interval: Optional[float] = None,
                  min_gain: Optional[float] = None,
                  max_moves: Optional[int] = None) -> None:
        with self._lock:
            if enabled is not None:
                self.enabled = bool(enabled)
            if interval is not None:
                self.interval = float(interval)
            if min_gain is not None:
                self.min_gain = float(min_gain)
            if max_moves is not None:
                self.max_moves = int(max_moves)

    # ------------------------------------------------------- lifecycle

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        t = threading.Thread(target=self._run, name="defrag-loop",
                             daemon=True)
        self._thread = t
        t.start()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
            self._thread = None
        self._abandon_wave("shutdown")

    def _run(self) -> None:
        while not self._stop.wait(TICK):
            try:
                self.tick()
            except Exception:  # noqa: BLE001 - the loop must survive
                self.logger.exception("defrag tick failed")

    # ------------------------------------------------------------ tick

    def tick(self, now: Optional[float] = None) -> None:
        """One scheduling decision: watch the in-flight wave, then run
        a round if the interval elapsed on a green, led cluster.
        Public (and monotonic-clock injectable) so tests and the bench
        rig can drive the loop synchronously."""
        now = time.monotonic() if now is None else now
        with self._lock:
            enabled = self.enabled
        if not self.server.is_leader():
            # Leadership loss pauses the loop AND abandons the wave:
            # the new leader re-derives its own (our wave's evals keep
            # their exactly-once path on whichever leader serves them,
            # but the slots are THIS process's claim to return).
            self._abandon_wave("leadership-lost")
            return
        # The wave clock is real monotonic time regardless of an
        # injected `now` (tests inject the ROUND clock; _wave_started
        # is always stamped from time.monotonic()).
        self._watch_wave(time.monotonic())
        if not enabled:
            return
        with self._lock:
            if self._wave:  # one wave at a time
                return
            if now < self._next_round:
                return
            interval = self.interval
        level = "green"
        try:
            level = self.server.admission.level()
        except Exception:  # noqa: BLE001 - a broken probe = don't optimize
            self.logger.exception("defrag pressure probe failed")
            level = "red"
        if level != "green":
            # An optimizer must never compete with overload: back off,
            # compounding x2 per consecutive skip (yellow AND red — a
            # yellow cluster is still one the optimizer should yield
            # to) up to MAX_BACKOFF intervals; a green round resets.
            with self._lock:
                self.pressure_skips += 1
                self._backoff = min(self._backoff * PRESSURE_BACKOFF,
                                    MAX_BACKOFF)
                self._next_round = now + interval * self._backoff
            return
        with self._lock:
            self._backoff = 1.0
            self._next_round = now + interval
        self.run_round()

    # ----------------------------------------------------------- round

    def run_round(self) -> Optional[DefragPlan]:
        """One solve->diff->wave round against the current snapshot.
        Returns the solver plan (None only if the server has no state
        yet). Public for the bench rig and tests."""
        from .. import trace
        from ..chaos import chaos
        from ..models.matrix import base_epoch
        from ..structs import consts
        from ..utils.ids import generate_uuid

        state = self.server.fsm.state.snapshot()
        dcs = sorted({n.datacenter for n in state.nodes()})
        if not dcs:
            return None
        with self._lock:
            min_gain = self.min_gain
            max_moves = self.max_moves
        epoch0 = base_epoch()
        t0 = time.monotonic()
        plan = compute_defrag_plan(
            state, dcs, max_moves=max_moves, min_gain=min_gain,
            warm=self._warm)
        round_id = f"defrag-{generate_uuid()[:8]}"
        trace.record_span(
            round_id, trace.STAGE_DEFRAG_SOLVE, t0,
            ann={"movable": plan.movable, "moves": len(plan.moves),
                 "gain": round(plan.gain, 4), "warm": plan.warm,
                 "solve_ms": round(plan.solve_ms, 3)})
        trace.complete(round_id)
        with self._lock:
            self.rounds += 1
            self.last_solve_ms = plan.solve_ms
            self.last_gain = plan.gain
            self.last_frag = plan.frag_after
            self.last_movable = plan.movable
            if not plan.movable:
                # No movable set = no solve ran: counting the early
                # return as a "cold solve" would poison the warm-vs-
                # cold acceptance pair with sub-ms non-solves (seen on
                # the first live-agent rounds before any placement).
                pass
            elif plan.warm:
                self.warm_solves += 1
                self.last_warm_solve_ms = plan.solve_ms
                if (self.min_warm_solve_ms == 0.0
                        or plan.solve_ms < self.min_warm_solve_ms):
                    self.min_warm_solve_ms = plan.solve_ms
            else:
                self.cold_solves += 1
                self.last_cold_solve_ms = plan.solve_ms
                if self.first_cold_solve_ms == 0.0:
                    self.first_cold_solve_ms = plan.solve_ms

        # Staleness: a plan-apply rejection purged the resident base
        # chain while we solved — whatever this wave derived from is
        # suspect. Discard it (and the warm carry: it extends the same
        # convicted chain); the next round re-anchors from a clean
        # rebuild. The chaos site forces this path deterministically.
        stale = base_epoch() != epoch0
        if chaos.enabled and chaos.fire("defrag.solve_stale") == "drop":
            stale = True
        if stale:
            with self._lock:
                self.stale_discards += 1
            self._warm.clear()
            return plan

        if not plan.moves:
            with self._lock:
                self.no_gain_rounds += 1
            return plan

        # Wave budget: claim governor slots UP FRONT (the scheduler
        # treats defrag-marked migrations as pre-claimed), so defrag
        # disruption shares migrate_max_parallel with drain storms —
        # one cap, one high-water mark.
        from ..migrate import get_governor

        governor = get_governor()
        granted = governor.acquire(len(plan.moves))
        if granted == 0:
            with self._lock:
                self.budget_skips += 1
            return plan
        moves = plan.moves[:granted]
        evals = build_wave_evals(state, moves)
        if not evals:
            governor.release(granted)
            return plan
        # Slots per eval = its move count; any clamp remainder rides on
        # the first eval so every granted slot has an owner to release.
        per_eval = {ev.id: len(ev.defrag_alloc_ids) for ev in evals}
        slack = granted - sum(per_eval.values())
        if slack > 0:
            per_eval[evals[0].id] += slack
        try:
            self.server.eval_update(evals)
        except Exception:  # noqa: BLE001 - leader flap mid-wave
            self.logger.exception("defrag wave submit failed")
            governor.release(granted)
            return plan
        with self._lock:
            self._wave = per_eval
            self._wave_started = time.monotonic()
            self.waves += 1
            self.moves_proposed += sum(
                len(ev.defrag_alloc_ids) for ev in evals)
        self.logger.info(
            "defrag wave: %d moves across %d jobs (gain %.4f, frag "
            "%.4f -> %.4f)", len(moves), len(evals), plan.gain,
            plan.frag_before, plan.frag_after)
        return plan

    # ------------------------------------------------------ wave watch

    def _watch_wave(self, now: float) -> None:
        from ..chaos import chaos

        with self._lock:
            if not self._wave:
                return
            started = self._wave_started
            pending = dict(self._wave)
        if chaos.enabled and chaos.fire("defrag.wave_lost") == "drop":
            # Forced dead-wave: release every remaining slot NOW. The
            # wave's evals keep their own exactly-once terminal path —
            # only the loop's claim is reclaimed.
            self._abandon_wave("chaos")
            return
        if now - started > WAVE_TIMEOUT:
            self._abandon_wave("timeout")
            return
        state = self.server.fsm.state
        from ..migrate import get_governor

        done: List[str] = []
        for eval_id in pending:
            ev = state.eval_by_id(eval_id)
            if ev is None or ev.terminal_status():
                done.append(eval_id)
        if not done:
            return
        released = 0
        with self._lock:
            for eval_id in done:
                released += self._wave.pop(eval_id, 0)
            self.moves_completed += released
            wave_done = not self._wave
        if released:
            get_governor().release(released)
        if wave_done:
            self.logger.debug("defrag wave settled (%d slots)", released)

    def _abandon_wave(self, reason: str) -> None:
        with self._lock:
            if not self._wave:
                return
            slots = sum(self._wave.values())
            self._wave = {}
            self.waves_lost += 1
        from ..migrate import get_governor

        get_governor().release(slots)
        self.logger.warning(
            "defrag wave abandoned (%s): released %d slots", reason, slots)

    # ----------------------------------------------------------- stats

    def reset_stats(self) -> None:
        """Re-baseline counters (bench windows) without touching the
        in-flight wave or the warm carry."""
        with self._lock:
            self.rounds = self.waves = self.waves_lost = 0
            self.moves_proposed = self.moves_completed = 0
            self.no_gain_rounds = self.pressure_skips = 0
            self.budget_skips = self.stale_discards = 0
            self.cold_solves = self.warm_solves = 0
            self.first_cold_solve_ms = 0.0
            self.min_warm_solve_ms = 0.0

    def stats(self) -> Dict[str, object]:
        with self._lock:
            return {
                "enabled": self.enabled,
                "interval": self.interval,
                "min_gain": self.min_gain,
                "max_moves_per_wave": self.max_moves,
                "rounds": self.rounds,
                "waves": self.waves,
                "waves_lost": self.waves_lost,
                "wave_in_flight": sum(self._wave.values()),
                "moves_proposed": self.moves_proposed,
                "moves_completed": self.moves_completed,
                "no_gain_rounds": self.no_gain_rounds,
                "pressure_skips": self.pressure_skips,
                "budget_skips": self.budget_skips,
                "stale_discards": self.stale_discards,
                "cold_solves": self.cold_solves,
                "warm_solves": self.warm_solves,
                "last_gain": round(self.last_gain, 6),
                "last_fragmentation": round(self.last_frag, 6),
                "last_movable": self.last_movable,
                "last_solve_ms": round(self.last_solve_ms, 3),
                "last_cold_solve_ms": round(self.last_cold_solve_ms, 3),
                "last_warm_solve_ms": round(self.last_warm_solve_ms, 3),
                "first_cold_solve_ms": round(self.first_cold_solve_ms, 3),
                "min_warm_solve_ms": round(self.min_warm_solve_ms, 3),
                "solve_programs": solve_cache_size(),
            }


__all__ = [
    "COLD_ITERS",
    "MAX_SOLVE_ALLOCS",
    "WARM_ITERS",
    "WAVE_TIMEOUT",
    "DefragLoop",
    "DefragPlan",
    "Move",
    "WarmState",
    "build_wave_evals",
    "cluster_fragmentation",
    "compute_defrag_plan",
    "frag_score",
    "reference_asks",
    "solve_cache_size",
]
