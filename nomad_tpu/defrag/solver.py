"""The defrag loop's relaxed global re-placement solve.

BENCH_r11's verdict on the convex kernel was "better placements,
too slow for the latency path" — so this module runs the SAME
mirror-descent program (kernels/convex.py mirror_descent) off the hot
path, over the WHOLE cluster instead of one eval's asks: every movable
allocation becomes a row of the relaxed assignment x [K, N], solved
against the residual (movable-set-removed) load of the device-resident
node matrix. CvxCluster (PAPERS.md) gets its re-solve speedups by
exploiting problem structure ACROSS solves; here that is the
**warm start**: the previous round's final logits (the mirror-descent
iterate — entropic duals up to the softmax) are carried per alloc id,
keyed on the resident base family signature, so a steady-state round
pays WARM_ITERS (a handful) of closed-form gradient steps instead of a
cold solve. The two programs (cold/warm iteration counts are
compile-time constants) compile once per (K bucket, N) shape and then
never again — steady-state ``jit_recompiles`` stays 0, the same
contract as the placement kernels (the solve is registered in
ops/binpack.py's jit accounting).

Move extraction is host-side and deliberately conservative: the
rounded solution is diffed against current placements, candidate moves
are re-simulated one at a time against a copy of the utilization
matrix, and only moves that STRICTLY reduce the cluster fragmentation
score (kernels/quality.py quality_from_arrays — the Tesserae axis the
scoreboard already measures) survive, best-gain-first, up to the wave
cap. Validity is not this module's job at all: a move is only ever a
*preference* on a defrag eval (structs/eval.py defrag_targets), and
the replacement placement runs the scheduler's full feasibility stack
downstream.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

# Cold-start iteration count: a from-scratch solve of the global
# program (K movable allocs is a much wider problem than one eval's
# asks, and the logits start diffuse).
COLD_ITERS = 24
# Warm-start iteration count: with the previous round's logits carried
# per alloc, steady state only has to absorb the delta the churn since
# last round introduced.
WARM_ITERS = 5
# A round whose carried rows cover less than this fraction of the
# movable set solves cold (mass churn: the carried iterate is mostly
# noise, and a cold solve converges where a warm one would wander).
WARM_MIN_CARRY = 0.5
# Movable-set cap: the solve is O(K*N) per iteration; past the cap the
# round keeps the allocs on the LEAST-filled occupied nodes (the
# consolidation candidates — a full node's allocs have nowhere better
# to be) and leaves the rest for later rounds.
MAX_SOLVE_ALLOCS = 512
# K-axis shape buckets (pad-to-bucket like the ask axis of the
# placement path) so steady-state churn in the movable count reuses
# one compiled program per bucket.
K_BUCKETS = [16, 32, 64, 128, 256, MAX_SOLVE_ALLOCS]
# Registered sizer for ntalint's `unbucketed-shape` rule (_k_bucket is
# also sanctioned structurally — it returns a bucket_size call — but
# the manifest keeps the sanction explicit; see models/topology.py).
NTA_BUCKET_FNS = ("_k_bucket",)
# Class-compressed solve (models/classes.py): past this fleet size,
# when the signature interning compresses at least this much, the
# relaxed program runs over x[K, C] instead of x[K, N] and expands
# back to nodes at the rounding step. Below the thresholds the exact
# node-granular solve is already cheap — small fleets (and tier-1
# tests) keep the uncompressed path bit-for-bit.
CLASS_COMPRESS_MIN_NODES = 2048
CLASS_COMPRESS_MIN_RATIO = 2.0


@dataclass
class Move:
    """One accepted defrag move: alloc -> target node, with the
    fragmentation delta its acceptance measured."""

    alloc_id: str
    job_id: str
    from_node: str
    to_node: str
    gain: float


@dataclass
class DefragPlan:
    """One round's outcome: the accepted move set + solve telemetry."""

    moves: List[Move] = field(default_factory=list)
    frag_before: float = 0.0
    frag_after: float = 0.0
    gain: float = 0.0
    movable: int = 0
    candidates: int = 0
    k: int = 0
    n: int = 0
    warm: bool = False
    carried: int = 0
    solve_ms: float = 0.0
    # Class-compression telemetry (models/classes.py): whether this
    # round solved over classes, and at what N/C ratio.
    compressed: bool = False
    classes: int = 0
    compression_ratio: float = 0.0


class WarmState:
    """Per-alloc carry of the previous round's solver iterate, keyed
    on the resident base family signature + problem shape — the
    node-set identity that keys the batcher's delta chain. A key
    mismatch (node register/deregister, K bucket move) drops the
    carry: those are exactly the transitions where the old iterate
    describes a different program."""

    def __init__(self):
        self.key: Optional[Tuple] = None
        self.logits: Dict[str, np.ndarray] = {}

    def take(self, key: Tuple) -> Dict[str, np.ndarray]:
        if key != self.key:
            self.key = key
            self.logits = {}
        return self.logits

    def store(self, key: Tuple, logits: Dict[str, np.ndarray]) -> None:
        self.key = key
        self.logits = logits

    def clear(self) -> None:
        self.key = None
        self.logits = {}


# Distinct reference asks the fragmentation objective scores against
# (frequency-weighted, most-common first): a single median ask is
# blind to a mixed workload — free space that fits the small ask but
# strands the big one (or vice versa) must move the score.
MAX_REF_ASKS = 4


def reference_asks(ask_res) -> List[Tuple[np.ndarray, float]]:
    """[(ask [R], weight)] over the movable set's distinct resource
    shapes, weight = frequency share, top MAX_REF_ASKS shapes."""
    ask_res = np.asarray(ask_res, np.float64)
    if not len(ask_res):
        return []
    shapes, counts = np.unique(ask_res, axis=0, return_counts=True)
    top = np.argsort(-counts)[:MAX_REF_ASKS]
    total = float(counts[top].sum())
    return [(shapes[i], counts[i] / total) for i in top]


def frag_score(util, capacity, node_ok, refs) -> float:
    """The defrag objective: frequency-weighted mean of the quality
    scoreboard's fragmentation over the workload's reference asks.
    One number both the solver's move acceptance and the bench
    trajectory read (cluster_fragmentation), so the loop can never
    'improve' a score nobody measures."""
    from ..kernels.quality import quality_from_arrays

    if not refs:
        return 0.0
    return float(sum(
        w * quality_from_arrays(util, capacity, node_ok,
                                ask)["fragmentation"]
        for ask, w in refs))


def cluster_fragmentation(state, datacenters) -> float:
    """Measure the current cluster's defrag objective from a snapshot:
    the same resolve + movable-set + frag_score path the solver runs,
    without solving. The bench --defrag-ab trajectory samples THIS for
    both arms."""
    from ..models.matrix import (
        _alloc_usage,
        resolve_cluster_base,
        universe_nodes_cached,
    )

    base, _kind = resolve_cluster_base(state, datacenters)
    nodes, _by_dc, _usig = universe_nodes_cached(state, datacenters)
    row_of = {node.id: i for i, node in enumerate(nodes)}
    movable = movable_allocs(state, row_of, base.node_ok)
    if not movable:
        return 0.0
    refs = reference_asks(np.array(
        [_alloc_usage(a)[:4] for a in movable], np.float64))
    return frag_score(base.util, base.capacity,
                      np.asarray(base.node_ok, bool), refs)


_SOLVE_JIT = None


def _solve_jit():
    """The jitted global-relaxation program (lazy: jax imports only
    when a solve actually runs). Static over `iters`, so exactly two
    programs exist per (K bucket, N) shape — cold and warm."""
    global _SOLVE_JIT
    if _SOLVE_JIT is None:
        import functools

        import jax
        import jax.numpy as jnp

        from ..kernels.convex import NEG_INF, mirror_descent
        from ..ops.binpack import NUM_RESOURCES

        @functools.partial(jax.jit, static_argnames=("iters",))
        def solve(logits0, fresh, base_util, capacity, sched_capacity,
                  node_ok, bw_avail, bw_used, ports_free,
                  ask_res, ask_bw, ask_ports, active, iters):
            denom_nr = jnp.maximum(sched_capacity, 1.0)  # [N, R]
            base_frac = base_util / denom_nr
            # util includes the node's reserved slice (matrix.py
            # _fill_static), so headroom is against RAW capacity —
            # the same accounting convex.py's initial-state mask uses.
            headroom = capacity - base_util
            feas = node_ok[None, :] & (capacity[None, :, 0] > 0)
            for r in range(NUM_RESOURCES):
                feas &= ask_res[:, r][:, None] <= headroom[None, :, r]
            feas &= ask_bw[:, None] <= (bw_avail - bw_used)[None, :]
            feas &= ask_ports[:, None] <= ports_free[None, :]
            mask = jnp.where(feas, 0.0, NEG_INF)

            # BestFit affinity at the residual state — the same
            # fitness shape the convex kernel scores with, so the
            # global solve and the per-eval kernel pull the same way.
            free_cpu = 1.0 - (base_util[None, :, 0]
                              + ask_res[:, None, 0]) / denom_nr[None, :, 0]
            free_mem = 1.0 - (base_util[None, :, 1]
                              + ask_res[:, None, 1]) / denom_nr[None, :, 1]
            fitness = jnp.clip(
                20.0 - (jnp.power(10.0, free_cpu)
                        + jnp.power(10.0, free_mem)), 0.0, 18.0)
            fitness = jnp.where(
                (sched_capacity[None, :, 0] <= 0)
                | (sched_capacity[None, :, 1] <= 0), 0.0, fitness)
            lin = jnp.where(feas, fitness, 0.0)

            active_col = active.astype(jnp.float32)[:, None]
            res_active = ask_res * active_col
            bw_active = ask_bw * active_col[:, 0]
            ports_active = ask_ports * active_col[:, 0]
            bw_denom = jnp.maximum(bw_avail, 1.0)
            base_bw_frac = bw_used / bw_denom
            ports_denom = jnp.maximum(ports_free, 1.0)

            # Warm start: carried rows resume from their previous
            # iterate; fresh rows (new allocs, first round) start at
            # the objective's own linear term like the cold path.
            logits = jnp.where(fresh[:, None], lin, logits0)
            logits = mirror_descent(
                logits, lin, mask, res_active, bw_active, ports_active,
                base_frac, base_bw_frac, denom_nr, bw_denom, ports_denom,
                active_col, iters)
            x = jax.nn.softmax(logits + mask, axis=1) * active_col
            return logits, x

        _SOLVE_JIT = solve
    return _SOLVE_JIT


def solve_cache_size() -> int:
    """Compiled-program count of the defrag solve (the defrag analog
    of ops/binpack.jit_cache_size, and an input to it): steady state is
    exactly 2 per live (K bucket, N) shape — cold + warm."""
    if _SOLVE_JIT is None:
        return 0
    try:
        return _SOLVE_JIT._cache_size()
    except Exception:  # noqa: BLE001 - accounting must never raise
        return 0


def _k_bucket(k: int) -> int:
    from ..models.matrix import bucket_size

    return bucket_size(k, K_BUCKETS)


def movable_allocs(state, row_of: Dict[str, int], node_ok) -> List:
    """The allocations a defrag wave may move: live, desired-run,
    service-job allocs on healthy in-matrix nodes. System jobs are
    node-pinned, batch jobs lose completed work when restarted, and
    allocs on draining/down nodes already belong to the drain/lost
    machinery — all excluded."""
    from ..structs import consts

    out = []
    for a in state.allocs():
        if a.terminal_status():
            continue
        if a.desired_status != consts.ALLOC_DESIRED_RUN:
            continue
        if a.job is None or a.job.type != consts.JOB_TYPE_SERVICE:
            continue
        row = row_of.get(a.node_id)
        if row is None or not node_ok[row]:
            continue
        out.append(a)
    out.sort(key=lambda a: a.id)  # deterministic solve order
    return out


def compute_defrag_plan(state, datacenters, *, max_moves: int,
                        min_gain: float, warm: WarmState,
                        movable_cap: int = MAX_SOLVE_ALLOCS,
                        class_compress: Optional[bool] = None,
                        mesh=None) -> DefragPlan:
    """One defrag round against an MVCC snapshot: resolve the resident
    cluster base (the same cacheable path the schedulers ride — in
    steady state this is a cache hit, not a rebuild), solve the relaxed
    global re-placement warm-started from `warm`, and extract the
    gain-verified move set. Mutates `warm` with this round's iterate.

    ``class_compress`` forces (True) or forbids (False) the
    class-compressed solve; None auto-enables it past
    CLASS_COMPRESS_MIN_NODES when the fleet compresses at least
    CLASS_COMPRESS_MIN_RATIO. The compressed solve aggregates per-class
    capacity/residual over SCHEDULABLE members only; the aggregate
    relaxes feasibility (a class's pooled headroom can exceed any one
    member's), which is safe here because the rounding walk and the
    move simulation below both re-verify per-NODE headroom — a class
    choice that no member can absorb rounds to nothing.

    ``mesh`` (parallel/mesh.py) shards the UNcompressed solve's node
    axis across devices via GSPMD input shardings — the x[K, N] tensor
    is the biggest in the system and must shard past device memory.
    The compressed solve is small enough to stay single-device."""
    from ..models.matrix import (
        _alloc_usage,
        resolve_cluster_base,
        universe_nodes_cached,
    )

    t0 = time.perf_counter()
    plan = DefragPlan()
    base, _kind = resolve_cluster_base(state, datacenters)
    nodes, _by_dc, usig = universe_nodes_cached(state, datacenters)
    row_of = {node.id: i for i, node in enumerate(nodes)}
    movable = movable_allocs(state, row_of, base.node_ok)
    plan.movable = len(movable)
    plan.n = base.n
    if not movable:
        plan.solve_ms = (time.perf_counter() - t0) * 1000.0
        return plan

    if len(movable) > movable_cap:
        # Keep the consolidation candidates: allocs on the least-filled
        # occupied nodes (a full node's allocs have nowhere better to
        # be). Fill fraction is max(cpu, mem) like binpack_score.
        denom = np.maximum(base.capacity[:, :2], 1.0)
        fill = (base.util[:, :2] / denom).max(axis=1)
        movable.sort(key=lambda a: (fill[row_of[a.node_id]], a.id))
        movable = movable[:movable_cap]
        movable.sort(key=lambda a: a.id)

    k_real = len(movable)
    k = _k_bucket(k_real)
    plan.k = k

    ask_res = np.zeros((k, 4), np.float32)
    ask_bw = np.zeros(k, np.float32)
    ask_ports = np.zeros(k, np.float32)
    active = np.zeros(k, bool)
    cur_row = np.zeros(k_real, np.int64)
    for i, a in enumerate(movable):
        cpu, mem, disk, iops, mbits, ports = _alloc_usage(a)
        ask_res[i] = (cpu, mem, disk, iops)
        ask_bw[i] = mbits
        ask_ports[i] = ports
        active[i] = True
        cur_row[i] = row_of[a.node_id]

    # Residual state: the movable set's own load removed, so the solve
    # re-places it from scratch over what everything else occupies.
    base_util = base.util.copy()
    np.subtract.at(base_util, cur_row, ask_res[:k_real])
    np.maximum(base_util, 0.0, out=base_util)
    bw_used = base.bw_used.copy()
    np.subtract.at(bw_used, cur_row, ask_bw[:k_real])
    np.maximum(bw_used, 0.0, out=bw_used)
    ports_free = base.ports_free.copy()
    np.add.at(ports_free, cur_row, ask_ports[:k_real])
    node_ok = np.asarray(base.node_ok, bool)

    # ---- class compression (models/classes.py): solve over x[K, C]
    # instead of x[K, N] when the fleet is big and compresses. The
    # residual state above stays node-granular; only the solve's view
    # aggregates, and the expansion back happens before rounding.
    cidx = getattr(base, "class_index", None)
    compress = class_compress
    if compress is None:
        compress = (cidx is not None
                    and base.n_real >= CLASS_COMPRESS_MIN_NODES
                    and cidx.compression_ratio()
                    >= CLASS_COMPRESS_MIN_RATIO)
    compress = bool(compress) and cidx is not None
    if compress:
        from ..models.classes import class_any, class_sum
        from ..models.matrix import BUCKETS, bucket_size

        ids = cidx.ids[: cidx.n_real]
        c_pad = bucket_size(cidx.n_classes, BUCKETS)
        # Aggregate over SCHEDULABLE members only: a class's pooled
        # capacity is its LIVE capacity, and an all-down class zeroes
        # out (capacity 0 -> infeasible in the solve's mask).
        solve_util = class_sum(base_util, ids, c_pad, where=node_ok)
        solve_cap = class_sum(base.capacity, ids, c_pad, where=node_ok)
        solve_sched = class_sum(base.sched_capacity, ids, c_pad,
                                where=node_ok)
        solve_bw_avail = class_sum(base.bw_avail, ids, c_pad,
                                   where=node_ok)
        solve_bw_used = class_sum(bw_used, ids, c_pad, where=node_ok)
        solve_ports = class_sum(ports_free.astype(np.float32), ids,
                                c_pad, where=node_ok)
        solve_ok = class_any(node_ok, ids, c_pad)
        width = c_pad
        # A class move means a different warm-carry geometry: the
        # "class" marker keys the carry apart from node-granular
        # rounds so a mode flip drops the stale iterate.
        key = (usig, c_pad, k, "class")
        plan.compressed = True
        plan.classes = int(cidx.n_classes)
        plan.compression_ratio = round(cidx.compression_ratio(), 2)
    else:
        solve_util, solve_cap = base_util, base.capacity
        solve_sched = base.sched_capacity
        solve_bw_avail, solve_bw_used = base.bw_avail, bw_used
        solve_ports, solve_ok = ports_free, node_ok
        width = base.n
        key = (usig, base.n, k)

    # Warm-start carry, keyed on the family signature (node-set
    # identity) + shape: gather carried rows per alloc id.
    carried = warm.take(key)
    logits0 = np.zeros((k, width), np.float32)
    fresh = np.ones(k, bool)
    n_carried = 0
    for i, a in enumerate(movable):
        row = carried.get(a.id)
        if row is not None and row.shape == (width,):
            logits0[i] = row
            fresh[i] = False
            n_carried += 1
    plan.carried = n_carried
    plan.warm = n_carried >= max(1, int(k_real * WARM_MIN_CARRY))
    iters = WARM_ITERS if plan.warm else COLD_ITERS

    solve_args = (logits0, fresh, solve_util, solve_cap, solve_sched,
                  solve_ok, solve_bw_avail, solve_bw_used, solve_ports,
                  ask_res, ask_bw, ask_ports, active)
    if mesh is not None and not compress:
        from ..parallel.mesh import NODE_AXIS, shard_defrag_inputs

        if base.n % int(mesh.shape[NODE_AXIS]) == 0:
            solve_args = shard_defrag_inputs(mesh, solve_args)
    logits, x = _solve_jit()(*solve_args, iters=iters)
    logits = np.asarray(logits)
    x = np.asarray(x)
    warm.store(key, {a.id: logits[i] for i, a in enumerate(movable)})
    if compress:
        # Expand the class-granular solution back to node granularity
        # for the rounding walk: each class's mass splits evenly over
        # its members (a tie-break, not a feasibility claim — the walk
        # checks actual per-node headroom).
        from ..models.classes import expand_to_nodes

        x_nodes = np.zeros((k_real, base.n), np.float32)
        x_nodes[:, : cidx.n_real] = expand_to_nodes(
            x[:k_real], ids, cidx.counts)
        x = x_nodes

    # ---- rounding: the convex kernel's repair scan, on the host. A
    # per-row argmax is degenerate (symmetric asks get symmetric rows
    # and the pack reward piles them on one node); the convex kernel
    # rounds with a SEQUENTIAL feasibility-respecting scan biased by
    # the row preference + the aggregate node mass y — the same shape
    # here, in numpy (this path runs once per round, off the hot path).
    y = x[:k_real].sum(axis=0)
    pref = (x[:k_real] / (x[:k_real].max(axis=1, keepdims=True) + 1e-9)
            + y[None, :] / (y.max() + 1e-9))
    # Big-first rounding order (ties by id): large remainders are what
    # strands capacity, so they anchor the packing.
    size = ask_res[:k_real, :2].max(axis=1)
    order = sorted(range(k_real), key=lambda i: (-size[i], movable[i].id))
    headroom = base.capacity - base_util  # residual state, as solved
    assign = np.full(k_real, -1, np.int64)
    for i in order:
        feas = node_ok & np.all(headroom >= ask_res[i][None, :], axis=1)
        if not feas.any():
            continue
        scores = np.where(feas, pref[i], -np.inf)
        t = int(np.argmax(scores))
        assign[i] = t
        headroom[t] -= ask_res[i]

    # ---- move extraction: diff the rounded solution against current
    # placements, simulate the candidate moves CUMULATIVELY against
    # the real utilization (the rounded solution re-placed everything;
    # executing a subset must re-verify fit), and keep the best-gain
    # PREFIX — consolidation often walks through flat steps (swap one
    # remainder out before its node can absorb another), so per-move
    # strict improvement would refuse exactly the waves that matter.
    cand = [i for i in order
            if assign[i] >= 0 and assign[i] != cur_row[i]]
    plan.candidates = len(cand)

    refs = reference_asks(ask_res[:k_real])

    def frag(u):
        return frag_score(u, base.capacity, node_ok, refs)

    util_sim = base.util.copy()
    frag0 = frag(util_sim)
    plan.frag_before = frag0

    # Directly-consolidating moves first: score each candidate's SOLO
    # gain at the real state (a remainder-combining move — the only
    # single move that shifts the fragmentation score — shows it here)
    # and walk those before the plateau steps of the global re-layout,
    # so a bounded wave spends its moves where the gain is.
    def solo_gain(i):
        t = int(assign[i])
        res = ask_res[i]
        if np.any(base.capacity[t] - util_sim[t] < res):
            return None
        trial = util_sim.copy()
        trial[cur_row[i]] = np.maximum(trial[cur_row[i]] - res, 0.0)
        trial[t] += res
        return frag0 - frag(trial)

    solo = {i: solo_gain(i) for i in cand}
    rank = {i: r for r, i in enumerate(cand)}  # rounding order
    cand.sort(key=lambda i: (-(solo[i] or 0.0), rank[i]))
    trail: List[Tuple[int, int, float]] = []  # (k, target, frag after)
    for i in cand:
        if len(trail) >= max_moves:
            break
        t = int(assign[i])
        res = ask_res[i]
        if np.any(base.capacity[t] - util_sim[t] < res):
            continue  # occupied by movables that are NOT moving
        util_sim[cur_row[i]] = np.maximum(util_sim[cur_row[i]] - res, 0.0)
        util_sim[t] += res
        trail.append((i, t, frag(util_sim)))
    if trail:
        frags = [f for (_i, _t, f) in trail]
        best = int(np.argmin(frags))
        if frags[best] < frag0 - 1e-9:
            prev = frag0
            for (i, t, f) in trail[: best + 1]:
                a = movable[i]
                plan.moves.append(Move(
                    alloc_id=a.id, job_id=a.job_id, from_node=a.node_id,
                    to_node=nodes[t].id, gain=prev - f))
                prev = f
            plan.frag_after = frags[best]
        else:
            plan.frag_after = frag0
    else:
        plan.frag_after = frag0
    plan.gain = frag0 - plan.frag_after
    if plan.gain < min_gain:
        plan.moves = []
    plan.solve_ms = (time.perf_counter() - t0) * 1000.0
    return plan
