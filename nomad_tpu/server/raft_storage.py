"""Durable raft state: log, term/vote metadata, FSM snapshots.

Reference: the reference persists its raft log in BoltDB (raft.db via
raft-boltdb) and FSM snapshots as retained files (fsm.go:506-1036,
snapshotsRetained=2 at server.go:50), restoring snapshot + log replay
on restart. Here: an append-only JSONL log (rewritten on the rare
conflict truncation/compaction), a small meta JSON for term/voted_for
(flushed before votes are answered — the raft safety requirement), and
numbered snapshot files with retention.
"""

from __future__ import annotations

import json
import os
from typing import Any, Callable, List, Optional, Tuple

SNAPSHOTS_RETAINED = 2


class RaftStorage:
    def __init__(self, directory: str,
                 encode: Optional[Callable[[str, Any], Any]] = None,
                 decode: Optional[Callable[[str, Any], Any]] = None,
                 retained: int = SNAPSHOTS_RETAINED):
        self.dir = directory
        os.makedirs(directory, exist_ok=True)
        self.encode = encode or (lambda mt, p: p)
        self.decode = decode or (lambda mt, p: p)
        self.retained = retained
        self._log_path = os.path.join(directory, "raft_log.jsonl")
        self._meta_path = os.path.join(directory, "raft_meta.json")
        self._log_file = None

    # ------------------------------------------------------------ meta

    def save_meta(self, term: int, voted_for: Optional[str]) -> None:
        """Durable BEFORE answering votes: a restarted node must not
        vote twice in one term."""
        tmp = self._meta_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"term": term, "voted_for": voted_for}, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._meta_path)

    def load_meta(self) -> Tuple[int, Optional[str]]:
        try:
            with open(self._meta_path) as f:
                data = json.load(f)
            return int(data.get("term", 0)), data.get("voted_for")
        except (OSError, ValueError):
            return 0, None

    # ------------------------------------------------------------- log

    def _entry_to_wire(self, entry) -> dict:
        return {
            "term": entry.term,
            "index": entry.index,
            "msg_type": entry.msg_type,
            "payload": self.encode(entry.msg_type, entry.payload),
        }

    def append_entry(self, entry) -> None:
        if self._log_file is None:
            self._log_file = open(self._log_path, "a")
        self._log_file.write(json.dumps(self._entry_to_wire(entry)) + "\n")
        self._log_file.flush()
        # Same safety bar as save_meta: an entry counted as durably
        # replicated must survive power loss before the commit is acked.
        os.fsync(self._log_file.fileno())

    def rewrite_log(self, entries: List[Any]) -> None:
        """Full rewrite after a conflict truncation or compaction."""
        if self._log_file is not None:
            self._log_file.close()
            self._log_file = None
        tmp = self._log_path + ".tmp"
        with open(tmp, "w") as f:
            for entry in entries:
                f.write(json.dumps(self._entry_to_wire(entry)) + "\n")
        os.replace(tmp, self._log_path)

    def load_log(self, entry_cls) -> List[Any]:
        entries = []
        try:
            with open(self._log_path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        raw = json.loads(line)
                    except ValueError:
                        break  # torn tail write: ignore the partial line
                    entries.append(entry_cls(
                        term=raw["term"], index=raw["index"],
                        msg_type=raw["msg_type"],
                        payload=self.decode(raw["msg_type"], raw["payload"]),
                    ))
        except OSError:
            pass
        return entries

    # ------------------------------------------------------- snapshots

    def _snapshot_path(self, index: int) -> str:
        return os.path.join(self.dir, f"snapshot-{index:020d}.json")

    def save_snapshot(self, index: int, term: int, data: dict) -> None:
        tmp = self._snapshot_path(index) + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"index": index, "term": term, "data": data}, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._snapshot_path(index))
        # retention (server.go:50 snapshotsRetained)
        snaps = sorted(
            n for n in os.listdir(self.dir)
            if n.startswith("snapshot-") and n.endswith(".json")
        )
        for name in snaps[: -self.retained]:
            try:
                os.unlink(os.path.join(self.dir, name))
            except OSError:
                pass

    def load_latest_snapshot(self) -> Optional[Tuple[int, int, dict]]:
        snaps = sorted(
            (n for n in os.listdir(self.dir)
             if n.startswith("snapshot-") and n.endswith(".json")),
            reverse=True,
        )
        for name in snaps:
            try:
                with open(os.path.join(self.dir, name)) as f:
                    raw = json.load(f)
                return int(raw["index"]), int(raw["term"]), raw["data"]
            except (OSError, ValueError, KeyError):
                continue  # corrupt snapshot: fall back to the previous
        return None

    def close(self) -> None:
        if self._log_file is not None:
            self._log_file.close()
            self._log_file = None
