from .broker import EvalBroker
from .blocked import BlockedEvals
from .config import ServerConfig
from .fsm import FSM, DevLog
from .plan_apply import PlanApplier, evaluate_node_plan
from .plan_queue import PlanQueue
from .server import Server
from .timetable import TimeTable
from .worker import Worker

__all__ = [
    "EvalBroker",
    "BlockedEvals",
    "ServerConfig",
    "FSM",
    "DevLog",
    "PlanApplier",
    "evaluate_node_plan",
    "PlanQueue",
    "Server",
    "TimeTable",
    "Worker",
]
