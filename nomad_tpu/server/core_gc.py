"""CoreScheduler: garbage collection driven by `_core` evals.

Reference: nomad/core_sched.go:29 — the leader periodically enqueues
core-job evals (leader.go GC timers); a worker dequeues them like any
other eval and this scheduler reaps terminal evals/allocs, dead jobs,
and down nodes older than their thresholds, using the TimeTable to map
time thresholds to raft indexes.
"""

from __future__ import annotations

import logging
import time
from typing import List, Optional

from ..structs import Evaluation, consts


class CoreScheduler:
    """Registered under the `_core` eval type. The eval's job_id selects
    the GC pass: eval-gc, job-gc, node-gc, or force-gc."""

    def __init__(self, logger, state, planner, rng=None, server=None):
        self.logger = logger or logging.getLogger("nomad_tpu.core_gc")
        self.state = state
        self.server = server

    def process_eval(self, ev: Evaluation) -> None:
        kind = ev.job_id
        if kind == consts.CORE_JOB_EVAL_GC:
            self._eval_gc(force=False)
        elif kind == consts.CORE_JOB_JOB_GC:
            self._job_gc(force=False)
        elif kind == consts.CORE_JOB_NODE_GC:
            self._node_gc(force=False)
        elif kind == consts.CORE_JOB_FORCE_GC:
            self._eval_gc(force=True)
            self._job_gc(force=True)
            self._node_gc(force=True)
        else:
            self.logger.error("core sched: unknown job %r", kind)

    # ------------------------------------------------------------------

    def _threshold_index(self, threshold_seconds: float, force: bool) -> int:
        if force:
            return self.server.fsm.state.latest_index()
        cutoff = time.time() - threshold_seconds
        return self.server.fsm.timetable.nearest_index(cutoff)

    def _eval_gc(self, force: bool) -> None:
        """Reap terminal evals (and their terminal allocs) older than the
        threshold (core_sched.go:164)."""
        cfg = self.server.config
        oldest = self._threshold_index(cfg.eval_gc_threshold, force)
        gc_evals: List[str] = []
        gc_allocs: List[str] = []
        for ev in self.state.evals():
            if not ev.terminal_status() or ev.modify_index > oldest:
                continue
            allocs = self.state.allocs_by_eval(ev.id)
            if any(not a.terminal_status() or a.modify_index > oldest for a in allocs):
                continue  # eval still referenced by live allocs
            gc_evals.append(ev.id)
            gc_allocs.extend(a.id for a in allocs)
        if gc_evals or gc_allocs:
            self.logger.debug(
                "eval GC reaping %d evals, %d allocs", len(gc_evals), len(gc_allocs)
            )
            self.server.eval_reap(gc_evals, gc_allocs)

    def _job_gc(self, force: bool) -> None:
        """Reap dead jobs whose evals/allocs are all collectible
        (core_sched.go:68)."""
        cfg = self.server.config
        oldest = self._threshold_index(cfg.job_gc_threshold, force)
        for job in self.state.jobs():
            if job.status != consts.JOB_STATUS_DEAD or job.modify_index > oldest:
                continue
            if job.is_periodic():
                continue  # parents live until deregistered
            evals = self.state.evals_by_job(job.id)
            if any(not ev.terminal_status() or ev.modify_index > oldest for ev in evals):
                continue
            allocs = self.state.allocs_by_job(job.id)
            if any(not a.terminal_status() or a.modify_index > oldest for a in allocs):
                continue
            self.logger.debug("job GC reaping %s", job.id)
            self.server.eval_reap(
                [ev.id for ev in evals], [a.id for a in allocs]
            )
            self.server.job_deregister(job.id, create_eval=False)

    def _node_gc(self, force: bool) -> None:
        """Reap down nodes with no allocs (core_sched.go:335)."""
        cfg = self.server.config
        oldest = self._threshold_index(cfg.node_gc_threshold, force)
        for node in self.state.nodes():
            if node.status != consts.NODE_STATUS_DOWN or node.modify_index > oldest:
                continue
            # Only NON-terminal allocations pin a node; completed ones
            # are the eval GC's business (core_sched.go:361-378
            # TestCoreScheduler_NodeGC_TerminalAllocs).
            if any(not a.terminal_status()
                   for a in self.state.allocs_by_node(node.id)):
                continue
            self.logger.debug("node GC reaping %s", node.id)
            self.server.node_deregister(node.id)
