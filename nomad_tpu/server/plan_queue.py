"""PlanQueue: leader-only priority queue of pending plans.

Reference: nomad/plan_queue.go:29 — plans are futures: the worker blocks
on the result while the single plan applier serializes commits.
"""

from __future__ import annotations

import heapq
import itertools
import threading
import time
from typing import List, Optional, Tuple

from ..structs import Plan, PlanResult


class PendingPlan:
    """A queued plan and its response future."""

    __slots__ = ("plan", "enqueue_time", "_event", "_result", "_error")

    def __init__(self, plan: Plan):
        self.plan = plan
        self.enqueue_time = time.monotonic()
        self._event = threading.Event()
        self._result: Optional[PlanResult] = None
        self._error: Optional[Exception] = None

    def respond(self, result: Optional[PlanResult], error: Optional[Exception]) -> None:
        self._result = result
        self._error = error
        self._event.set()

    def wait(self, timeout: Optional[float] = None) -> PlanResult:
        if not self._event.wait(timeout):
            raise TimeoutError("plan apply timed out")
        if self._error is not None:
            raise self._error
        return self._result


class PlanQueue:
    def __init__(self):
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._enabled = False
        self._heap: List[Tuple[int, int, PendingPlan]] = []
        self._counter = itertools.count()

    def set_enabled(self, enabled: bool) -> None:
        with self._lock:
            self._enabled = enabled
            if not enabled:
                for _, _, pending in self._heap:
                    pending.respond(None, RuntimeError("plan queue disabled"))
                self._heap = []
            self._cond.notify_all()

    def enabled(self) -> bool:
        with self._lock:
            return self._enabled

    def enqueue(self, plan: Plan) -> PendingPlan:
        with self._lock:
            if not self._enabled:
                raise RuntimeError("plan queue is disabled")
            pending = PendingPlan(plan)
            heapq.heappush(
                self._heap, (-plan.priority, next(self._counter), pending)
            )
            self._cond.notify()
            return pending

    def dequeue(self, timeout: Optional[float] = None) -> Optional[PendingPlan]:
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._lock:
            while True:
                if not self._enabled:
                    return None
                if self._heap:
                    return heapq.heappop(self._heap)[2]
                remaining = None
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return None
                self._cond.wait(remaining if remaining is not None else 1.0)

    def depth(self) -> int:
        with self._lock:
            return len(self._heap)
