"""Scheduling worker: dequeue -> wait-for-index -> invoke scheduler ->
submit plan -> ack.

Reference: nomad/worker.go:50 — the worker implements the scheduler's
Planner interface (worker.go:285-483): plans go through the leader's
plan queue; a RefreshIndex response makes the worker catch its local
state up and hand the scheduler a fresh snapshot.
"""

from __future__ import annotations

import logging
import random
import threading
import time
from typing import Optional, Tuple

from ..scheduler import new_scheduler
from ..utils import metrics
from ..structs import Evaluation, Plan, PlanResult, consts

DEQUEUE_TIMEOUT = 0.5
BACKOFF_BASE = 0.02
BACKOFF_LIMIT = 2.0


class Worker:
    def __init__(self, server, worker_id: int):
        self.server = server
        self.id = worker_id
        self.logger = logging.getLogger(f"nomad_tpu.worker.{worker_id}")
        self._stop = threading.Event()
        self._paused = False
        self._pause_lock = threading.Lock()
        self._pause_cond = threading.Condition(self._pause_lock)
        self._thread: Optional[threading.Thread] = None
        # Current eval context for the Planner interface
        self._eval: Optional[Evaluation] = None
        self._token: str = ""
        self.rng = random.Random()

    # ------------------------------------------------------------------

    def start(self) -> None:
        self._stop.clear()
        self._thread = threading.Thread(
            target=self.run, name=f"worker-{self.id}", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self.set_pause(False)
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    def set_pause(self, paused: bool) -> None:
        """Leader parks 3/4 of its workers to give CPU to the plan
        applier (leader.go:108-117, worker.go:82-98)."""
        with self._pause_lock:
            self._paused = paused
            self._pause_cond.notify_all()

    def _check_paused(self) -> None:
        with self._pause_lock:
            while self._paused and not self._stop.is_set():
                self._pause_cond.wait(0.5)

    # ------------------------------------------------------------------

    def run(self) -> None:
        while not self._stop.is_set():
            self._check_paused()
            start = time.monotonic()
            ev, token = self.server.eval_dequeue(
                self.server.config.enabled_schedulers, DEQUEUE_TIMEOUT
            )
            if ev is None:
                continue
            metrics.measure_since(("worker", "dequeue_eval"), start)
            start = time.monotonic()
            if not self._wait_for_index(ev.modify_index, timeout=5.0):
                self.server.eval_nack(ev.id, token)
                continue
            metrics.measure_since(("worker", "wait_for_index"), start)
            self._eval, self._token = ev, token
            start = time.monotonic()
            try:
                self._invoke_scheduler(ev)
            except Exception:
                self.logger.exception("eval %s failed", ev.id)
                self._safe_nack(ev.id, token)
                continue
            finally:
                metrics.measure_since(("worker", "invoke_scheduler", ev.type), start)
            try:
                self.server.eval_ack(ev.id, token)
            except ValueError:
                pass  # nack timer fired concurrently

    def _safe_nack(self, eval_id: str, token: str) -> None:
        try:
            self.server.eval_nack(eval_id, token)
        except ValueError:
            pass

    def _wait_for_index(self, index: int, timeout: float) -> bool:
        """Local FSM catch-up with exponential backoff
        (worker.go:214,503)."""
        deadline = time.monotonic() + timeout
        backoff = BACKOFF_BASE
        while self.server.fsm.state.latest_index() < index:
            if self._stop.is_set() or time.monotonic() > deadline:
                return False
            time.sleep(backoff)
            backoff = min(backoff * 2, BACKOFF_LIMIT)
        return True

    def _invoke_scheduler(self, ev: Evaluation) -> None:
        snapshot = self.server.fsm.state.snapshot()
        factory = self.server.config.factory_for(ev.type)
        sched = new_scheduler(factory, self.logger, snapshot, self, rng=self.rng)
        sched.process_eval(ev)

    # ------------------------------------------------ Planner interface

    def submit_plan(self, plan: Plan) -> Tuple[PlanResult, Optional[object]]:
        start = time.monotonic()
        plan.eval_token = self._token
        # The Nack clock stops while the plan waits in the queue
        # (plan_endpoint.go:16).
        try:
            self.server.eval_pause_nack(self._eval.id, self._token)
        except ValueError:
            pass
        try:
            result = self.server.plan_submit(plan)
        finally:
            try:
                self.server.eval_resume_nack(self._eval.id, self._token)
            except ValueError:
                pass
        metrics.measure_since(("worker", "submit_plan"), start)
        if result.refresh_index:
            # Stale snapshot: catch up and hand back fresh state.
            self._wait_for_index(result.refresh_index, timeout=5.0)
            return result, self.server.fsm.state.snapshot()
        return result, None

    def update_eval(self, ev: Evaluation) -> None:
        self.server.eval_update([ev])

    def create_eval(self, ev: Evaluation) -> None:
        ev.snapshot_index = self.server.fsm.state.latest_index()
        self.server.eval_update([ev])

    def reblock_eval(self, ev: Evaluation) -> None:
        token = self.server.eval_outstanding(ev.id)
        if token != self._token:
            raise ValueError(f"eval {ev.id!r} is not outstanding")
        ev.snapshot_index = self.server.fsm.state.latest_index()
        self.server.eval_update([ev], token=self._token)
