"""Scheduling worker: dequeue -> wait-for-index -> invoke scheduler ->
submit plan -> ack.

Reference: nomad/worker.go:50 — the worker implements the scheduler's
Planner interface (worker.go:285-483): plans go through the leader's
plan queue; a RefreshIndex response makes the worker catch its local
state up and hand the scheduler a fresh snapshot.

Extension over the reference (VERDICT round 1 / BASELINE north star):
when an eval routes to a dense (TPU) factory, the worker drains more
ready evals of the same type in one broker visit (dequeue_many) and
processes them concurrently, so their placement programs coalesce into
one batched device dispatch (scheduler/batcher.py) even with a single
active worker. The reference's single-dequeue loop cannot form device
batches; this is the drain-to-batch shim the dense backend needs.
"""

from __future__ import annotations

import logging
import os
import random
import threading
import time
from typing import List, Optional, Tuple

from ..scheduler import new_scheduler
from ..utils import metrics
from ..utils.backoff import poll_until
from ..structs import Evaluation, Plan, PlanResult, consts
from .. import trace

DEQUEUE_TIMEOUT = 0.5
BACKOFF_BASE = 0.02
BACKOFF_LIMIT = 2.0
# Nap between saturation re-checks when the dispatch pipeline's
# accumulator is full (intake backpressure, nomad_tpu/admission):
# bounded, and short enough that drain resumes within a batch launch.
BACKPRESSURE_NAP = 0.01


def is_dense_factory(name: str) -> bool:
    """Dense/TPU factories benefit from drain-to-batch processing."""
    return name.endswith("-tpu")


def factory_kernel(name: str) -> Optional[str]:
    """The kernel a dense factory variant pins ("service-convex-tpu"
    -> "convex"; nomad_tpu/kernels lazy registry), None for plain
    dense factories and host factories. The scheduler executive's
    fast path reads the pin from here so its cohort dispatches run
    the SAME kernel the per-eval scheduler (and the conflict re-run)
    would — a drift would compile a second program per shape bucket
    and break executive-vs-worker parity."""
    if not is_dense_factory(name):
        return None
    base = name[: -len("-tpu")]
    from ..kernels import kernel_names

    for kernel in kernel_names():
        if base.endswith("-" + kernel):
            return kernel
    return None


def host_factory(name: str) -> str:
    """The host (CPU iterator) factory with identical placement
    semantics — where latency-aware routing sends lone evals. Kernel-
    pinned dense variants ("service-convex-tpu", nomad_tpu/kernels)
    map to the same host factory as their plain siblings: the host
    path has no kernels, the infix strips with the suffix."""
    if not is_dense_factory(name):
        return name
    kernel = factory_kernel(name)
    base = name[: -len("-tpu")]
    if kernel is not None:
        return base[: -(len(kernel) + 1)]
    return base


class EvalSession:
    """Per-eval Planner (worker.go:285-483). One session per in-flight
    eval so a worker can process a drained batch concurrently — the
    Planner callbacks need the eval's own token, not worker state."""

    def __init__(self, worker: "Worker", ev: Evaluation, token: str):
        self.worker = worker
        self.server = worker.server
        self.eval = ev
        self.token = token
        # The dense kernel's in-batch conflict pre-resolution flag
        # (scheduler/tpu.py reads it off its Planner): worker-drained
        # batches share a snapshot exactly like pipeline batches do.
        self.pre_resolve = worker.server.config.dense_pre_resolve

    def submit_plan(self, plan: Plan) -> Tuple[PlanResult, Optional[object]]:
        start = time.monotonic()
        plan.eval_token = self.token
        # The Nack clock stops while the plan waits in the queue
        # (plan_endpoint.go:16).
        try:
            self.server.eval_pause_nack(self.eval.id, self.token)
        except ValueError:
            pass
        try:
            result = self.server.plan_submit(plan)
        finally:
            try:
                self.server.eval_resume_nack(self.eval.id, self.token)
            except ValueError:
                pass
        metrics.measure_since(("worker", "submit_plan"), start)
        trace.record_span(self.eval.id, trace.STAGE_PLAN_SUBMIT, start,
                          trace_id=self.eval.trace_id)
        if result.refresh_index:
            # Stale snapshot: catch up and hand back fresh state.
            self.worker._wait_for_index(result.refresh_index, timeout=5.0)
            return result, self.server.fsm.state.snapshot()
        return result, None

    def update_eval(self, ev: Evaluation) -> None:
        self.server.eval_update([ev])

    def create_eval(self, ev: Evaluation) -> None:
        ev.snapshot_index = self.server.fsm.state.latest_index()
        self.server.eval_update([ev])

    def reblock_eval(self, ev: Evaluation) -> None:
        token = self.server.eval_outstanding(ev.id)
        if token != self.token:
            raise ValueError(f"eval {ev.id!r} is not outstanding")
        ev.snapshot_index = self.server.fsm.state.latest_index()
        self.server.eval_update([ev], token=self.token)


class Worker:
    def __init__(self, server, worker_id: int):
        self.server = server
        self.id = worker_id
        self.logger = logging.getLogger(f"nomad_tpu.worker.{worker_id}")
        self._stop = threading.Event()
        self._paused = False  # guarded-by: _pause_lock
        self._pause_lock = threading.Lock()
        self._pause_cond = threading.Condition(self._pause_lock)
        self._parked = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.rng = random.Random()

    # ------------------------------------------------------------------

    def start(self) -> None:
        self._stop.clear()
        self._thread = threading.Thread(
            target=self.run, name=f"worker-{self.id}", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self.set_pause(False)
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    def set_pause(self, paused: bool) -> None:
        """Leader parks 3/4 of its workers to give CPU to the plan
        applier (leader.go:108-117, worker.go:82-98)."""
        with self._pause_lock:
            self._paused = paused
            self._pause_cond.notify_all()

    def parked(self) -> bool:
        """True while the run loop is waiting inside the paused state —
        i.e. this worker is provably NOT inside a broker dequeue. A
        sleep after ``set_pause(True)`` is not equivalent: an in-flight
        dequeue long-poll can outlive any fixed sleep on a loaded host
        and steal the next enqueued eval."""
        return self._parked.is_set()

    def _check_paused(self) -> None:
        with self._pause_lock:
            if not (self._paused and not self._stop.is_set()):
                return
            self._parked.set()
            try:
                while self._paused and not self._stop.is_set():
                    self._pause_cond.wait(0.5)
            finally:
                self._parked.clear()

    # ------------------------------------------------------------------

    def run(self) -> None:
        while not self._stop.is_set():
            self._check_paused()
            executive = getattr(self.server, "executive", None)
            if executive is not None and not executive.enabled:
                executive = None
            pipeline = getattr(self.server, "dispatch", None)
            if (executive is not None and executive.saturated()) or (
                    pipeline is not None and pipeline.enabled
                    and pipeline.saturated()):
                # Intake backpressure (nomad_tpu/admission): the
                # central accumulator already holds two full batches.
                # Draining more would only move backlog out of the
                # BOUNDED broker ready queues into the pipeline's
                # unbounded pending list, hiding it from priority
                # shedding and deadline enforcement. Nap (bounded) and
                # re-check; the stop/pause paths stay responsive.
                metrics.incr_counter(("worker", "backpressure"))
                time.sleep(BACKPRESSURE_NAP)
                continue
            start = time.monotonic()
            ev, token = self.server.eval_dequeue(
                self.server.config.enabled_schedulers, DEQUEUE_TIMEOUT
            )
            if ev is None:
                continue
            metrics.measure_since(("worker", "dequeue_eval"), start)
            group = [(ev, token)]
            factory = self.server.config.factory_for(ev.type)
            batch_max = self.server.config.eval_batch_size
            if executive is not None and is_dense_factory(factory):
                # Scheduler executive (server/executive.py): the worker
                # is only the broker's long-poll seed — the executive
                # owns the drain from here (bulk top-ups, array-side
                # reconcile, one no-park cohort dispatch). The worker
                # immediately returns to the broker for host-path work.
                executive.submit(ev, token)
                metrics.incr_counter(("worker", "executive_handoff"))
                continue
            pipeline = getattr(self.server, "dispatch", None)
            if (pipeline is not None and pipeline.enabled
                    and is_dense_factory(factory)):
                # Central dispatch pipeline (nomad_tpu/dispatch): hand
                # the eval to the leader-side accumulator instead of
                # draining a per-worker slice — ONE drain packs full
                # batches across all workers, submits run pipelined,
                # and conflict retries rejoin the accumulating batch.
                # This worker immediately returns to the broker for
                # more (host-path evals keep flowing meanwhile).
                pipeline.submit(ev, token)
                metrics.incr_counter(("worker", "pipeline_handoff"))
                continue
            if batch_max > 1 and is_dense_factory(factory):
                # Drain-to-batch: siblings of the same type ride one
                # device dispatch. Non-blocking — whatever is ready now.
                group.extend(
                    self.server.eval_dequeue_many([ev.type], batch_max - 1)
                )
            if batch_max > 1 and is_dense_factory(factory) and (
                len(group) < self.server.config.dense_min_batch
            ):
                # (batch_max == 1 disables batching AND routing — an
                # operator who turned draining off still gets the dense
                # factory they configured, one eval per dispatch.)
                # Latency-aware routing: too few evals to amortize the
                # device dispatch — a lone interactive eval must not pay
                # the batch-window + device RTT. The host factory has
                # identical placement semantics (parity-tested).
                factory = host_factory(factory)
                metrics.incr_counter(("worker", "route_host"))
            if len(group) == 1:
                self._process_eval(ev, token, factory)
            else:
                metrics.add_sample(("worker", "eval_batch"), len(group))
                # One MVCC snapshot for the whole drained batch: every
                # member plans against the same cluster state, so their
                # ClusterMatrix bases share one cache entry and one
                # device upload (the batcher's overlay fast path needs
                # matching base tokens). Per-eval snapshots would
                # interleave with plan applies and fracture the batch
                # into mixed-token dispatches. Optimistic concurrency
                # makes this safe: the plan applier re-verifies every
                # node and hands back RefreshIndex when stale
                # (plan_apply.go:122-166).
                snapshot = None
                max_index = max(e.modify_index for e, _ in group)
                if self._wait_for_index(max_index, timeout=5.0):
                    snapshot = self.server.fsm.state.snapshot()
                # Batch members run concurrently on the server's shared
                # bounded pool (their place() calls coalesce in the
                # batcher); the worker thread takes the first itself.
                futures = [
                    self.server.eval_pool.submit(
                        self._process_eval, e, t, factory, snapshot)
                    for e, t in group[1:]
                ]
                self._process_eval(ev, token, factory, snapshot)
                for f in futures:
                    # Bounded with a shutdown re-check: an unbounded
                    # wait here pinned the worker thread to a wedged
                    # batch member forever (ntalint unbounded-wait).
                    while not f.wait(1.0) and not self._stop.is_set():
                        pass
                    if self._stop.is_set():
                        break

    def _process_eval(self, ev: Evaluation, token: str,
                      factory: Optional[str] = None,
                      snapshot=None) -> None:
        start = time.monotonic()
        if snapshot is None:
            if not self._wait_for_index(ev.modify_index, timeout=5.0):
                self._safe_nack(ev.id, token)
                return
        metrics.measure_since(("worker", "wait_for_index"), start)
        start = time.monotonic()
        try:
            self._invoke_scheduler(ev, token, factory, snapshot)
        except Exception:
            self.logger.exception("eval %s failed", ev.id)
            self._safe_nack(ev.id, token)
            return
        finally:
            metrics.measure_since(("worker", "invoke_scheduler", ev.type), start)
            trace.record_span(ev.id, trace.STAGE_SCHED_PROCESS, start,
                              ann={"path": "worker"},
                              trace_id=ev.trace_id)
        try:
            self.server.eval_ack(ev.id, token)
        except ValueError:
            pass  # nack timer fired concurrently

    def _safe_nack(self, eval_id: str, token: str) -> None:
        try:
            self.server.eval_nack(eval_id, token)
        except ValueError:
            pass

    def _wait_for_index(self, index: int, timeout: float) -> bool:
        """Local FSM catch-up with jittered exponential backoff
        (worker.go:214,503; policy in utils/backoff.py)."""
        return poll_until(
            lambda: self.server.fsm.state.latest_index() >= index,
            timeout, stop=self._stop,
            base=BACKOFF_BASE, max_delay=BACKOFF_LIMIT)

    def _invoke_scheduler(self, ev: Evaluation, token: str,
                          factory: Optional[str] = None,
                          snapshot=None) -> None:
        if snapshot is None:
            snapshot = self.server.fsm.state.snapshot()
        if factory is None:
            factory = self.server.config.factory_for(ev.type)
        session = EvalSession(self, ev, token)
        # Independent PRNG per eval: concurrent batch members must not
        # share tie-break streams (duplicate streams would correlate
        # their placements, spiking plan conflicts); seeding from the OS
        # keeps this race-free across the batch threads.
        rng = random.Random(int.from_bytes(os.urandom(8), "little"))
        sched = new_scheduler(factory, self.logger, snapshot, session, rng=rng)
        sched.process_eval(ev)
