"""BlockedEvals: evals waiting for cluster capacity changes.

Reference: nomad/blocked_evals.go:24 — captured evals indexed by
computed-class eligibility, escaped evals re-run on any change, one
blocked eval per job with duplicate cancellation, and the
missed-unblock index check that closes the race between a capacity
change landing and the blocked eval being registered.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional

from ..structs import Evaluation, consts


class BlockedEvals:
    def __init__(self, enqueue_fn: Callable[[List[Evaluation]], None]):
        self._lock = threading.RLock()
        self._enabled = False
        self._enqueue = enqueue_fn  # broker enqueue_all

        self._captured: Dict[str, Evaluation] = {}  # class-limited evals
        self._escaped: Dict[str, Evaluation] = {}  # escaped computed class
        self._jobs: Dict[str, str] = {}  # job_id -> blocked eval id
        self._duplicates: List[Evaluation] = []
        # class -> latest index at which that class saw new capacity
        self._unblock_indexes: Dict[str, int] = {}

    # ------------------------------------------------------------------

    def set_enabled(self, enabled: bool) -> None:
        with self._lock:
            self._enabled = enabled
        if not enabled:
            self.flush()

    def flush(self) -> None:
        with self._lock:
            self._captured.clear()
            self._escaped.clear()
            self._jobs.clear()
            self._duplicates.clear()
            self._unblock_indexes.clear()

    # ------------------------------------------------------------------

    def block(self, ev: Evaluation) -> None:
        with self._lock:
            if not self._enabled:
                return
            if ev.id in self._captured or ev.id in self._escaped:
                return
            # One blocked eval per job: newer ones are duplicates the
            # leader cancels (blocked_evals.go:43-54).
            existing = self._jobs.get(ev.job_id)
            if existing is not None and existing != ev.id:
                self._duplicates.append(ev)
                return
            # Missed-unblock race: capacity may have changed between the
            # eval's snapshot and now (blocked_evals.go:214).
            if self._missed_unblock(ev):
                self._enqueue([ev])
                return
            self._jobs[ev.job_id] = ev.id
            if ev.escaped_computed_class:
                self._escaped[ev.id] = ev
            else:
                self._captured[ev.id] = ev

    def reblock(self, ev: Evaluation) -> None:
        """Re-track an eval that was already blocked (the scheduler ran
        it again and still couldn't place everything)."""
        with self._lock:
            self._jobs.pop(ev.job_id, None)
            self._captured.pop(ev.id, None)
            self._escaped.pop(ev.id, None)
        self.block(ev)

    def _missed_unblock(self, ev: Evaluation) -> bool:
        for cls, index in self._unblock_indexes.items():
            if index <= ev.snapshot_index:
                continue
            if ev.escaped_computed_class:
                return True
            elig = ev.class_eligibility.get(cls)
            if elig is None or elig:
                # Unknown or eligible class gained capacity after our
                # snapshot: we may have missed it.
                return True
        return False

    # ------------------------------------------------------------------

    def unblock(self, computed_class: str, index: int) -> None:
        """Capacity changed on nodes of the given class: requeue every
        eval that might now be placeable."""
        with self._lock:
            if not self._enabled:
                return
            self._unblock_indexes[computed_class] = index
            unblocked: List[Evaluation] = []
            for eid, ev in list(self._escaped.items()):
                unblocked.append(ev)
                del self._escaped[eid]
                self._jobs.pop(ev.job_id, None)
            for eid, ev in list(self._captured.items()):
                elig = ev.class_eligibility.get(computed_class)
                if elig is None or elig:
                    unblocked.append(ev)
                    del self._captured[eid]
                    self._jobs.pop(ev.job_id, None)
            if unblocked:
                self._enqueue(unblocked)

    def unblock_failed(self) -> None:
        """Periodically retried by the leader so evals blocked due to
        max-plan failures aren't stuck forever (leader.go:441)."""
        with self._lock:
            unblocked = []
            for store in (self._captured, self._escaped):
                for eid, ev in list(store.items()):
                    if ev.triggered_by == consts.EVAL_TRIGGER_MAX_PLANS:
                        unblocked.append(ev)
                        del store[eid]
                        self._jobs.pop(ev.job_id, None)
            if unblocked:
                self._enqueue(unblocked)

    def untrack(self, job_id: str) -> None:
        """Job deregistered: drop its blocked eval."""
        with self._lock:
            eid = self._jobs.pop(job_id, None)
            if eid:
                self._captured.pop(eid, None)
                self._escaped.pop(eid, None)

    def get_duplicates(self) -> List[Evaluation]:
        """Drain duplicate blocked evals for leader cancellation
        (leader.go:407 reapDupBlockedEvaluations)."""
        with self._lock:
            dups = self._duplicates
            self._duplicates = []
            return dups

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "total_blocked": len(self._captured) + len(self._escaped),
                "total_escaped": len(self._escaped),
            }
