"""Remote-leader client: follower→leader forwarding over HTTP.

Reference: rpc.go:178 `forward` — workers and endpoints on a follower
route leader-only operations (eval broker dequeue/ack/nack, plan
submit, heartbeat timers) to the current leader. The reference pipes
them over its msgpack RPC; here they ride the same HTTP substrate as
everything else, on internal /v1/internal/* routes the leader serves.
"""

from __future__ import annotations

import json
from typing import List, Optional, Tuple

from ..structs import Evaluation, Plan, PlanResult
from ..utils.backoff import Backoff
from ..utils.codec import from_dict, to_dict
from ..utils.httppool import HTTPPool, PoolError


class LeaderUnavailableError(Exception):
    pass


class RemoteLeader:
    """Leader-only operations executed on a remote leader.

    Rides a keep-alive pool (pool.go:144): a follower's workers
    dequeue/ack/submit against the leader on a handful of persistent
    sockets instead of a TCP handshake per RPC. The pool is
    per-instance (the server caches one RemoteLeader per leader addr):
    a process-wide pool keyed by address could hand a NEW leader's
    client a socket opened to a previous process on a reused port."""

    def __init__(self, addr: str, timeout: float = 10.0,
                 ssl_context=None):
        self.addr = addr.rstrip("/")
        self.timeout = timeout
        # The dequeue long-poll passes per-call timeouts above
        # self.timeout; size the pool's ceiling for those.
        # ssl_context: the cluster client context when the HTTP API
        # runs under TLS — without it every follower->leader forward
        # would fail verification against the cluster CA.
        self._pool = HTTPPool(self.addr, timeout=120.0,
                              ssl_context=ssl_context)

    def _call(self, path: str, body: dict, timeout: Optional[float] = None,
              retryable: bool = True):
        """One leader RPC. `retryable` ops ride a short jittered
        backoff through transport-level failures (a leader restart's
        refused-connection window): every /v1/internal mutation is
        token-guarded — a duplicate ack/nack after a lost response is
        REJECTED by the broker, never double-applied — so at-least-once
        retry only converts 'leader briefly gone' from an error into
        latency. Non-retryable: the long-poll dequeue (its wait budget
        is the caller's) and plan submit (at-most-once by contract; the
        conflict machinery owns its retries)."""
        bo = Backoff(base=0.05, max_delay=0.4, attempts=2)
        while True:
            try:
                status, _headers, payload = self._pool.request(
                    "PUT", path, body=json.dumps(body).encode(),
                    headers={"Content-Type": "application/json"},
                    timeout=timeout or self.timeout,
                )
            except PoolError as e:
                if retryable and bo.sleep():
                    continue
                raise LeaderUnavailableError(str(e)) from None
            if status >= 400:
                try:
                    message = json.loads(payload).get("error", "")
                except Exception:  # noqa: BLE001
                    message = payload.decode(errors="replace")
                raise LeaderUnavailableError(message or f"HTTP {status}")
            return json.loads(payload or b"null")

    # ------------------------------------------------------------ evals

    def eval_dequeue(self, schedulers: List[str],
                     timeout: float) -> Tuple[Optional[Evaluation], str]:
        out = self._call(
            "/v1/internal/eval/dequeue",
            {"schedulers": schedulers, "timeout": timeout},
            timeout=timeout + 10.0,
            retryable=False,  # long-poll: the wait budget is the caller's
        )
        ev = from_dict(Evaluation, out.get("eval")) if out.get("eval") else None
        return ev, out.get("token", "")

    def eval_dequeue_many(
        self, schedulers: List[str], max_n: int
    ) -> List[Tuple[Evaluation, str]]:
        out = self._call(
            "/v1/internal/eval/dequeue-many",
            {"schedulers": schedulers, "max_n": max_n},
        )
        return [
            (from_dict(Evaluation, item["eval"]), item.get("token", ""))
            for item in out.get("evals") or []
        ]

    def eval_ack(self, eval_id: str, token: str) -> None:
        self._call("/v1/internal/eval/ack",
                   {"eval_id": eval_id, "token": token})

    def eval_nack(self, eval_id: str, token: str) -> None:
        self._call("/v1/internal/eval/nack",
                   {"eval_id": eval_id, "token": token})

    def eval_pause_nack(self, eval_id: str, token: str) -> None:
        self._call("/v1/internal/eval/pause-nack",
                   {"eval_id": eval_id, "token": token})

    def eval_resume_nack(self, eval_id: str, token: str) -> None:
        self._call("/v1/internal/eval/resume-nack",
                   {"eval_id": eval_id, "token": token})

    def eval_outstanding(self, eval_id: str) -> Optional[str]:
        out = self._call("/v1/internal/eval/outstanding",
                         {"eval_id": eval_id})
        return out.get("token") or None

    # ------------------------------------------------------------ plans

    def plan_submit(self, plan: Plan) -> PlanResult:
        out = self._call("/v1/internal/plan/submit",
                         {"plan": to_dict(plan)}, timeout=40.0,
                         retryable=False)  # at-most-once by contract
        return from_dict(PlanResult, out["result"])

    # ------------------------------------------------------- heartbeats

    def heartbeat_reset(self, node_id: str) -> float:
        out = self._call("/v1/internal/heartbeat/reset",
                         {"node_id": node_id})
        return float(out.get("ttl", 0.0))
