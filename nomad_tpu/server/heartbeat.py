"""Leader-side node heartbeat TTL timers.

Reference: nomad/heartbeat.go:14 — a timer per node; expiry marks the
node down through the normal status-update path, which fans out
re-scheduling evals. TTLs are randomized within [min, min + n/rate] to
spread renewal load (heartbeat.go:47, config.go:235-238).
"""

from __future__ import annotations

import logging
import random
import threading
from typing import Dict

from ..chaos import chaos
from ..structs import consts
from ..utils.pool import WorkPool
from ..utils.timer import default_wheel

INVALIDATE_WORKERS = 8


class HeartbeatTimers:
    def __init__(self, server):
        self.server = server
        self.logger = logging.getLogger("nomad_tpu.heartbeat")
        self._lock = threading.Lock()
        self._wheel = default_wheel()  # one thread for ALL node TTLs
        self._timers: Dict[str, object] = {}
        self._enabled = False
        # Invalidation does a raft apply, which can block for a leader
        # term; running it on the wheel's dispatch pool would let a
        # drain storm head-of-line-block broker nack timers. A private
        # bounded pool absorbs the storm instead.
        self._invalidate_pool = WorkPool(INVALIDATE_WORKERS, name="hb-invalidate")

    def set_enabled(self, enabled: bool) -> None:
        with self._lock:
            self._enabled = enabled
            if not enabled:
                for t in self._timers.values():
                    t.cancel()
                self._timers.clear()

    def initialize(self) -> None:
        """On becoming leader, arm a timer for every live node
        (heartbeat.go:14 initializeHeartbeatTimers)."""
        for node in self.server.fsm.state.nodes():
            if node.terminal_status():
                continue
            self.reset_timer(node.id)

    def ttl(self) -> float:
        cfg = self.server.config
        n = len(self._timers)
        spread = max(n / cfg.max_heartbeats_per_second, cfg.heartbeat_grace)
        return cfg.min_heartbeat_ttl + random.random() * spread

    def reset_timer(self, node_id: str) -> float:
        """(Re)arm the TTL timer; returns the TTL granted to the node."""
        with self._lock:
            if not self._enabled:
                return 0.0
            existing = self._timers.pop(node_id, None)
            if existing is not None:
                existing.cancel()
            ttl = self.ttl()
            self._timers[node_id] = self._wheel.schedule(
                ttl + self.server.config.heartbeat_grace,
                self._invalidate, node_id,
            )
            return ttl

    def clear_timer(self, node_id: str) -> None:
        with self._lock:
            timer = self._timers.pop(node_id, None)
            if timer is not None:
                timer.cancel()

    def _invalidate(self, node_id: str) -> None:
        """TTL expired without a heartbeat: node is down
        (heartbeat.go:84 invalidateHeartbeat). Runs on the wheel's
        dispatch pool — only bookkeeping here; the raft apply moves to
        the private pool."""
        with self._lock:
            self._timers.pop(node_id, None)
            if not self._enabled:
                return
        self._invalidate_pool.submit(self._apply_down, node_id)

    def _apply_down(self, node_id: str) -> None:
        if chaos.enabled:
            # 'drop' = the invalidation is lost once; re-arm the timer
            # so the node downs a full TTL late instead of never.
            # 'delay' sleeps here on the private pool thread (a raft
            # apply stuck behind a flapping leader).
            if chaos.fire("heartbeat.expire", node=node_id) == "drop":
                self.reset_timer(node_id)
                return
        # The apply may have sat queued behind raft-blocked workers for
        # a while: if the node heartbeated meanwhile (timer re-armed) or
        # leadership was lost, downing it now would be spurious.
        with self._lock:
            if not self._enabled or node_id in self._timers:
                return
        self.logger.warning("node %s TTL expired, marking down", node_id)
        try:
            self.server.node_update_status(node_id, consts.NODE_STATUS_DOWN)
        except Exception:
            self.logger.exception("failed to invalidate heartbeat for %s", node_id)

    def count(self) -> int:
        with self._lock:
            return len(self._timers)
