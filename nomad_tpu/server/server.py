"""Server: composition of the control plane + RPC-endpoint methods.

Reference: nomad/server.go:69 (Server, NewServer:169), leader-only
services (leader.go:108 establishLeadership), and the RPC endpoints
(job_endpoint.go, node_endpoint.go, eval_endpoint.go, plan_endpoint.go,
alloc_endpoint.go). In dev mode a single in-process server is its own
leader over a DevLog; the raft log replaces DevLog behind the same
apply() interface.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..scheduler import register_scheduler
from ..structs import (
    Allocation,
    Evaluation,
    Job,
    Node,
    Plan,
    PlanResult,
    consts,
    new_eval,
)
from ..utils.ids import generate_uuid
from ..utils.pool import WorkPool
from .. import trace
from . import fsm as fsm_msgs
from .blocked import BlockedEvals
from .broker import FAILED_QUEUE, EvalBroker
from ..gang import gang_stats as _gang_stats
from ..kernels.quality import get_board as _quality_board
from ..migrate import churn_stats as _churn_stats
from ..models.resident import device_state_stats as _device_state_stats
from ..profile import get_profiler as _get_profiler
from .config import ServerConfig
from .core_gc import CoreScheduler
from .fsm import FSM, DevLog
from .heartbeat import HeartbeatTimers
from .periodic import PeriodicDispatch
from .plan_apply import PlanApplier
from .plan_queue import PlanQueue
from .worker import Worker


class Server:
    def __init__(self, config: Optional[ServerConfig] = None):
        self.config = config or ServerConfig()
        self.logger = logging.getLogger("nomad_tpu.server")
        # Cluster TLS material (set_tls_contexts): None = plaintext.
        # Declared here so every construction path has the attributes —
        # a missing attribute would silently downgrade gossip and
        # leader forwarding to plaintext.
        self.tls_client_ctx = None  # outbound HTTP (leader/region/peers)
        self.tls_rpc_server_ctx = None  # gossip + raft mTLS, server side
        self.tls_rpc_client_ctx = None  # gossip + raft mTLS, client side

        self.fsm = FSM()
        self.log = DevLog(self.fsm)
        self.broker = EvalBroker(
            self.config.eval_nack_timeout, self.config.eval_delivery_limit,
            ready_cap=self.config.eval_ready_cap,
            ready_caps=self.config.eval_ready_caps,
        )
        self.blocked_evals = BlockedEvals(self.broker.enqueue_all)
        self.plan_queue = PlanQueue()
        self.plan_applier = PlanApplier(
            self.plan_queue, self.fsm, self.log,
            pool_size=self.config.plan_verify_workers,
        )
        self.heartbeats = HeartbeatTimers(self)
        self.periodic = PeriodicDispatch(self)
        self.workers: List[Worker] = []
        # Shared pool for drain-to-batch eval processing: batch members
        # must run concurrently (the batcher coalesces their blocked
        # place() calls into one device dispatch) but thread-per-eval at
        # storm rates is churn — a fixed ceiling of persistent daemon
        # workers serves every Worker's batches. Sized so every worker's
        # full drain fits at once: a dequeued eval queued behind other
        # workers' batches would hold its broker lease past the nack
        # clock and miss its batch's dispatch window.
        self.eval_pool = WorkPool(
            max(2, min(192, max(
                self.config.num_schedulers
                * max(1, self.config.eval_batch_size - 1),
                # The dispatch pipeline fans a full batch out per
                # in-flight slot; a pool smaller than that would strand
                # batch members behind their own batch's dispatch. +1
                # per slot for the launch prologue itself — it runs on
                # this pool too (the dispatcher thread must never
                # block), and its FSM catch-up may stall the full
                # wait-for-index timeout.
                (self.config.eval_batch_size + 1)
                * max(1, self.config.dispatch_max_inflight)))),
            name="eval-batch")
        # Central dispatch pipeline for dense-path evals (dispatch/):
        # workers hand dense evals here; the pipeline drains the rest
        # of the broker centrally, packs full device batches, and
        # folds plan-conflict retries back into the accumulating batch.
        from ..dispatch import DispatchPipeline

        self.dispatch = DispatchPipeline(self)
        # Scheduler executive (server/executive.py): the batched
        # event-loop replacement for thread-per-eval dense scheduling —
        # behind `scheduler_executive` (the pipeline+worker fan-out
        # stays the default for A/B). Constructed unconditionally so
        # stats()/endpoints always have the surface.
        from .executive import SchedulerExecutive

        self.executive = SchedulerExecutive(self)
        # Overload protection (nomad_tpu/admission): pressure monitor +
        # token-bucket intake control; the HTTP layer and the TCP
        # transport consult it per request. The device-path breaker is
        # process-global (it guards the one shared device, like the
        # batcher); configure() updates thresholds without un-tripping.
        from ..admission import AdmissionController, get_breaker

        self.admission = AdmissionController(self, self.config)
        get_breaker().configure(
            failure_threshold=self.config.breaker_failure_threshold,
            slow_ms=self.config.breaker_slow_ms,
            slow_batches=self.config.breaker_slow_batches,
            cooldown=self.config.breaker_cooldown,
            enabled=self.config.breaker_enabled,
        )
        # Contention observatory (nomad_tpu/profile): process-global
        # like the recorder; configure() flips recording and the GIL
        # sampler without dropping lock registrations.
        _get_profiler().configure(
            enabled=self.config.profile_enabled,
            sampler_interval=self.config.gil_sampler_interval,
        )
        # Device-resident node state (models/resident.py): process-
        # global like the breaker and the batcher's device cache it
        # fronts; configure() updates policy without dropping counters.
        from ..models.resident import configure as configure_resident

        configure_resident(
            enabled=self.config.device_resident,
            rebuild_rows=self.config.resident_rebuild_rows,
        )
        # Placement kernel (nomad_tpu/kernels): validate HERE, not at
        # first eval — a typo'd placement_kernel must fail server init
        # loudly with the registered-kernel list, the same contract as
        # an unknown scheduler factory. The active kernel is process-
        # global (like the batcher whose dispatches it shapes), so
        # only an EXPLICIT choice (placement_kernel is not None —
        # "greedy" included) flips it: a default-configured Server in
        # this process must not silently reset another's kernel.
        from ..kernels import configure as configure_kernels

        configure_kernels(self.config.placement_kernel)
        # Churn control (nomad_tpu/migrate): the migration budget and
        # the preemption policy are process-global like the breaker;
        # the pressure probe points preemption eligibility at THIS
        # server's admission signal (PR 5) — preemption only ever
        # fires on a red cluster.
        from ..migrate import configure as configure_migrate

        configure_migrate(
            migrate_max_parallel=self.config.migrate_max_parallel,
            preemption_enabled=self.config.preemption_enabled,
            preempt_priority_threshold=self.config.preempt_priority_threshold,
            pressure_probe=self.admission.level,
        )
        # Continuous defragmentation (nomad_tpu/defrag): the leader-
        # side optimizer loop. Constructed unconditionally (stats
        # surface); it only optimizes while defrag_enabled AND this
        # server leads AND the admission monitor reads green.
        from ..defrag import DefragLoop

        self.defrag = DefragLoop(self)
        # Read plane (nomad_tpu/readplane): the parked-watcher long-poll
        # multiplexer. Constructed unconditionally (stats surface); the
        # HTTP layer only parks continuations here while
        # read_mux_enabled — otherwise blocking queries fall back to
        # the thread-parking loop (the bench baseline arm). The store
        # accessor is a callable because FSM snapshot-restore swaps the
        # StateStore instance.
        from ..readplane import ReadMux

        self.read_mux = ReadMux(
            lambda: self.fsm.state,
            workers=self.config.read_mux_workers,
            max_parked=self.config.read_mux_max_parked,
        )
        self._leader = False
        self._shutdown = False
        self._gc_threads: List[threading.Timer] = []
        # Multi-server mode (start_with_raft): consensus node + peer
        # registry for leader-routed operations (the reference forwards
        # RPCs to the leader, rpc.go:178).
        self.raft = None
        self.cluster: Optional[Dict[str, "Server"]] = None
        self.node_id = self.config.node_name or "server-0"
        self._leadership_lock = threading.Lock()
        # Gossip membership (serf.go): peers is all known servers keyed
        # by region, local_peers the same-region subset — mirroring
        # server.go:100-104 peers/localPeers.
        self.serf = None
        self.peers: Dict[str, Dict[str, object]] = {}
        self._peers_lock = threading.Lock()
        # Raft membership changes triggered by gossip run here, never
        # on the serf event thread (they block on a raft commit).
        self._membership_pool = WorkPool(1, name="raft-membership")
        # Vault token authority (vault.go): the HTTP provider when an
        # address is configured, else the in-process stub so the
        # derive→renew→revoke lifecycle works without an external
        # service. Swappable via set_vault_provider.
        self.vault = None
        if self.config.vault_enabled:
            if self.config.vault_addr:
                from .vault import HTTPVaultProvider, VaultError

                provider = HTTPVaultProvider(
                    self.config.vault_addr, self.config.vault_token,
                    ttl=self.config.vault_token_ttl,
                    allowed_policies=self.config.vault_allowed_policies,
                )
                try:
                    # Startup check of our own token (vault.go
                    # establishConnection): surfaces a bad/revoked token
                    # now, not at the first task derive. Vault being
                    # temporarily down is not fatal — the renewal loop
                    # keeps retrying.
                    provider.validate()
                except VaultError as e:
                    self.logger.error("vault token validation failed: %s", e)
                provider.start_renewal()
                self.vault = provider
            else:
                from .vault import StubVault

                self.vault = StubVault(
                    ttl=self.config.vault_token_ttl,
                    allowed_policies=self.config.vault_allowed_policies,
                )

        self._register_core_scheduler()

    def set_vault_provider(self, provider) -> None:
        """Swap the token authority (tests; operators re-pointing vault
        without a restart)."""
        old = self.vault
        self.vault = provider
        if old is not None and hasattr(old, "stop"):
            old.stop()

    def _register_core_scheduler(self) -> None:
        server = self

        def factory(logger, state, planner, rng=None):
            return CoreScheduler(logger, state, planner, rng=rng, server=server)

        register_scheduler("_core", factory)

    # ------------------------------------------------------- lifecycle

    def start(self) -> None:
        """Dev mode: single server, immediately leader."""
        for i in range(self.config.num_schedulers):
            worker = Worker(self, i)
            self.workers.append(worker)
            worker.start()
        self.dispatch.start()
        self.executive.start()
        self.defrag.start()
        if self.config.read_mux_enabled:
            self.read_mux.start()
        self.establish_leadership()
        self._start_telemetry()

    def _start_telemetry(self) -> None:
        """Periodic broker/plan-queue/heartbeat gauges (the reference
        leader loops emit these via go-metrics, eval_broker.go:650,
        server.go:262-271)."""
        from ..utils import metrics

        if self.config.statsd_addr:
            metrics.get_metrics().add_statsd(self.config.statsd_addr)

        def emit():
            while not self._telemetry_stop.wait(self.config.telemetry_interval):
                try:
                    # Dispatch-pipeline gauges are per-server (the
                    # pipeline runs on followers too, forwarding plans
                    # to the leader), so they emit before the
                    # leader-only gate below.
                    if self.dispatch.enabled:
                        d = self.dispatch.stats()
                        metrics.set_gauge(
                            ("dispatch", "occupancy"), d["occupancy"])
                        metrics.set_gauge(
                            ("dispatch", "retries_per_eval"),
                            d["retries_per_eval"])
                        metrics.set_gauge(
                            ("dispatch", "in_flight"), d["in_flight"])
                        metrics.set_gauge(
                            ("dispatch", "pending"), d["pending"])
                    # Pressure level is per-server too (followers gate
                    # their own HTTP intake); snapshot() refreshes the
                    # cached level and emits the gauge itself.
                    self.admission.pressure.snapshot()
                    # Device-resident state is process-global (the
                    # batcher's device cache serves every server in
                    # this process): recompile storms (jit_cache_size
                    # climbing under steady load) and staleness
                    # rebuilds must be visible on a live agent, not
                    # just in bench.
                    ds = _device_state_stats()
                    metrics.set_gauge(
                        ("device_state", "jit_cache_size"),
                        ds["jit_cache_size"])
                    metrics.set_gauge(
                        ("device_state", "full_rebuilds"),
                        ds["full_rebuilds"])
                    metrics.set_gauge(
                        ("device_state", "stale_rebuilds"),
                        ds["stale_rebuilds"])
                    metrics.set_gauge(
                        ("device_state", "delta_updates"),
                        ds["delta_updates"])
                    metrics.set_gauge(
                        ("device_state", "upload_bytes"),
                        ds["upload_bytes"])
                    # Placement-quality gauges (kernels/quality.py):
                    # the active kernel's committed-plan medians plus
                    # the queueing p99, scrapeable at /v1/metrics so a
                    # kernel rollout's quality shift shows up on a
                    # dashboard, not just in bench.
                    pq = _quality_board().snapshot()
                    metrics.set_gauge(
                        ("placement_quality", "queueing_delay_ms"),
                        pq["queueing_delay_ms"])
                    for kname, q in pq["kernels"].items():
                        metrics.set_gauge(
                            ("placement_quality", kname,
                             "fragmentation"), q["fragmentation"])
                        metrics.set_gauge(
                            ("placement_quality", kname,
                             "binpack_score"), q["binpack_score"])
                    # Per-interval quality window (kernels/quality.py
                    # window_snapshot): each emission publishes the
                    # medians of the samples since the LAST emission
                    # then re-marks — the defrag fragmentation
                    # trajectory reads straight off /v1/metrics with
                    # no client-side delta math.
                    pw = _quality_board().window_snapshot(reset=True)
                    metrics.set_gauge(
                        ("placement_quality", "window",
                         "queueing_delay_ms"), pw["queueing_delay_ms"])
                    for kname, q in pw["kernels"].items():
                        metrics.set_gauge(
                            ("placement_quality", kname,
                             "window_fragmentation"),
                            q["fragmentation"])
                        metrics.set_gauge(
                            ("placement_quality", kname,
                             "window_binpack_score"),
                            q["binpack_score"])
                    # Continuous defragmentation (nomad_tpu/defrag):
                    # the loop's trajectory + gate counters, so an
                    # operator can see rounds/waves/moves and the
                    # last measured gain on a dashboard.
                    df = self.defrag.stats()
                    for gname in ("rounds", "waves", "waves_lost",
                                  "moves_proposed", "moves_completed",
                                  "pressure_skips", "stale_discards",
                                  "last_gain", "last_fragmentation",
                                  "last_solve_ms"):
                        metrics.set_gauge(("defrag", gname), df[gname])
                    if not self._leader:
                        # Broker/plan-queue/heartbeats are leader-only
                        # (eval_broker.go:650 runs in the leader loop);
                        # followers emitting zeros would clobber the
                        # leader's gauges in shared sinks.
                        continue
                    broker = self.broker.stats()
                    metrics.set_gauge(("broker", "shed"), broker["shed"])
                    metrics.set_gauge(("broker", "expired"), broker["expired"])
                    metrics.set_gauge(("broker", "total_ready"), broker["total_ready"])
                    metrics.set_gauge(("broker", "total_unacked"), broker["total_unacked"])
                    metrics.set_gauge(("broker", "total_blocked"), broker["total_blocked"])
                    metrics.set_gauge(
                        ("blocked_evals", "total_blocked"),
                        self.blocked_evals.stats()["total_blocked"],
                    )
                    metrics.set_gauge(("plan", "queue_depth"), self.plan_queue.depth())
                    metrics.set_gauge(("heartbeat", "active"), self.heartbeats.count())
                except Exception:  # noqa: BLE001 — telemetry must not die
                    self.logger.exception("telemetry emit failed")

        self._telemetry_stop = threading.Event()
        t = threading.Thread(target=emit, name="telemetry", daemon=True)
        t.start()
        self._telemetry_thread = t

    def start_with_raft(self, node_id: str, peers: List[str], transport,
                        cluster: Dict[str, "Server"],
                        data_dir: str = "",
                        snapshot_threshold: int = 1024) -> None:
        """Multi-server mode: leadership follows raft elections. With a
        data_dir the raft log/meta persist and the FSM snapshots with
        compaction (reference: raft-boltdb + fsm.go snapshots)."""
        from .raft import RaftLog, RaftNode

        storage = None
        if data_dir:
            from .raft_storage import RaftStorage
            from .transport import _encode_payload, fsm_payload_decoder

            storage = RaftStorage(
                data_dir,
                encode=lambda mt, p: _encode_payload(p),
                decode=fsm_payload_decoder,
            )
        self.node_id = node_id
        self.cluster = cluster
        cluster[node_id] = self
        self.raft = RaftNode(
            node_id, peers, transport, self.fsm.apply,
            self._leadership_changed,
            fsm_snapshot=self.fsm.snapshot_data,
            fsm_restore=self.fsm.restore,
            storage=storage,
            snapshot_threshold=snapshot_threshold if storage else 0,
        )
        self.log = RaftLog(self.raft)
        self.plan_applier.log = self.log
        transport.register(self.raft)
        # RPC intake admission (raft + leader-forward kinds exempt;
        # transport.py _dispatch). Plain attribute assignment: inmem
        # test transports simply never consult it.
        transport.admission = self.admission
        for i in range(self.config.num_schedulers):
            worker = Worker(self, i)
            self.workers.append(worker)
            worker.start()
        self.dispatch.start()
        self.executive.start()
        self.defrag.start()
        if self.config.read_mux_enabled:
            self.read_mux.start()
        self.raft.start()
        threading.Thread(target=self._membership_reconcile_loop,
                         name="raft-membership-sweep", daemon=True).start()
        self._start_telemetry()

    def setup_raft_cluster(self, transport, raft_addr: str, expect: int,
                           data_dir: str = "",
                           snapshot_threshold: int = 1024) -> None:
        """Form a raft cluster through gossip: wait until
        `bootstrap_expect` same-region servers advertise a raft address
        in their serf tags, then start raft over that seed peer set
        (server.go bootstrap_expect + leader.go peer wiring). Until
        then, writes fail with no-leader.

        The seed set only bootstraps: afterwards gossip drives dynamic
        membership (_reconcile_raft_member -> raft add_peer/remove_peer),
        so servers can join an established cluster late — the leader
        adds them and replication corrects their seed config."""
        from .raft import UnavailableLog

        self.log = UnavailableLog()
        self.plan_applier.log = self.log

        def wait_and_start():
            while not self._shutdown:
                members = [
                    m for m in self.serf_members()
                    if getattr(m, "region", None) == self.config.region
                    and getattr(m, "status", "alive") == "alive"
                ]
                addrs = sorted(
                    {m.tags.get("rpc_addr") for m in members
                     if m.tags.get("rpc_addr")} | {raft_addr}
                )
                if len(addrs) >= expect:
                    self.logger.info(
                        "raft bootstrap reached %d servers: %s",
                        len(addrs), addrs)
                    self.start_with_raft(
                        raft_addr, addrs, transport, {},
                        data_dir=data_dir,
                        snapshot_threshold=snapshot_threshold)
                    return
                time.sleep(0.5)

        threading.Thread(target=wait_and_start, daemon=True,
                         name="raft-bootstrap").start()

    def _leadership_changed(self, is_leader: bool) -> None:
        # Serialized: elections can flap faster than the services
        # start/stop.
        with self._leadership_lock:
            if is_leader:
                self.establish_leadership()
            else:
                self.revoke_leadership()

    def _leader_server(self) -> Optional["Server"]:
        """The server object currently holding leadership (self in dev
        mode). Leader-only operations route through this."""
        if self._leader or self.cluster is None:
            return self
        leader_id = self.raft.leader_id if self.raft is not None else None
        if leader_id is None:
            return None
        return self.cluster.get(leader_id)

    def leader_http_addr(self) -> Optional[str]:
        """The leader's advertised HTTP address, resolved through serf
        tags (how followers route to the leader in TCP mode)."""
        leader_id = self.raft.leader_id if self.raft is not None else None
        if leader_id is None:
            return None
        for m in self.serf_members():
            if m.tags.get("rpc_addr") == leader_id:
                return m.tags.get("http_addr") or None
        return None

    def _remote_leader(self):
        """Remote-leader proxy for TCP multi-server mode (rpc.go:178
        forward): used when the leader isn't an in-process Server."""
        addr = self.leader_http_addr()
        if addr is None:
            return None
        from .leader_client import RemoteLeader

        cached = getattr(self, "_remote_leader_cache", None)
        if cached is None or cached.addr != addr.rstrip("/"):
            cached = RemoteLeader(addr, ssl_context=self.tls_client_ctx)
            self._remote_leader_cache = cached
        return cached

    def _reset_heartbeat(self, node_id: str) -> float:
        leader = self._leader_server()
        if leader is not None:
            return leader.heartbeats.reset_timer(node_id)
        remote = self._remote_leader()
        if remote is not None:
            return remote.heartbeat_reset(node_id)
        return 0.0

    def _clear_heartbeat(self, node_id: str) -> None:
        leader = self._leader_server()
        if leader is not None:
            leader.heartbeats.clear_timer(node_id)

    def shutdown(self) -> None:
        self._shutdown = True
        if getattr(self, "_telemetry_stop", None) is not None:
            self._telemetry_stop.set()
        self.revoke_leadership()
        if self.serf is not None:
            self.serf.shutdown()
        if self.raft is not None:
            self.raft.stop()
        self.dispatch.stop()
        self.executive.stop()
        self.defrag.stop()
        self.read_mux.stop()
        for w in self.workers:
            w.stop()
        if self.vault is not None and hasattr(self.vault, "stop"):
            self.vault.stop()  # own-token renewal loop

    def is_leader(self) -> bool:
        return self._leader

    def read_staleness(self) -> tuple:
        """(last_contact_ms, known_leader) for `?stale` read headers:
        how old this replica's view may be (0.0 while leading or in
        dev mode — the local store IS the authority) and whether a
        leader is currently known."""
        if self._leader:
            return 0.0, True
        raft = self.raft
        if raft is None:
            # Dev mode never revokes leadership; a non-leader without
            # raft is mid-shutdown — report unknown.
            return 0.0, False
        return raft.last_contact() * 1000.0, raft.leader_id is not None

    def wait_consistent(self, timeout: float = 5.0) -> None:
        """`?consistent` read barrier: block until the local FSM has
        applied the leader's last-known commit index (read-your-writes
        on a follower without forwarding the read). No-op on the
        leader/dev server, whose FSM is the commit authority."""
        raft = self.raft
        if raft is None or self._leader:
            return
        self._wait_applied(raft.known_commit_index(), timeout=timeout)

    # ---------------------------------------------------- serf/federation

    def setup_serf(self, host: str = "127.0.0.1", port: int = 0,
                   http_addr: str = "", rpc_addr: str = "") -> str:
        """Join the gossip pool, advertising this server's addresses.

        Reference: server.go:740-760 (setupSerf tags) + serf.go
        (serfEventHandler maintaining peers/localPeers).
        """
        from .serf import ALIVE, LEFT, Serf

        def on_event(event: str, member) -> None:
            with self._peers_lock:
                region_peers = self.peers.setdefault(member.region, {})
                if member.status == ALIVE:
                    region_peers[member.name] = member
                else:
                    region_peers.pop(member.name, None)
                    if not region_peers:
                        self.peers.pop(member.region, None)
            # Off the gossip thread: add/remove_peer waits for a raft
            # commit (up to APPLY_TIMEOUT) and blocking here would
            # freeze probing — missed acks would mark healthy members
            # failed.
            self._membership_pool.submit(self._reconcile_raft_member, member)

        self.serf = Serf(
            name=f"{self.node_id}.{self.config.region}",
            region=self.config.region,
            datacenter=self.config.datacenter,
            tags={
                "role": "nomad",
                "http_addr": http_addr,
                "rpc_addr": rpc_addr,
                "bootstrap_expect": str(self.config.bootstrap_expect),
            },
            on_event=on_event,
            # Gossip rides the same mTLS material as raft: its member
            # records carry the addresses forwarding trusts.
            ssl_server_ctx=self.tls_rpc_server_ctx,
            ssl_client_ctx=self.tls_rpc_client_ctx,
        )
        return self.serf.serve(host, port)

    def _reconcile_raft_member(self, member) -> None:
        """Gossip drives raft membership on the leader (leader.go:491
        reconcileMember -> :551 addRaftPeer / :577 removeRaftPeer):
        a same-region server joining with a raft address is added as a
        peer; one that LEAVES is removed (failures are transient and do
        not shrink the quorum, matching the reference). Serf fires an
        event only on the status TRANSITION, so a miss here (no leader
        yet, or a config change in flight) is not redelivered — the
        periodic sweep in _membership_reconcile_loop retries until the
        cluster converges (the reference reconciles on its leader-loop
        interval too, leader.go:47-60)."""
        from .serf import ALIVE, LEFT

        if self.raft is None or not self.raft.is_leader():
            return
        if getattr(member, "region", None) != self.config.region:
            return
        rpc_addr = member.tags.get("rpc_addr") if member.tags else None
        if not rpc_addr or rpc_addr == self.raft.node_id:
            return
        try:
            if member.status == ALIVE:
                self.raft.add_peer(rpc_addr)
            elif member.status == LEFT:
                self.raft.remove_peer(rpc_addr)
        except Exception as e:  # noqa: BLE001
            self.logger.warning(
                "raft membership reconcile for %s failed (periodic sweep"
                " will retry): %s", rpc_addr, e)

    def _membership_reconcile_loop(self, interval: float = 5.0) -> None:
        """Leader-only periodic sweep over the serf member list: the
        event-driven path can miss transitions (see above), and
        add_peer/remove_peer are no-ops when already converged, so the
        sweep is cheap."""
        while not self._shutdown:
            time.sleep(interval)
            try:
                if self.raft is None or not self.raft.is_leader():
                    continue
                for member in self.serf_members():
                    self._reconcile_raft_member(member)
            except Exception:  # noqa: BLE001 - sweep must survive
                self.logger.exception("membership reconcile sweep failed")

    def serf_join(self, addrs: List[str]) -> int:
        if self.serf is None:
            raise ValueError("serf not configured on this server")
        return self.serf.join(addrs)

    def serf_members(self) -> List[object]:
        return self.serf.members() if self.serf is not None else []

    def serf_force_leave(self, name: str) -> bool:
        if self.serf is None:
            return False
        return self.serf.force_leave(name)

    def regions(self) -> List[str]:
        """Sorted known regions (region_endpoint.go:13)."""
        with self._peers_lock:
            known = set(self.peers.keys())
        known.add(self.config.region)
        return sorted(known)

    def peer_http_addr(self, region: str) -> Optional[str]:
        """An HTTP address of some alive server in the region, for
        cross-region request forwarding (rpc.go:263 forwardRegion picks
        a random server)."""
        import random as _random

        with self._peers_lock:
            members = list(self.peers.get(region, {}).values())
        candidates = [m.tags.get("http_addr") for m in members]
        candidates = [a for a in candidates if a]
        return _random.choice(candidates) if candidates else None

    def establish_leadership(self) -> None:
        """Enable leader-only services and restore their state
        (leader.go:108)."""
        self._leader = True
        self.plan_queue.set_enabled(True)
        self.plan_applier.start()
        self.broker.set_enabled(True)
        self.blocked_evals.set_enabled(True)
        self.fsm.broker = self.broker
        self.fsm.blocked_evals = self.blocked_evals
        self.fsm.periodic = self.periodic
        self.periodic.set_enabled(True)
        self.heartbeats.set_enabled(True)
        self.heartbeats.initialize()
        self._restore_evals()
        self._restore_periodic()
        self._schedule_gc()
        self._start_eval_hygiene()
        # Pause 3/4 of the workers on the leader (leader.go:111-117).
        if len(self.workers) > 1:
            for w in self.workers[: len(self.workers) * 3 // 4]:
                w.set_pause(True)

    def revoke_leadership(self) -> None:
        self._leader = False
        # Drain FIRST, while the broker still accepts nacks: the
        # pipeline's/executive's accumulated evals go back to the ready
        # queue (or, on a real flap where the broker flushes anyway,
        # fail cleanly and re-seed from raft state via the new leader's
        # _restore_evals) — either way no eval is lost with the batch.
        self.dispatch.drain()
        self.executive.drain()
        # The defrag loop pauses itself on is_leader() per tick; the
        # explicit abandon here returns its wave's governor slots NOW
        # instead of on the next tick (the new leader's drain storms
        # should not find the budget pre-spent by a ghost wave).
        self.defrag._abandon_wave("leadership-revoked")
        self._stop_eval_hygiene()
        for timer in self._gc_threads:
            timer.cancel()
        self._gc_threads = []
        self.fsm.broker = None
        self.fsm.blocked_evals = None
        self.fsm.periodic = None
        self.broker.set_enabled(False)
        self.blocked_evals.set_enabled(False)
        self.plan_applier.stop()
        self.plan_queue.set_enabled(False)
        self.periodic.set_enabled(False)
        self.heartbeats.set_enabled(False)
        for w in self.workers:
            w.set_pause(False)

    def _restore_evals(self) -> None:
        """Re-seed broker/blocked-evals from state on failover
        (leader.go:192 restoreEvals)."""
        for ev in self.fsm.state.evals():
            if ev.should_enqueue():
                self.broker.enqueue(ev)
            elif ev.should_block():
                self.blocked_evals.block(ev)

    def _restore_periodic(self) -> None:
        for job in self.fsm.state.jobs_by_periodic(True):
            self.periodic.add(job)

    # ------------------------------------------------------ eval hygiene

    def _start_eval_hygiene(self) -> None:
        """Leader-only janitors (leader.go:369 reapFailedEvaluations,
        :407 reapDupBlockedEvaluations, :441 periodicUnblockFailedEvals):
        without them, delivery-limit evals sit in the broker's `_failed`
        queue forever and displaced duplicate blocked evals leak in the
        state store as pending-looking work."""
        # The epoch's stop event rides in as a thread ARG: reading
        # self._hygiene_stop from the thread body would race a fast
        # revoke->re-establish (the body could bind the NEW epoch's
        # event and never see its own stop, leaving duplicate janitors
        # racing on the failed queue).
        stop = threading.Event()
        self._hygiene_stop = stop
        self._hygiene_threads = [
            threading.Thread(target=self._reap_failed_evals, args=(stop,),
                             daemon=True, name="eval-reap-failed"),
            threading.Thread(target=self._blocked_evals_hygiene,
                             args=(stop,),
                             daemon=True, name="eval-reap-dup"),
        ]
        for t in self._hygiene_threads:
            t.start()

    def _stop_eval_hygiene(self) -> None:
        stop = getattr(self, "_hygiene_stop", None)
        if stop is not None:
            stop.set()

    def _reap_failed_evals(self, stop: threading.Event) -> None:
        """Mark delivery-limit evals status=failed through raft, then
        ack them out of the broker. On a raft error the eval stays
        unacked — its nack timer re-parks it on the failed queue and a
        later pass retries."""
        while self._leader and not self._shutdown and not stop.is_set():
            ev, token = self.broker.dequeue([FAILED_QUEUE], timeout=0.5)
            if ev is None:
                continue
            updated = ev.copy()
            updated.status = consts.EVAL_STATUS_FAILED
            if not updated.status_description:
                # Dead-lettered evals arrive pre-stamped by the broker
                # (delivery count + original trigger); keep that richer
                # reason and only synthesize one for legacy parks.
                updated.status_description = (
                    "evaluation reached delivery limit "
                    f"({self.config.eval_delivery_limit})")
            try:
                self.eval_update([updated])
                self.broker.ack(ev.id, token)
            except Exception:  # noqa: BLE001 - leader flap mid-reap
                self.logger.exception("failed-eval reap of %s", ev.id)

    def _blocked_evals_hygiene(self, stop: threading.Event) -> None:
        """Cancel duplicate blocked evals (newer eval displaced them in
        BlockedEvals) and periodically release max-plan-failure evals
        back to the ready queue."""
        next_unblock = (
            time.monotonic() + self.config.failed_eval_unblock_interval)
        while self._leader and not self._shutdown and not stop.is_set():
            dups = self.blocked_evals.get_duplicates()
            if dups:
                cancelled = []
                for ev in dups:
                    upd = ev.copy()
                    upd.status = consts.EVAL_STATUS_CANCELLED
                    upd.status_description = (
                        "evaluation is outdated: duplicate blocked eval")
                    cancelled.append(upd)
                try:
                    self.eval_update(cancelled)
                except Exception:  # noqa: BLE001 - leader flap mid-reap
                    self.logger.exception("duplicate blocked-eval reap")
            if time.monotonic() >= next_unblock:
                next_unblock = (time.monotonic()
                                + self.config.failed_eval_unblock_interval)
                self.blocked_evals.unblock_failed()
            stop.wait(0.1)

    # ------------------------------------------------------------ jobs

    def job_register(
        self, job: Job, triggered_by: str = consts.EVAL_TRIGGER_JOB_REGISTER,
        enforce_index: bool = False, job_modify_index: int = 0,
    ) -> Tuple[str, int]:
        """Job.Register (job_endpoint.go:41): validate, optionally gate
        on the job-modify index (:60-79, the `plan`→`run -check-index`
        safe-deploy flow), commit the job, then commit its evaluation
        (periodic parents get no eval)."""
        job.canonicalize()
        errors = job.validate()
        if errors:
            raise ValueError("; ".join(errors))
        # Vault policy check at submit time (job_endpoint.go:84-120):
        # reject jobs asking for policies the authority won't grant, so
        # the failure surfaces at register instead of at task prestart.
        for tg in job.task_groups:
            for task in tg.tasks:
                if task.vault is None:
                    continue
                if self.vault is None:
                    raise ValueError(
                        f"task {task.name!r} has a vault block but vault "
                        "is not enabled"
                    )
                if not task.vault.policies:
                    raise ValueError(
                        f"task {task.name!r} vault block needs policies"
                    )
                if "root" in task.vault.policies:
                    raise ValueError("root policy is not allowed for tasks")
                allowed = getattr(self.vault, "allowed_policies", None)
                if allowed is not None:
                    bad = [p for p in task.vault.policies if p not in allowed]
                    if bad:
                        raise ValueError(f"vault policies not allowed: {bad}")
        # The enforce-index gate is decided inside the FSM apply (same
        # log position -> same verdict on every replica), which makes
        # check+commit atomic even when this server is a raft follower
        # forwarding the write to the leader.
        payload = {"job": job}
        if enforce_index:
            payload["enforce_index"] = True
            payload["job_modify_index"] = job_modify_index
        index = self.log.apply(fsm_msgs.JOB_REGISTER, payload)
        if enforce_index:
            self._wait_applied(index)
            err = self.fsm.outcome(index)
            if err is not None:
                raise ValueError(str(err))

        if job.is_periodic():
            return "", index

        stored = self.fsm.state.job_by_id(job.id)
        ev = new_eval(stored, triggered_by)
        self.eval_update([ev])
        return ev.id, index

    def _wait_applied(self, index: int, timeout: float = 5.0) -> None:
        """Wait until the local FSM has applied `index` (a follower's
        FSM lags the leader commit it just forwarded)."""
        from ..utils.backoff import poll_until

        if not poll_until(lambda: self.fsm.last_applied_index >= index,
                          timeout, base=0.005, max_delay=0.1):
            raise TimeoutError(f"timed out waiting for index {index}")

    def job_deregister(self, job_id: str, create_eval: bool = True) -> Optional[str]:
        job = self.fsm.state.job_by_id(job_id)
        self.log.apply(fsm_msgs.JOB_DEREGISTER, {"job_id": job_id})
        if not create_eval or job is None or job.is_periodic():
            return None
        ev = Evaluation(
            id=generate_uuid(),
            priority=job.priority,
            type=job.type,
            triggered_by=consts.EVAL_TRIGGER_JOB_DEREGISTER,
            job_id=job_id,
            job_modify_index=job.job_modify_index,
            status=consts.EVAL_STATUS_PENDING,
        )
        self.eval_update([ev])
        return ev.id

    def job_evaluate(self, job_id: str) -> str:
        """Job.Evaluate: force a new evaluation (job_endpoint.go:236)."""
        job = self.fsm.state.job_by_id(job_id)
        if job is None:
            raise ValueError(f"job {job_id!r} not found")
        if job.is_periodic():
            raise ValueError("can't evaluate periodic job")
        ev = new_eval(job, consts.EVAL_TRIGGER_JOB_REGISTER)
        self.eval_update([ev])
        return ev.id

    def job_plan(self, job: Job, diff: bool = False, contextual: bool = False) -> dict:
        """Job.Plan dry-run (job_endpoint.go:545): run a real scheduler
        against a snapshot through the Harness; nothing commits."""
        from ..scheduler.testing import Harness

        job.canonicalize()
        errors = job.validate()
        if errors:
            raise ValueError("; ".join(errors))

        # Shadow copy of state with the updated job injected at index+1;
        # the real store is never written (job_endpoint.go:584).
        from ..state import StateStore

        snap_store = self.fsm.state
        shadow_store = StateStore.restore(snap_store.persist())
        # The shadow store is a private dry-run copy seeded from a
        # snapshot — nothing it absorbs is replicated state, so the
        # raft-funnel rule does not apply to this write.
        shadow_store.upsert_job(  # nta: disable=raft-funnel
            snap_store.latest_index() + 1, job)
        harness = Harness(state=shadow_store)
        harness._next_index = shadow_store.latest_index() + 1

        ev = new_eval(shadow_store.job_by_id(job.id), consts.EVAL_TRIGGER_JOB_REGISTER)
        ev.annotate_plan = True

        factory = self.config.factory_for(job.type)
        from ..scheduler import new_scheduler

        sched = new_scheduler(factory, self.logger, shadow_store.snapshot(), harness)
        sched.process_eval(ev)

        annotations = None
        failed = {}
        if harness.plans:
            plan = harness.plans[-1]
            if plan.annotations is not None:
                annotations = plan.annotations
            failed = plan.failed_tg_allocs
        if harness.evals:
            failed = harness.evals[-1].failed_tg_allocs or failed

        old_job = snap_store.job_by_id(job.id)
        result = {
            "annotations": annotations,
            "failed_tg_allocs": failed,
            "next_periodic_launch": (
                job.periodic.next_launch(time.time()) if job.is_periodic() else None
            ),
            "index": snap_store.latest_index(),
            # Gate value for `run -check-index` (job_endpoint.go:626-630).
            "job_modify_index": old_job.job_modify_index if old_job is not None else 0,
        }
        if diff:
            from ..structs.diff import annotate as annotate_diff
            from ..structs.diff import job_diff

            jd = job_diff(old_job, job, contextual=contextual)
            annotate_diff(jd, annotations)
            result["diff"] = jd
        return result

    # ----------------------------------------------------------- nodes

    def node_register(self, node: Node) -> float:
        """Node.Register (node_endpoint.go:51). Returns the heartbeat
        TTL granted."""
        if not node.id:
            raise ValueError("missing node ID")
        if not node.datacenter:
            raise ValueError("missing datacenter")
        if not node.secret_id:
            node.secret_id = generate_uuid()
        existing = self.fsm.state.node_by_id(node.id)
        self.log.apply(fsm_msgs.NODE_REGISTER, {"node": node})
        # Transitioning to ready re-schedules its jobs.
        if existing is not None and existing.status != node.status:
            self._create_node_evals(node.id)
        return self._reset_heartbeat(node.id)

    def node_deregister(self, node_id: str) -> None:
        self.log.apply(fsm_msgs.NODE_DEREGISTER, {"node_id": node_id})
        self._clear_heartbeat(node_id)

    def node_update_status(self, node_id: str, status: str) -> float:
        """Node.UpdateStatus (node_endpoint.go:272): commit the status,
        fan out evals for every affected job."""
        node = self.fsm.state.node_by_id(node_id)
        if node is None:
            raise ValueError(f"node {node_id!r} not found")
        if node.status != status:
            self.log.apply(
                fsm_msgs.NODE_UPDATE_STATUS,
                {"node_id": node_id, "status": status},
            )
            self._create_node_evals(node_id)
        if status == consts.NODE_STATUS_DOWN:
            self._clear_heartbeat(node_id)
            return 0.0
        return self._reset_heartbeat(node_id)

    def node_heartbeat(self, node_id: str, secret_id: str = "") -> float:
        node = self.fsm.state.node_by_id(node_id)
        if node is None:
            raise ValueError(f"node {node_id!r} not found")
        if secret_id and node.secret_id != secret_id:
            raise PermissionError("node secret ID does not match")
        if node.status != consts.NODE_STATUS_READY:
            return self.node_update_status(node_id, consts.NODE_STATUS_READY)
        return self._reset_heartbeat(node_id)

    def node_update_drain(self, node_id: str, drain: bool) -> None:
        """Node.UpdateDrain (node_endpoint.go:374)."""
        node = self.fsm.state.node_by_id(node_id)
        if node is None:
            raise ValueError(f"node {node_id!r} not found")
        self.log.apply(
            fsm_msgs.NODE_UPDATE_DRAIN, {"node_id": node_id, "drain": drain}
        )
        if drain:
            self._create_node_evals(node_id)

    def derive_vault_token(
        self, node_id: str, secret_id: str, alloc_id: str, tasks: List[str]
    ) -> Tuple[Dict[str, str], float]:
        """Per-task vault token derivation (node_endpoint.go:940
        DeriveVaultToken): validate node secret + alloc placement + that
        each task declares a vault block, mint tokens, then commit the
        accessors through the log before handing tokens out. Returns
        ({task: token}, min ttl across minted tokens)."""
        from .vault import VaultAccessor, VaultError

        if self.vault is None:
            raise ValueError("vault is not enabled on this server")
        state = self.fsm.state
        node = state.node_by_id(node_id)
        if node is None:
            raise ValueError(f"node {node_id!r} not found")
        # A node with a secret always requires it — an empty caller
        # secret must NOT bypass authentication (minting tokens is the
        # most sensitive endpoint on the server).
        if node.secret_id and node.secret_id != secret_id:
            raise PermissionError("node secret ID does not match")
        alloc = state.alloc_by_id(alloc_id)
        if alloc is None:
            raise ValueError(f"alloc {alloc_id!r} not found")
        if alloc.node_id != node_id:
            raise PermissionError("allocation not placed on requesting node")
        if alloc.terminal_status():
            raise ValueError("cannot derive tokens for terminal allocation")
        group = alloc.job.lookup_task_group(alloc.task_group) if alloc.job else None
        by_name = {t.name: t for t in (group.tasks if group else [])}
        tokens: Dict[str, str] = {}
        accessors: List[VaultAccessor] = []
        min_ttl = float("inf")
        for task_name in tasks:
            task = by_name.get(task_name)
            if task is None or task.vault is None:
                self.vault.revoke_tokens([a.accessor for a in accessors])
                raise ValueError(
                    f"task {task_name!r} does not declare a vault block"
                )
            try:
                token, accessor, ttl = self.vault.create_token(task.vault.policies)
            except VaultError as e:
                # Revoke tokens already minted this request — a partial
                # failure must not leave live untracked credentials.
                self.vault.revoke_tokens([a.accessor for a in accessors])
                raise ValueError(str(e)) from e
            min_ttl = min(min_ttl, ttl)
            tokens[task_name] = token
            accessors.append(
                VaultAccessor(
                    accessor=accessor, alloc_id=alloc_id,
                    task=task_name, node_id=node_id,
                    policies=list(task.vault.policies),
                )
            )
        # Accessors are committed before tokens are returned, so a
        # crash can't leak untracked (unrevokable) tokens.
        self.log.apply(
            fsm_msgs.VAULT_ACCESSOR_REGISTER, {"accessors": accessors}
        )
        return tokens, (min_ttl if tokens else 0.0)

    def vault_renew(self, token: str) -> float:
        from .vault import VaultError

        if self.vault is None:
            raise ValueError("vault is not enabled on this server")
        try:
            return self.vault.renew_token(token)
        except VaultError as e:
            raise ValueError(str(e)) from e

    def revoke_vault_accessors(self, accessors: List[str]) -> None:
        """Revoke at the authority, then drop the tracking rows
        (vault.go RevokeTokens + fsm deregister)."""
        if not accessors:
            return
        if self.vault is not None:
            self.vault.revoke_tokens(accessors)
        self.log.apply(
            fsm_msgs.VAULT_ACCESSOR_DEREGISTER, {"accessors": accessors}
        )

    def node_update_allocs(self, allocs: List[Allocation]) -> int:
        """Node.UpdateAlloc: client-reported status sync
        (node_endpoint.go:664)."""
        return self.log.apply(fsm_msgs.ALLOC_CLIENT_UPDATE, {"allocs": allocs})

    def _create_node_evals(self, node_id: str) -> List[str]:
        """One eval per job with allocs on the node, plus every system
        job (node_endpoint.go:812 createNodeEvals)."""
        node = self.fsm.state.node_by_id(node_id)
        node_index = node.modify_index if node else 0
        evals: List[Evaluation] = []
        seen_jobs = set()
        for alloc in self.fsm.state.allocs_by_node(node_id):
            if alloc.job_id in seen_jobs or alloc.job is None:
                continue
            seen_jobs.add(alloc.job_id)
            evals.append(
                Evaluation(
                    id=generate_uuid(),
                    priority=alloc.job.priority,
                    type=alloc.job.type,
                    triggered_by=consts.EVAL_TRIGGER_NODE_UPDATE,
                    job_id=alloc.job_id,
                    job_modify_index=alloc.job.job_modify_index,
                    node_id=node_id,
                    node_modify_index=node_index,
                    status=consts.EVAL_STATUS_PENDING,
                )
            )
        for job in self.fsm.state.jobs_by_scheduler(consts.JOB_TYPE_SYSTEM):
            if job.id in seen_jobs:
                continue
            evals.append(
                Evaluation(
                    id=generate_uuid(),
                    priority=job.priority,
                    type=job.type,
                    triggered_by=consts.EVAL_TRIGGER_NODE_UPDATE,
                    job_id=job.id,
                    job_modify_index=job.job_modify_index,
                    node_id=node_id,
                    node_modify_index=node_index,
                    status=consts.EVAL_STATUS_PENDING,
                )
            )
        if evals:
            self.eval_update(evals)
        return [e.id for e in evals]

    # ----------------------------------------------------------- evals

    def eval_update(self, evals: List[Evaluation], token: str = "") -> int:
        # Deadline stamping at the creation funnel: every fresh pending
        # eval passes through here before the FSM commit that enqueues
        # it. stamp() is a no-op on terminal/already-stamped evals, so
        # status re-commits of existing evals pass through untouched.
        ttl = self.config.eval_deadline_ttl
        if ttl > 0:
            from ..admission import deadline as _deadline

            now = time.time()
            for ev in evals:
                _deadline.stamp(ev, ttl, now)
        return self.log.apply(
            fsm_msgs.EVAL_UPDATE, {"evals": evals, "token": token}
        )

    def eval_dequeue(
        self, schedulers: List[str], timeout: float
    ) -> Tuple[Optional[Evaluation], str]:
        leader = self._leader_server()
        if leader is not None:
            return leader.broker.dequeue(schedulers, timeout)
        remote = self._remote_leader()
        if remote is not None:
            try:
                return remote.eval_dequeue(schedulers, timeout)
            except Exception:  # noqa: BLE001 - leader flap: retry later
                self.logger.debug(
                    "remote eval dequeue failed; retrying next loop",
                    exc_info=True)
        # Jittered: on a leader flap EVERY follower worker lands here —
        # a fixed interval would hammer the recovering leader in
        # lockstep (utils/backoff.py sleep_jittered).
        from ..utils.backoff import sleep_jittered

        sleep_jittered(min(timeout, 0.2))
        return None, ""

    def eval_dequeue_many(
        self, schedulers: List[str], max_n: int
    ) -> List[Tuple[Evaluation, str]]:
        """Non-blocking drain of additional ready evals (dense-backend
        batch path; see broker.dequeue_many). Followers forward to the
        leader over the keep-alive pool so their workers form device
        batches too — the dense backend's throughput must hold for N
        workers x all servers, not just leader-local ones."""
        if max_n <= 0:
            return []
        leader = self._leader_server()
        if leader is not None:
            return leader.broker.dequeue_many(schedulers, max_n)
        remote = self._remote_leader()
        if remote is not None:
            try:
                return remote.eval_dequeue_many(schedulers, max_n)
            except Exception:  # noqa: BLE001 - leader flap: batch later
                self.logger.debug(
                    "remote eval drain failed; batching later",
                    exc_info=True)
        return []

    def eval_ack(self, eval_id: str, token: str) -> None:
        leader = self._leader_server()
        if leader is not None:
            leader.broker.ack(eval_id, token)
            return
        remote = self._remote_leader()
        if remote is None:
            raise ValueError("no leader")
        remote.eval_ack(eval_id, token)

    def eval_nack(self, eval_id: str, token: str) -> None:
        leader = self._leader_server()
        if leader is not None:
            leader.broker.nack(eval_id, token)
            return
        remote = self._remote_leader()
        if remote is None:
            raise ValueError("no leader")
        remote.eval_nack(eval_id, token)

    def eval_pause_nack(self, eval_id: str, token: str) -> None:
        leader = self._leader_server()
        if leader is not None:
            leader.broker.pause_nack_timeout(eval_id, token)
            return
        remote = self._remote_leader()
        if remote is not None:
            remote.eval_pause_nack(eval_id, token)

    def eval_resume_nack(self, eval_id: str, token: str) -> None:
        leader = self._leader_server()
        if leader is not None:
            leader.broker.resume_nack_timeout(eval_id, token)
            return
        remote = self._remote_leader()
        if remote is not None:
            remote.eval_resume_nack(eval_id, token)

    def eval_outstanding(self, eval_id: str) -> Optional[str]:
        leader = self._leader_server()
        if leader is not None:
            return leader.broker.outstanding(eval_id)
        remote = self._remote_leader()
        if remote is not None:
            try:
                return remote.eval_outstanding(eval_id)
            except Exception:  # noqa: BLE001
                return None
        return None

    def eval_reap(self, eval_ids: List[str], alloc_ids: List[str]) -> int:
        # Reaped allocs take their derived vault tokens with them
        # (core_sched GC → vault.go RevokeTokens → accessor dereg).
        accessors = [
            a.accessor
            for alloc_id in alloc_ids
            for a in self.fsm.state.vault_accessors_by_alloc(alloc_id)
        ]
        self.revoke_vault_accessors(accessors)
        return self.log.apply(
            fsm_msgs.EVAL_DELETE, {"eval_ids": eval_ids, "alloc_ids": alloc_ids}
        )

    # ------------------------------------------------------------ plans

    def plan_submit(self, plan: Plan) -> PlanResult:
        """Plan.Submit (plan_endpoint.go:16). The eval token is the
        split-brain guard: it must still be the outstanding token."""
        leader = self._leader_server()
        if leader is None:
            remote = self._remote_leader()
            if remote is None:
                raise ValueError("no leader to submit plan to")
            return remote.plan_submit(plan)
        token = leader.broker.outstanding(plan.eval_id)
        if token != plan.eval_token:
            raise ValueError("plan's eval token does not match outstanding eval")
        pending = leader.plan_queue.enqueue(plan)
        return pending.wait(timeout=30.0)

    # --------------------------------------------------------- periodic

    def periodic_launch_record(self, job_id: str, launch: float) -> None:
        self.log.apply(
            fsm_msgs.PERIODIC_LAUNCH, {"job_id": job_id, "launch": launch}
        )

    def periodic_force(self, job_id: str) -> Optional[str]:
        leader = self._leader_server()
        if leader is None:
            raise ValueError("no leader")
        return leader.periodic.force_run(job_id)

    # --------------------------------------------------------------- gc

    def _core_eval(self, core_job_id: str) -> Evaluation:
        return Evaluation(
            id=generate_uuid(),
            priority=consts.CORE_JOB_PRIORITY,
            type=consts.JOB_TYPE_CORE,
            triggered_by=consts.EVAL_TRIGGER_SCHEDULED,
            job_id=core_job_id,
            status=consts.EVAL_STATUS_PENDING,
        )

    def force_gc(self) -> None:
        """System.GC endpoint (system_endpoint.go:16)."""
        leader = self._leader_server()
        if leader is None:
            raise ValueError("no leader")
        leader.broker.enqueue(leader._core_eval(consts.CORE_JOB_FORCE_GC))

    def _schedule_gc(self) -> None:
        """Leader GC timers enqueue core-job evals on their intervals
        (leader.go schedulePeriodic)."""

        def tick(core_job: str, interval: float):
            if not self._leader or self._shutdown:
                return
            self.broker.enqueue(self._core_eval(core_job))
            timer = threading.Timer(interval, tick, args=(core_job, interval))
            timer.daemon = True
            self._gc_threads.append(timer)
            timer.start()

        for core_job, interval in (
            (consts.CORE_JOB_EVAL_GC, self.config.eval_gc_interval),
            (consts.CORE_JOB_JOB_GC, self.config.job_gc_interval),
            (consts.CORE_JOB_NODE_GC, self.config.node_gc_interval),
        ):
            timer = threading.Timer(interval, tick, args=(core_job, interval))
            timer.daemon = True
            self._gc_threads.append(timer)
            timer.start()

    # ------------------------------------------------------------ stats

    def stats(self) -> Dict[str, object]:
        out = {
            "leader": self._leader,
            "last_index": self.log.last_index(),
            "broker": self.broker.stats(),
            "blocked_evals": self.blocked_evals.stats(),
            "plan_queue_depth": self.plan_queue.depth(),
            "heartbeat_timers": self.heartbeats.count(),
            "num_workers": len(self.workers),
            "dispatch_pipeline": self.dispatch.stats(),
            # Scheduler executive (server/executive.py): cohort sizes,
            # fast-vs-legacy lane split (with routing reasons), and
            # the drain/build/dispatch/finalize time breakdown.
            "scheduler_executive": self.executive.stats(),
            "plan_applier": self.plan_applier.stats(),
            # Overload-protection surface (nomad_tpu/admission):
            # pressure level + reasons, intake-bucket stats, and the
            # device-path breaker state.
            "admission": self.admission.snapshot(),
            # Per-stage eval-lifecycle latency table (nomad_tpu/trace):
            # count/mean/max + log-bucket p50/p95/p99 per stage, plus
            # the e2e row — the north-star p99, attributed.
            "trace": trace.get_recorder().stage_stats(),
            # Contention observatory (nomad_tpu/profile): per-site lock
            # wait/hold, GIL overshoot, run-queue delay, and the
            # batch-boundary convoy table. /v1/agent/profile adds the
            # ?lock=/?thread= drill-downs.
            "profile": _get_profiler().snapshot(),
            # Device-resident node state (models/resident.py): delta/
            # rebuild counters + the jit compile-cache size — a
            # CLIMBING cache under steady load is a recompile storm,
            # and stale_rebuilds says how often plan-apply verification
            # had to re-anchor the delta chain.
            "device_state": _device_state_stats(),
            # Placement-quality scoreboard (nomad_tpu/kernels/quality):
            # per-kernel fragmentation / bin-pack medians from the
            # dense paths' committed plans + the broker-wait queueing
            # p99 — how WELL the active kernel places, next to the
            # trace table's how-fast.
            "placement_quality": _quality_board().snapshot(),
            # Churn control (nomad_tpu/migrate): migration-budget
            # in-flight/high-water/deferral counters + preemption
            # staged/committed/placement tallies.
            "churn": _churn_stats(),
            # Continuous defragmentation (nomad_tpu/defrag): rounds/
            # waves/moves, gate skips (pressure/budget/stale), solve
            # cost split cold-vs-warm, and the compiled-program count.
            "defrag": self.defrag.stats(),
            # Gang scheduling (nomad_tpu/gang): gangs placed/rejected
            # per path; the applier-side whole-gang rejections live in
            # plan_applier stats ("gangs_rejected").
            "gang": _gang_stats(),
            # Read plane (nomad_tpu/readplane): parked continuations,
            # wake/spurious/served/timeout/write-error counters, and
            # the serve-pool depth.
            "read_mux": self.read_mux.stats(),
        }
        if self.raft is not None:
            # Term/commit/membership for operators (the reference's
            # Server.Stats exposes the raft section the same way,
            # server.go:915).
            out["raft"] = self.raft.stats()
        return out
