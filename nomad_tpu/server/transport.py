"""TCP/JSON raft transport for multi-host clusters.

Reference: the reference multiplexes raft streams over one TCP port
with a 1-byte protocol prefix (nomad/rpc.go:23-30, raft_rpc.go:33) and
POOLS yamux sessions per peer (nomad/pool.go:144) so replication fan-out
rides persistent connections. Here each message is one length-prefixed
JSON frame; connections are keep-alive and pooled per peer (a stale
pooled socket gets one retry on a fresh dial, utils/httppool.py's
discipline), and the whole channel optionally runs under mutual TLS
(utils/tlsutil.py; a plaintext or unauthenticated peer fails the
handshake, rpc.go:23-30 rpcTLS).
"""

from __future__ import annotations

import json
import logging
import socket
import socketserver
import ssl
import struct
import threading
from typing import Any, Dict, List, Optional, Tuple

from ..admission import AdmissionRejected
from ..chaos import chaos
from ..utils.backoff import Backoff
from ..utils.codec import from_dict, to_dict
from .raft import LogEntry, Transport

_HEADER = struct.Struct(">I")
CONNECT_TIMEOUT = 1.0
RPC_TIMEOUT = 5.0
# Server side: how long a pooled keep-alive connection may sit idle
# before its handler thread gives up on it. Heartbeat cadence is
# sub-second, so anything this quiet belongs to a departed peer.
IDLE_CONN_TIMEOUT = 300.0


def _send_frame(sock: socket.socket, obj: dict) -> None:
    data = json.dumps(obj).encode()
    sock.sendall(_HEADER.pack(len(data)) + data)


def _recv_frame(sock: socket.socket) -> Optional[dict]:
    header = _recv_exact(sock, _HEADER.size)
    if header is None:
        return None
    (length,) = _HEADER.unpack(header)
    data = _recv_exact(sock, length)
    if data is None:
        return None
    return json.loads(data)


def _recv_exact(sock: socket.socket, count: int) -> Optional[bytes]:
    buf = b""
    while len(buf) < count:
        chunk = sock.recv(count - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


def _encode_payload(payload: Any) -> Any:
    """Log payloads hold structs objects; encode them for the wire."""
    if isinstance(payload, dict):
        return {
            k: to_dict(v) if not isinstance(v, (str, int, float, bool, type(None))) else v
            for k, v in payload.items()
        }
    return to_dict(payload)


class TCPTransport(Transport):
    """Raft transport over TCP. The local node must call serve() with
    its bind address; peers are "host:port" strings.

    Note: JSON payload round-trips lose the structs object types, so
    multi-host mode requires typed payload decode hooks per message
    type; the decode_payload callback does that (the server wires it to
    the FSM's schema)."""

    MAX_IDLE_PER_PEER = 4

    def __init__(self, decode_payload=None,
                 ssl_server_ctx: Optional[ssl.SSLContext] = None,
                 ssl_client_ctx: Optional[ssl.SSLContext] = None):
        self.logger = logging.getLogger("nomad_tpu.raft.tcp")
        self.node: Optional[object] = None
        self.decode_payload = decode_payload or (lambda mt, p: p)
        self._server: Optional[socketserver.ThreadingTCPServer] = None
        self.addr: str = ""
        self.ssl_server_ctx = ssl_server_ctx
        self.ssl_client_ctx = ssl_client_ctx
        # Per-peer idle keep-alive connections (pool.go:144): one
        # socket per CONCURRENT in-flight RPC to a peer, reused across
        # sequential heartbeats/appends instead of a dial per message.
        self._pools: Dict[str, List[socket.socket]] = {}
        self._pool_lock = threading.Lock()
        self._closed = False
        self.dials = 0  # sockets ever opened (observability/tests)
        # RPC-intake admission control (nomad_tpu/admission), wired by
        # Server.start_with_raft. Raft consensus and leader-forward
        # kinds are exempt inside check_rpc — shedding append_entries
        # would turn overload into leader loss — so today this gates
        # only non-raft frames (future bulk/query kinds).
        self.admission = None

    # ------------------------------------------------------- serving

    def register(self, node) -> None:
        self.node = node

    def serve(self, host: str = "127.0.0.1", port: int = 0) -> str:
        transport = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                sock = self.request
                try:
                    # The idle read timeout bounds handler threads
                    # orphaned by peers that pooled a connection and
                    # then left the cluster — and it must be armed
                    # BEFORE the TLS handshake, or a peer that connects
                    # and never handshakes pins the thread forever.
                    sock.settimeout(IDLE_CONN_TIMEOUT)
                    # TLS terminates HERE, in the per-connection thread:
                    # wrapping in get_request would let one slow/failing
                    # handshake stall the accept loop for every peer.
                    if transport.ssl_server_ctx is not None:
                        sock = transport.ssl_server_ctx.wrap_socket(
                            sock, server_side=True)
                    # Keep-alive: serve frames until the peer hangs up —
                    # the client side pools this connection across
                    # heartbeats/appends instead of redialling.
                    while True:
                        msg = _recv_frame(sock)
                        if msg is None:
                            return
                        resp = transport._dispatch(msg)
                        _send_frame(sock, resp)
                except (OSError, ValueError, ssl.SSLError):
                    pass

        # Reuse-addr: an agent restarting on its configured port must
        # not fail on TIME_WAIT sockets from its previous run.
        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True

        self._server = Server((host, port), Handler)
        self._server.daemon_threads = True
        self.addr = "%s:%d" % self._server.server_address
        t = threading.Thread(
            target=self._server.serve_forever, name="raft-tcp", daemon=True
        )
        t.start()
        return self.addr

    def close(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
        with self._pool_lock:
            self._closed = True
            pools, self._pools = self._pools, {}
        for conns in pools.values():
            for sock in conns:
                try:
                    sock.close()
                except OSError:
                    pass

    def _dispatch(self, msg: dict) -> dict:
        kind = msg.get("kind")
        if self.node is None:
            return {"error": "node not ready"}
        if self.admission is not None:
            try:
                self.admission.check_rpc(kind)
            except AdmissionRejected as e:
                # Structured 503/429 analog for the frame protocol: the
                # caller sees a normal error frame plus the machine-
                # readable back-off hint, never a dropped connection.
                return {"error": e.message, "status": e.status,
                        "retry_after": round(e.retry_after, 3)}
        if kind == "request_vote":
            return self.node.handle_request_vote(msg["args"])
        if kind == "append_entries":
            args = msg["args"]
            args["entries"] = [
                LogEntry(
                    term=e["term"],
                    index=e["index"],
                    msg_type=e["msg_type"],
                    payload=self.decode_payload(e["msg_type"], e["payload"]),
                )
                for e in args["entries"]
            ]
            return self.node.handle_append_entries(args)
        if kind == "install_snapshot":
            return self.node.handle_install_snapshot(msg["args"])
        if kind == "forward_apply":
            index = self.node.apply(
                msg["msg_type"], self.decode_payload(msg["msg_type"], msg["payload"])
            )
            return {"index": index}
        return {"error": f"unknown kind {kind!r}"}

    # -------------------------------------------------------- client

    def _checkout(self, peer: str,
                  use_pool: bool = True) -> Tuple[Optional[socket.socket], bool]:
        """Returns (conn, pooled); dials when the idle pool is empty
        (or when the caller demands a fresh socket — the keep-alive
        retry must not pop ANOTHER stale pooled socket, or a restarted
        peer with several pooled sockets looks dead until the pool
        drains)."""
        if use_pool:
            with self._pool_lock:
                conns = self._pools.get(peer)
                if conns:
                    return conns.pop(), True
        host, port_s = peer.rsplit(":", 1)
        try:
            sock = socket.create_connection(
                (host, int(port_s)), timeout=CONNECT_TIMEOUT)
            if self.ssl_client_ctx is not None:
                sock = self.ssl_client_ctx.wrap_socket(
                    sock, server_hostname=host)
        except (OSError, ValueError, ssl.SSLError):
            return None, False
        with self._pool_lock:
            self.dials += 1
        return sock, False

    def forget_peer(self, peer: str) -> None:
        """Drop the idle pool for a peer that left the cluster; without
        this, every address ever contacted keeps up to
        MAX_IDLE_PER_PEER sockets open until process shutdown."""
        with self._pool_lock:
            conns = self._pools.pop(peer, [])
        for sock in conns:
            try:
                sock.close()
            except OSError:
                pass

    def _checkin(self, peer: str, sock: socket.socket) -> None:
        with self._pool_lock:
            # An RPC in flight during close() must not park its socket
            # in a pool nobody will drain again (httppool.py's _closed
            # discipline).
            if not self._closed:
                conns = self._pools.setdefault(peer, [])
                if len(conns) < self.MAX_IDLE_PER_PEER:
                    conns.append(sock)
                    return
        try:
            sock.close()
        except OSError:
            pass

    def _call(self, peer: str, msg: dict, timeout: float = RPC_TIMEOUT,
              connect_backoff: Optional[Backoff] = None) -> Optional[dict]:
        """One RPC round-trip. `connect_backoff` is a retry policy for
        DIAL failures only — a failed dial provably sent nothing, so
        retrying it can never double-deliver; exchange failures keep
        the single fresh-dial keep-alive retry and then fail to the
        caller (the frame may have been acted on)."""
        if chaos.enabled and chaos.fire("transport.send", peer=peer) == "drop":
            return None  # injected: request lost before the wire
        use_pool = True
        while True:
            sock, pooled = self._checkout(peer, use_pool=use_pool)
            if sock is None:
                # Dial failure: nothing was sent. Ride out a peer
                # restart / flap window when the caller asked for it.
                if connect_backoff is not None and connect_backoff.sleep():
                    continue
                return None
            try:
                sock.settimeout(timeout)
                _send_frame(sock, msg)
                resp = _recv_frame(sock)
                if resp is None:
                    raise OSError("peer closed connection")
            except (OSError, ValueError, ssl.SSLError) as e:
                try:
                    sock.close()
                except OSError:
                    pass
                # The peer may have dropped the idle socket between our
                # messages (keep-alive race): raft RPCs are idempotent
                # (term/index-guarded state machines), so one retry on
                # a fresh dial is safe. NOT after a timeout: a slow but
                # alive peer already burned the full RPC timeout, and
                # _broadcast_heartbeat iterates peers serially — a
                # retry would double the stall for every other
                # follower. The keep-alive race shows up as instant
                # EOF/RST, never as a timeout.
                is_timeout = isinstance(e, (socket.timeout, TimeoutError))
                if pooled and not is_timeout:
                    use_pool = False
                    continue
                return None
            if chaos.enabled and chaos.fire(
                    "transport.recv", peer=peer) == "drop":
                # Injected: response lost in flight. The request WAS
                # served; close the socket (its framing state is now
                # a lie for the pool) and report unreachable.
                try:
                    sock.close()
                except OSError:
                    pass
                return None
            self._checkin(peer, sock)
            return resp

    def request_vote(self, peer: str, args: dict) -> Optional[dict]:
        return self._call(peer, {"kind": "request_vote", "args": args})

    def install_snapshot(self, peer: str, args: dict) -> Optional[dict]:
        # FSM snapshot data is already wire-safe (state.persist() emits
        # plain dicts), so it ships as-is.
        return self._call(peer, {"kind": "install_snapshot", "args": args},
                          timeout=30.0)

    def append_entries(self, peer: str, args: dict) -> Optional[dict]:
        wire_args = dict(args)
        wire_args["entries"] = [
            {
                "term": e.term,
                "index": e.index,
                "msg_type": e.msg_type,
                "payload": _encode_payload(e.payload),
            }
            for e in args["entries"]
        ]
        return self._call(peer, {"kind": "append_entries", "args": wire_args})

    def forward_apply(self, peer: str, msg_type: str, payload: Any) -> int:
        # Dial-failure retries ride a jittered backoff: a follower
        # forwarding a write during a leader restart sees connection
        # refusals for the flap window — retrying those is free of
        # double-apply risk (nothing was sent), unlike exchange
        # failures, which _call never retries past the keep-alive race.
        resp = self._call(
            peer,
            {
                "kind": "forward_apply",
                "msg_type": msg_type,
                "payload": _encode_payload(payload),
            },
            connect_backoff=Backoff(base=0.05, max_delay=0.4, attempts=3),
        )
        if resp is None or "error" in resp:
            raise ConnectionError(
                f"forward to {peer} failed: {resp and resp.get('error')}"
            )
        return resp["index"]


def fsm_payload_decoder(msg_type: str, payload: Any) -> Any:
    """Decode wire payloads back into structs objects per message type
    (the typed half of the codec)."""
    from ..structs import Allocation, Evaluation, Job, Node
    from . import fsm as m

    if not isinstance(payload, dict):
        return payload
    out = dict(payload)
    if msg_type == m.NODE_REGISTER and "node" in out:
        out["node"] = from_dict(Node, out["node"])
    elif msg_type == m.JOB_REGISTER and "job" in out:
        out["job"] = from_dict(Job, out["job"])
    elif msg_type == m.EVAL_UPDATE and "evals" in out:
        out["evals"] = [from_dict(Evaluation, e) for e in out["evals"]]
    elif msg_type in (m.ALLOC_UPDATE, m.ALLOC_CLIENT_UPDATE):
        if out.get("allocs"):
            out["allocs"] = [from_dict(Allocation, a) for a in out["allocs"]]
        if out.get("job"):
            out["job"] = from_dict(Job, out["job"])
    elif msg_type == m.VAULT_ACCESSOR_REGISTER and out.get("accessors"):
        from .vault import VaultAccessor

        out["accessors"] = [
            a if isinstance(a, VaultAccessor) else from_dict(VaultAccessor, a)
            for a in out["accessors"]
        ]
    return out
