"""EvalBroker: leader-only, in-memory, at-least-once evaluation queue.

Reference: nomad/eval_broker.go:43 — per-scheduler-type priority heaps,
per-job serialization (a job is claimed at enqueue time; later evals
wait in a per-job blocked heap until the outstanding one is Acked),
unack tracking with Nack timers, a delivery limit routing poison evals
to the `_failed` queue, and wait-time evals.

Overload protection (nomad_tpu/admission) extends the reference: ready
queues are optionally BOUNDED (per-scheduler-type depth caps) with
priority-aware shedding — lowest priority, newest first, stamped with a
structured `EVAL_TRIGGER_SHED` outcome exactly once and parked on the
failed queue for the reaper, never silently dropped — and evals carry a
creation-stamped deadline the dequeue path enforces, so stale work is
parked (`EVAL_TRIGGER_EXPIRED`) instead of burning a scheduler.
"""

from __future__ import annotations

import heapq
import itertools
import time
from typing import Dict, List, Optional, Tuple

from ..chaos import chaos
from ..profile import ProfiledCondition, ProfiledRLock
from ..structs import Evaluation, consts
from ..utils import metrics
from ..utils.ids import generate_uuid
from ..utils.timer import default_wheel
from .. import trace

FAILED_QUEUE = "_failed"

# Triggers that mark an eval already parked for terminal processing on
# the failed queue: a copy carrying one of these is never re-stamped,
# re-counted, or dead-lettered again (shed/expired evals must reach
# exactly ONE structured terminal outcome).
_TERMINAL_PARK_TRIGGERS = (
    consts.EVAL_TRIGGER_DEAD_LETTER,
    consts.EVAL_TRIGGER_SHED,
    consts.EVAL_TRIGGER_EXPIRED,
)

# ntalint raft-funnel manifest (analysis/protocol.py): the failed-queue
# park is the broker's exactly-once terminal funnel. A shed/expired/
# dead-letter stamp is only legal on a copy that flows into it — the
# park feeds the leader reaper, which persists the terminal status
# through raft (server.py _reap_failed_evals -> eval_update). The
# _TERMINAL_PARK_TRIGGERS guard above is the dynamic half of the same
# exactly-once contract.
NTA_RAFT_FUNNELS = ("EvalBroker._park_failed_locked",)


class _Heap:
    """Max-priority, FIFO-within-priority eval heap."""

    def __init__(self):
        self._items: List[Tuple[int, int, Evaluation]] = []
        self._counter = itertools.count()

    def push(self, ev: Evaluation) -> None:
        heapq.heappush(self._items, (-ev.priority, next(self._counter), ev))

    def pop(self) -> Optional[Evaluation]:
        if not self._items:
            return None
        return heapq.heappop(self._items)[2]

    def peek_priority(self) -> Optional[int]:
        if not self._items:
            return None
        return -self._items[0][0]

    def worst_priority(self) -> Optional[int]:
        """Priority of the shed victim: the LOWEST priority resident
        (O(n) scan; only runs when a bounded queue is at capacity)."""
        if not self._items:
            return None
        return -max(item[0] for item in self._items)

    def pop_worst(self) -> Optional[Evaluation]:
        """Remove and return the shed victim: lowest priority, newest
        first (max insertion counter among the lowest priority)."""
        if not self._items:
            return None
        idx = max(range(len(self._items)),
                  key=lambda i: (self._items[i][0], self._items[i][1]))
        victim = self._items[idx][2]
        last = self._items.pop()
        if idx < len(self._items):
            self._items[idx] = last
            heapq.heapify(self._items)
        return victim

    def __len__(self):
        return len(self._items)

    def evals(self) -> List[Evaluation]:
        return [item[2] for item in self._items]


class _Unack:
    __slots__ = ("eval", "token", "timer", "nack_timer_paused")

    def __init__(self, ev: Evaluation, token: str, timer):
        self.eval = ev
        self.token = token
        self.timer = timer
        self.nack_timer_paused = False


class EvalBroker:
    def __init__(self, nack_timeout: float = 60.0, delivery_limit: int = 3,
                 ready_cap: int = 0,
                 ready_caps: Optional[Dict[str, int]] = None):
        self.nack_timeout = nack_timeout
        self.delivery_limit = delivery_limit
        # Bounded ready queues (nomad_tpu/admission): per-scheduler-type
        # depth caps — `ready_caps` overrides per type, `ready_cap` is
        # the default for every other type; 0 = unbounded. The failed
        # queue is never capped (it holds the structured terminal parks
        # the caps produce — capping it would shed the shed records).
        self.ready_cap = max(0, ready_cap)
        self._ready_caps = {k: max(0, v)
                            for k, v in (ready_caps or {}).items()}

        # Profiled (nomad_tpu/profile): every enqueue, dequeue, ack and
        # nack serializes here — under a drain storm this lock's
        # acquire-wait histogram is the broker's contention signature.
        self._lock = ProfiledRLock("server.broker")
        self._cond = ProfiledCondition(self._lock, "server.broker")
        self._enabled = False

        self._evals: Dict[str, int] = {}  # known eval id -> dequeue count
        self._ready: Dict[str, _Heap] = {}  # by scheduler type
        self._unack: Dict[str, _Unack] = {}
        self._job_evals: Dict[str, str] = {}  # job claim: job_id -> eval id
        self._blocked: Dict[str, _Heap] = {}  # per-job wait heaps
        self._wheel = default_wheel()  # shared timer thread (utils/timer.py)
        self._wait_timers: Dict[str, object] = {}
        # Evals the scheduler re-submitted (reblock) while outstanding;
        # processed on Ack (eval_broker.go:171-182 requeue).
        self._requeue: Dict[str, Evaluation] = {}
        # Evals routed to the failed queue on delivery-limit exhaustion
        # (dead-lettered); monotonic across flushes so server.stats()
        # reports lifetime poison-eval pressure.
        self.dead_lettered = 0  # guarded-by: _lock
        # Overload-protection counters, monotonic like dead_lettered:
        # evals shed from full bounded ready queues, and evals whose
        # deadline expired before a dequeuer reached them.
        self.shed = 0  # guarded-by: _lock
        self.expired = 0  # guarded-by: _lock

    # ------------------------------------------------------------------

    def enabled(self) -> bool:
        with self._lock:
            return self._enabled

    def set_enabled(self, enabled: bool) -> None:
        with self._lock:
            self._enabled = enabled
        if not enabled:
            self.flush()

    def flush(self) -> None:
        with self._lock:
            for unack in self._unack.values():
                unack.timer.cancel()
            for timer in self._wait_timers.values():
                timer.cancel()
            self._evals.clear()
            self._ready.clear()
            self._unack.clear()
            self._job_evals.clear()
            self._blocked.clear()
            self._wait_timers.clear()
            self._requeue.clear()
            self._cond.notify_all()

    # ------------------------------------------------------------------

    def enqueue(self, ev: Evaluation, token: str = "") -> None:
        with self._lock:
            self._process_enqueue(ev, token)

    def enqueue_all(self, evals: List[Evaluation]) -> None:
        # One critical section so unblocking dequeuers see the full,
        # highest-priority-first picture (eval_broker.go:155-163).
        with self._lock:
            for ev in evals:
                self._process_enqueue(ev, "")

    def _process_enqueue(self, ev: Evaluation, token: str) -> None:
        if ev.id in self._evals:
            if not token:
                return
            # Reblocked by its scheduler while outstanding: run again
            # after the Ack.
            unack = self._unack.get(ev.id)
            if unack is not None and unack.token == token:
                self._requeue[token] = ev
            return
        if self._enabled:
            self._evals[ev.id] = 0
        if ev.wait and ev.wait > 0:
            self._wait_timers[ev.id] = self._wheel.schedule(
                ev.wait, self._wait_done, ev)
            return
        self._enqueue_locked(ev, ev.type)

    def _wait_done(self, ev: Evaluation) -> None:
        with self._lock:
            self._wait_timers.pop(ev.id, None)
            self._enqueue_locked(ev, ev.type)

    def _enqueue_locked(self, ev: Evaluation, queue: str) -> None:
        if not self._enabled:
            return
        # Trace: stamp the enqueue instant (redeliveries re-stamp, so a
        # nacked eval's next broker.wait span measures ITS wait). The
        # recorder is a leaf lock and never blocks (ntalint
        # record-path-blocking) — safe under the broker lock. The
        # failed queue is excluded: its trace was already completed as
        # 'dead-letter', and marking the dead copy would open a second
        # bogus trace that the reaper's dequeue+ack then publishes.
        if queue != FAILED_QUEUE:
            trace.mark(ev.id, ev.trace_id)
        # Per-job serialization: the job is claimed by the first eval;
        # later ones wait in the per-job blocked heap until Ack. The
        # blocked heaps ride the same bounded-queue discipline as the
        # ready queue they feed: without a cap, re-registering one job
        # at storm rate while its eval is outstanding grows the heap
        # without bound, invisibly to the ready cap AND the pressure
        # monitor — exactly the unbounded intake the caps close.
        claimed = self._job_evals.get(ev.job_id, "")
        if not claimed:
            self._job_evals[ev.job_id] = ev.id
        elif claimed != ev.id:
            blocked = self._blocked.setdefault(ev.job_id, _Heap())
            cap = self._ready_caps.get(queue, self.ready_cap)
            if cap and len(blocked) >= cap:
                worst = blocked.worst_priority()
                if worst is None or ev.priority <= worst:
                    self._shed_locked(ev, queue, cap, where="blocked")
                    return
                self._shed_locked(blocked.pop_worst(), queue, cap,
                                  where="blocked")
            blocked.push(ev)
            return
        heap = self._ready.setdefault(queue, _Heap())
        if queue != FAILED_QUEUE:
            cap = self._ready_caps.get(queue, self.ready_cap)
            if cap and len(heap) >= cap:
                # Priority-aware shed, never a silent drop: the victim
                # is the LOWEST-priority eval, newest first — and the
                # incoming eval is by definition the newest at its
                # priority, so it sheds itself whenever it does not
                # strictly outrank the worst resident.
                worst = heap.worst_priority()
                if worst is None or ev.priority <= worst:
                    self._shed_locked(ev, queue, cap)
                    return
                self._shed_locked(heap.pop_worst(), queue, cap)
        heap.push(ev)
        self._cond.notify_all()

    def _shed_locked(self, ev: Evaluation, queue: str, cap: int,
                     where: str = "ready") -> None:
        """Shed one eval from a full bounded ready (or per-job blocked)
        queue: complete its trace, stamp the structured outcome exactly
        ONCE, count it, and park the stamped copy on the failed queue —
        the leader reaper persists it as a terminal status exactly like
        a dead-letter. A ready-shed eval's job claim intentionally
        stays with the eval id; the reaper's ack releases it and
        promotes the job's blocked evals (the dead-letter protocol,
        server.py _reap_failed_evals). A blocked-shed eval never held
        the claim."""
        with self._lock:
            trace.complete(ev.id, "shed")
            shed = ev.copy()
            if shed.triggered_by not in _TERMINAL_PARK_TRIGGERS:
                shed.triggered_by = consts.EVAL_TRIGGER_SHED
                shed.status_description = (
                    f"shed: {where} queue {queue!r} at capacity ({cap}); "
                    f"lowest-priority ({ev.priority}) newest eval "
                    f"dropped under overload (originally triggered by "
                    f"{ev.triggered_by!r})")
                self.shed += 1
                metrics.incr_counter(("broker", "shed"))
            self._park_failed_locked(shed)

    def _expire_locked(self, ev: Evaluation, queue: str) -> None:
        """An eval whose creation-stamped deadline passed while queued:
        skipped at dequeue, parked on the failed queue with a
        structured reason (exactly once — see _TERMINAL_PARK_TRIGGERS),
        so stale work never reaches a scheduler or a device lane."""
        with self._lock:
            trace.complete(ev.id, "expired")
            dead = ev.copy()
            if dead.triggered_by not in _TERMINAL_PARK_TRIGGERS:
                dead.triggered_by = consts.EVAL_TRIGGER_EXPIRED
                dead.status_description = (
                    f"deadline expired before dispatch: deadline "
                    f"{ev.deadline:.3f} passed while queued on "
                    f"{queue!r} (originally triggered by "
                    f"{ev.triggered_by!r})")
                self.expired += 1
                metrics.incr_counter(("broker", "expired"))
            self._park_failed_locked(dead)

    def _park_failed_locked(self, ev: Evaluation) -> None:
        """Push a stamped terminal copy straight onto the failed queue.
        Deliberately NOT routed through ``_enqueue_locked``: its
        per-job claim check would divert a copy whose job is claimed
        by a DIFFERENT eval (a blocked-heap shed) into the blocked
        heap instead of the failed queue — a terminal park must always
        reach the reaper. The failed queue is never capped and its
        copies are never trace-marked (their trace was completed at
        the park site)."""
        if not self._enabled:
            return
        self._ready.setdefault(FAILED_QUEUE, _Heap()).push(ev)
        self._cond.notify_all()

    # ------------------------------------------------------------------

    def dequeue(
        self, schedulers: List[str], timeout: Optional[float] = None
    ) -> Tuple[Optional[Evaluation], str]:
        """Blocking dequeue of the highest-priority ready eval for any of
        the given scheduler types. Returns (eval, token) or (None, "")."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._lock:
            while True:
                if not self._enabled:
                    return None, ""
                ev = self._scan_for_schedulers(schedulers)
                if ev is not None:
                    out = self._dequeue_locked(ev)
                    break
                remaining = None
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return None, ""
                self._cond.wait(remaining if remaining is not None else 1.0)
        return self._chaos_deliver(out)

    def _chaos_deliver(
        self, out: Tuple[Evaluation, str]
    ) -> Tuple[Optional[Evaluation], str]:
        """Fault-injection point on the delivery edge: a dropped
        delivery models a dequeuer that crashed before doing any work —
        the lease is burned (counts toward the delivery limit) and the
        eval redelivers immediately via nack. Runs OUTSIDE the broker
        lock (a 'delay' fault sleeps in fire())."""
        if chaos.enabled and chaos.fire(
                "broker.deliver", eval_id=out[0].id) == "drop":
            try:
                self.nack(out[0].id, out[1])
            except ValueError:
                pass  # timer already reclaimed it
            return None, ""
        return out

    def dequeue_many(
        self, schedulers: List[str], max_n: int
    ) -> List[Tuple[Evaluation, str]]:
        """Non-blocking drain of up to max_n ready evals for the given
        scheduler types. Extension over the reference's single-dequeue
        (eval_broker.go:259) for the dense backend's drain-to-batch
        path: per-job serialization still holds (a job's later evals
        stay in its blocked heap), so a drained batch is always over
        distinct jobs."""
        out: List[Tuple[Evaluation, str]] = []
        with self._lock:
            if not self._enabled:
                return out
            while len(out) < max_n:
                ev = self._scan_for_schedulers(schedulers)
                if ev is None:
                    break
                out.append(self._dequeue_locked(ev))
        if chaos.enabled:
            out = [item for item in map(self._chaos_deliver, out)
                   if item[0] is not None]
        return out

    def _scan_for_schedulers(self, schedulers: List[str]) -> Optional[Evaluation]:
        now = time.time()
        while True:
            best_queue = None
            best_priority = -1
            for sched in schedulers:
                heap = self._ready.get(sched)
                if heap is None:
                    continue
                prio = heap.peek_priority()
                if prio is not None and prio > best_priority:
                    best_priority = prio
                    best_queue = sched
            if best_queue is None:
                return None
            ev = self._ready[best_queue].pop()
            if ev is None:
                return None
            # Deadline enforcement at dequeue: an expired eval would
            # only burn a scheduler (or a device lane) producing a plan
            # the submitter no longer wants — park it structured and
            # keep scanning for live work. The failed queue is exempt:
            # its copies are terminal parks on their way to the reaper.
            if best_queue != FAILED_QUEUE and ev.expired(now):
                self._expire_locked(ev, best_queue)
                continue
            return ev

    def _dequeue_locked(self, ev: Evaluation) -> Tuple[Evaluation, str]:
        token = generate_uuid()
        deliveries = self._evals.get(ev.id, 0) + 1
        self._evals[ev.id] = deliveries
        timer = self._wheel.schedule(
            self.nack_timeout, self._nack_timeout, ev.id, token)
        self._unack[ev.id] = _Unack(ev, token, timer)
        trace.record_since_mark(
            ev.id, trace.STAGE_BROKER_WAIT,
            {"deliveries": deliveries, "type": ev.type})
        return ev, token

    def _nack_timeout(self, eval_id: str, token: str) -> None:
        """Nack timer fired: the worker died or stalled; redeliver."""
        if chaos.enabled:
            # 'drop' = the timeout itself is lost once: re-arm so the
            # eval redelivers a full nack_timeout late instead of never
            # (a dropped redelivery must degrade latency, not lose the
            # at-least-once guarantee). 'delay' sleeps in fire().
            if chaos.fire("broker.nack_timer", eval_id=eval_id) == "drop":
                with self._lock:
                    unack = self._unack.get(eval_id)
                    if unack is not None and unack.token == token:
                        unack.timer = self._wheel.schedule(
                            self.nack_timeout, self._nack_timeout,
                            eval_id, token)
                return
        try:
            self.nack(eval_id, token)
        except ValueError:
            pass  # already acked/nacked

    # ------------------------------------------------------------------

    def _check_token(self, eval_id: str, token: str) -> _Unack:
        unack = self._unack.get(eval_id)
        if unack is None or unack.token != token:
            raise ValueError(f"token does not match for eval {eval_id!r}")
        return unack

    def outstanding(self, eval_id: str) -> Optional[str]:
        with self._lock:
            unack = self._unack.get(eval_id)
            return unack.token if unack else None

    def ack(self, eval_id: str, token: str) -> None:
        with self._lock:
            unack = self._check_token(eval_id, token)
            unack.timer.cancel()
            del self._unack[eval_id]
            self._evals.pop(eval_id, None)
            job_id = unack.eval.job_id
            if self._job_evals.get(job_id) == eval_id:
                del self._job_evals[job_id]
            # Promote the next blocked eval for this job.
            blocked = self._blocked.get(job_id)
            if blocked:
                nxt = blocked.pop()
                if not len(blocked):
                    del self._blocked[job_id]
                if nxt is not None:
                    self._enqueue_locked(nxt, nxt.type)
            # Ack is the lifecycle's last breath: the plan (if any)
            # already committed before the worker acked, so the span
            # tree is whole. Completed BEFORE the reblock re-enqueue:
            # _process_enqueue marks the requeued run's enqueue instant
            # on what must be a FRESH trace — completing afterwards
            # would pop that mark and split the requeued lifecycle.
            # (Leaf locks only; same pattern as the dead-letter path.)
            trace.complete(eval_id, "acked")
            # Process a reblock submitted while this eval was outstanding.
            requeued = self._requeue.pop(token, None)
            if requeued is not None:
                self._process_enqueue(requeued, "")

    def nack(self, eval_id: str, token: str) -> None:
        with self._lock:
            unack = self._check_token(eval_id, token)
            unack.timer.cancel()
            del self._unack[eval_id]
            self._requeue.pop(token, None)
            ev = unack.eval
            # The job claim stays with this eval; redeliver it, or
            # dead-letter it past the delivery limit: the failed-queue
            # copy carries a structured trigger + reason (instead of
            # silently capping), the leader reaper persists them when it
            # marks the eval failed, and the counter surfaces poison
            # evals in server.stats().
            deliveries = self._evals.get(ev.id, 0)
            if deliveries >= self.delivery_limit:
                # A dead-lettered eval never acks: close its trace here
                # (the nacked-but-redelivering case below keeps the
                # trace open — its next delivery keeps appending spans).
                trace.complete(ev.id, "dead-letter")
                dead = ev.copy()
                # Idempotent: a reaper whose eval_update failed (leader
                # flap) lets the nack timer re-park the ALREADY
                # dead-lettered copy — re-stamping would clobber the
                # original trigger and double-count the eval. Shed and
                # expired parks are covered by the same guard: a shed
                # eval must never ALSO dead-letter (one structured
                # terminal outcome, exactly once).
                if dead.triggered_by not in _TERMINAL_PARK_TRIGGERS:
                    dead.triggered_by = consts.EVAL_TRIGGER_DEAD_LETTER
                    dead.status_description = (
                        f"dead-lettered: delivery limit "
                        f"({self.delivery_limit}) exhausted after "
                        f"{deliveries} deliveries "
                        f"(originally triggered by {ev.triggered_by!r})")
                    self.dead_lettered += 1
                    metrics.incr_counter(("broker", "dead_lettered"))
                self._park_failed_locked(dead)
            else:
                self._enqueue_locked(ev, ev.type)

    def pause_nack_timeout(self, eval_id: str, token: str) -> None:
        """Stop the redelivery clock while the plan sits in the plan
        queue (plan_endpoint.go:16)."""
        with self._lock:
            unack = self._check_token(eval_id, token)
            unack.timer.cancel()
            unack.nack_timer_paused = True

    def resume_nack_timeout(self, eval_id: str, token: str) -> None:
        with self._lock:
            unack = self._check_token(eval_id, token)
            if unack.nack_timer_paused:
                unack.timer = self._wheel.schedule(
                    self.nack_timeout, self._nack_timeout, eval_id, token)
                unack.nack_timer_paused = False

    # ------------------------------------------------------------------

    def ready_count(self) -> int:
        with self._lock:
            return sum(
                len(h) for q, h in self._ready.items() if q != FAILED_QUEUE
            )

    def unacked_count(self) -> int:
        with self._lock:
            return len(self._unack)

    def blocked_count(self) -> int:
        with self._lock:
            return sum(len(h) for h in self._blocked.values())

    def waiting_count(self) -> int:
        with self._lock:
            return len(self._wait_timers)

    def failed_evals(self) -> List[Evaluation]:
        """Evals past the delivery limit (reaped by the leader,
        leader.go:369)."""
        with self._lock:
            heap = self._ready.get(FAILED_QUEUE)
            return heap.evals() if heap else []

    def ready_by_queue(self) -> Dict[str, int]:
        """Per-scheduler-type ready depths (failed queue excluded) —
        the pressure monitor measures each CAPPED queue against its
        own budget; lumping uncapped queues into one total would read
        a deliberately-unbounded queue's backlog as cap pressure."""
        with self._lock:
            return {q: len(h) for q, h in self._ready.items()
                    if q != FAILED_QUEUE}

    def stats(self) -> Dict[str, object]:
        with self._lock:
            dead = self.dead_lettered
            shed = self.shed
            expired = self.expired
        return {
            "ready_by_queue": self.ready_by_queue(),
            "total_ready": self.ready_count(),
            "total_unacked": self.unacked_count(),
            "total_blocked": self.blocked_count(),
            "total_waiting": self.waiting_count(),
            "dead_lettered": dead,
            "shed": shed,
            "expired": expired,
        }
