"""TimeTable: raft-index <-> wall-clock mapping for GC thresholds.

Reference: nomad/timetable.go:30 (ring buffer of (index, time) pairs,
witnessed on every FSM apply, fsm.go:107).
"""

from __future__ import annotations

import threading
import time
from typing import List, Tuple


class TimeTable:
    def __init__(self, granularity: float = 1.0, limit: int = 72 * 3600):
        self.granularity = granularity
        self.limit = limit  # seconds of history retained
        self._lock = threading.Lock()
        self._table: List[Tuple[int, float]] = []  # (index, time), newest first

    def witness(self, index: int, when: float = None) -> None:
        when = time.time() if when is None else when
        with self._lock:
            if self._table and when - self._table[0][1] < self.granularity:
                return
            self._table.insert(0, (index, when))
            cutoff = when - self.limit
            while self._table and self._table[-1][1] < cutoff:
                self._table.pop()

    def nearest_index(self, when: float) -> int:
        """Largest index witnessed at-or-before `when` (0 if none)."""
        with self._lock:
            for index, t in self._table:
                if t <= when:
                    return index
        return 0

    def nearest_time(self, index: int) -> float:
        with self._lock:
            for idx, t in self._table:
                if idx <= index:
                    return t
        return 0.0
