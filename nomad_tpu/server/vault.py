"""Vault integration: per-task token derivation, renewal, revocation.

Reference: nomad/vault.go (vaultClient: CreateToken, RenewToken,
RevokeTokens, accessor tracking, 844 LoC) and the derive entrypoint
Node.DeriveVaultToken (nomad/node_endpoint.go:940). The reference talks
to a real HashiCorp Vault; here the provider is pluggable with an
in-process stub (token store with TTLs) so the full derive → use →
renew → revoke lifecycle runs without an external service. A real
backend would implement the same three-method surface over Vault's
HTTP API.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..structs.alloc import VaultAccessor  # noqa: F401 — re-export
from ..utils.ids import generate_uuid


class VaultError(Exception):
    pass


class VaultProvider:
    """Provider surface the server needs (vault.go CreateToken:~,
    RenewToken, RevokeTokens)."""

    def create_token(self, policies: List[str]) -> Tuple[str, str, float]:
        """Returns (token, accessor, ttl_seconds)."""
        raise NotImplementedError

    def renew_token(self, token: str) -> float:
        """Extends the token lease; returns the new ttl."""
        raise NotImplementedError

    def revoke_tokens(self, accessors: List[str]) -> None:
        raise NotImplementedError


@dataclass
class _StubToken:
    token: str
    accessor: str
    policies: List[str]
    expires: float


class StubVault(VaultProvider):
    """In-memory token authority with TTLs.

    Lookup-by-token works too so tests (and the dev agent) can assert a
    derived token is live, carries the requested policies, and dies on
    revocation/expiry.
    """

    def __init__(self, ttl: float = 3600.0, allowed_policies: Optional[List[str]] = None):
        self.ttl = ttl
        # None = allow any policy except root (the reference always
        # rejects root, job_endpoint.go vault checks).
        self.allowed_policies = allowed_policies
        self._lock = threading.Lock()
        self._by_token: Dict[str, _StubToken] = {}
        self._by_accessor: Dict[str, _StubToken] = {}
        self.logger = logging.getLogger("nomad_tpu.vault.stub")

    def create_token(self, policies: List[str]) -> Tuple[str, str, float]:
        if "root" in policies:
            raise VaultError("root policy cannot be derived for tasks")
        if self.allowed_policies is not None:
            bad = [p for p in policies if p not in self.allowed_policies]
            if bad:
                raise VaultError(f"policies not allowed: {bad}")
        tok = _StubToken(
            token=f"s.{generate_uuid()}",
            accessor=generate_uuid(),
            policies=list(policies),
            expires=time.monotonic() + self.ttl,
        )
        with self._lock:
            self._by_token[tok.token] = tok
            self._by_accessor[tok.accessor] = tok
        return tok.token, tok.accessor, self.ttl

    def renew_token(self, token: str) -> float:
        with self._lock:
            tok = self._by_token.get(token)
            if tok is None:
                raise VaultError("unknown token")
            if tok.expires < time.monotonic():
                raise VaultError("token expired")
            tok.expires = time.monotonic() + self.ttl
        return self.ttl

    def revoke_tokens(self, accessors: List[str]) -> None:
        with self._lock:
            for acc in accessors:
                tok = self._by_accessor.pop(acc, None)
                if tok is not None:
                    self._by_token.pop(tok.token, None)

    # ------------------------------------------------------ test hooks

    def lookup(self, token: str) -> Optional[List[str]]:
        """Policies of a live token, None if revoked/expired/unknown."""
        with self._lock:
            tok = self._by_token.get(token)
            if tok is None or tok.expires < time.monotonic():
                return None
            return list(tok.policies)
