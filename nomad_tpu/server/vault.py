"""Vault integration: per-task token derivation, renewal, revocation.

Reference: nomad/vault.go (vaultClient: CreateToken, RenewToken,
RevokeTokens, accessor tracking + the server's own token renewal loop,
844 LoC) and the derive entrypoint Node.DeriveVaultToken
(nomad/node_endpoint.go:940). Two providers behind one surface:

- StubVault: in-process token store with TTLs, for unit speed and
  vault-less deployments;
- HTTPVaultProvider: the real thing — speaks Vault's token API
  (auth/token/create, renew, revoke-accessor, lookup-self) over HTTP
  with the server's own vault token, renewing that token at half-life
  like the reference's renewal loop (vault.go renewalLoop).

FakeVaultServer serves the same HTTP surface in-process so the wire
path is testable without a vault binary (the FakeConsulServer pattern,
consul/api.py).
"""

from __future__ import annotations

import json
import logging
import threading
import time
import urllib.error
import urllib.request
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..structs.alloc import VaultAccessor  # noqa: F401 — re-export
from ..utils.ids import generate_uuid


class VaultError(Exception):
    pass


class VaultProvider:
    """Provider surface the server needs (vault.go CreateToken:~,
    RenewToken, RevokeTokens)."""

    allowed_policies: Optional[List[str]] = None

    def _check_policies(self, policies: List[str]) -> None:
        """Nomad-side policy rules shared by every provider: root is
        always rejected (job_endpoint.go vault checks), and an operator
        allowlist restricts the rest."""
        if "root" in policies:
            raise VaultError("root policy cannot be derived for tasks")
        if self.allowed_policies is not None:
            bad = [p for p in policies if p not in self.allowed_policies]
            if bad:
                raise VaultError(f"policies not allowed: {bad}")

    def create_token(self, policies: List[str]) -> Tuple[str, str, float]:
        """Returns (token, accessor, ttl_seconds)."""
        raise NotImplementedError

    def renew_token(self, token: str) -> float:
        """Extends the token lease; returns the new ttl."""
        raise NotImplementedError

    def revoke_tokens(self, accessors: List[str]) -> None:
        raise NotImplementedError


@dataclass
class _StubToken:
    token: str
    accessor: str
    policies: List[str]
    expires: float


class StubVault(VaultProvider):
    """In-memory token authority with TTLs.

    Lookup-by-token works too so tests (and the dev agent) can assert a
    derived token is live, carries the requested policies, and dies on
    revocation/expiry.
    """

    def __init__(self, ttl: float = 3600.0, allowed_policies: Optional[List[str]] = None):
        self.ttl = ttl
        # None = allow any policy except root (the reference always
        # rejects root, job_endpoint.go vault checks).
        self.allowed_policies = allowed_policies
        self._lock = threading.Lock()
        self._by_token: Dict[str, _StubToken] = {}
        self._by_accessor: Dict[str, _StubToken] = {}
        self.logger = logging.getLogger("nomad_tpu.vault.stub")

    def create_token(self, policies: List[str]) -> Tuple[str, str, float]:
        self._check_policies(policies)
        tok = _StubToken(
            token=f"s.{generate_uuid()}",
            accessor=generate_uuid(),
            policies=list(policies),
            expires=time.monotonic() + self.ttl,
        )
        with self._lock:
            self._by_token[tok.token] = tok
            self._by_accessor[tok.accessor] = tok
        return tok.token, tok.accessor, self.ttl

    def renew_token(self, token: str) -> float:
        with self._lock:
            tok = self._by_token.get(token)
            if tok is None:
                raise VaultError("unknown token")
            if tok.expires < time.monotonic():
                raise VaultError("token expired")
            tok.expires = time.monotonic() + self.ttl
        return self.ttl

    def revoke_tokens(self, accessors: List[str]) -> None:
        with self._lock:
            for acc in accessors:
                tok = self._by_accessor.pop(acc, None)
                if tok is not None:
                    self._by_token.pop(tok.token, None)

    # ------------------------------------------------------ test hooks

    def lookup(self, token: str) -> Optional[List[str]]:
        """Policies of a live token, None if revoked/expired/unknown."""
        with self._lock:
            tok = self._by_token.get(token)
            if tok is None or tok.expires < time.monotonic():
                return None
            return list(tok.policies)


class HTTPVaultProvider(VaultProvider):
    """Token authority over Vault's HTTP API (nomad/vault.go).

    `token` is the server's own vault token (config vault.token); every
    request carries it as X-Vault-Token. The reference validates it at
    startup and renews it at half-life forever (vault.go
    establishConnection + renewalLoop) — start_renewal()/stop() here.
    Policy allowlisting stays nomad-side (job_endpoint.go:84-120 checks
    at submit; the server consults `allowed_policies`), vault itself
    enforces whatever its own token policies allow.
    """

    def __init__(self, address: str, token: str, ttl: float = 3600.0,
                 allowed_policies: Optional[List[str]] = None,
                 timeout: float = 10.0):
        if "://" not in address:
            address = "http://" + address
        self.base = address.rstrip("/")
        self.token = token
        self.ttl = ttl
        self.allowed_policies = allowed_policies
        self.timeout = timeout
        self.logger = logging.getLogger("nomad_tpu.vault.http")
        self._renew_stop: Optional[threading.Event] = None

    # ------------------------------------------------------------ wire

    def _request(self, method: str, path: str,
                 body: Optional[dict] = None) -> dict:
        url = self.base + path
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(
            url, data=data, method=method,
            headers={"X-Vault-Token": self.token,
                     "Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                payload = resp.read()
        except urllib.error.HTTPError as e:
            detail = e.read().decode(errors="replace")
            try:
                errors = json.loads(detail).get("errors") or [detail]
            except ValueError:
                errors = [detail]
            raise VaultError(
                f"vault {method} {path}: {e.code} {'; '.join(errors)}") from e
        except (urllib.error.URLError, OSError) as e:
            raise VaultError(f"vault {method} {path}: {e}") from e
        if not payload:
            return {}
        try:
            return json.loads(payload)
        except ValueError as e:
            raise VaultError(f"vault {method} {path}: bad json") from e

    # ------------------------------------------------------- provider

    def create_token(self, policies: List[str]) -> Tuple[str, str, float]:
        self._check_policies(policies)
        resp = self._request("POST", "/v1/auth/token/create", {
            "policies": list(policies),
            "ttl": f"{int(self.ttl)}s",
            "display_name": "nomad-task",
            # Orphan-less child of the server token, like the reference
            # (vault.go CreateToken uses the role / non-orphan default):
            # revoking our token revokes every derived one.
            "renewable": True,
        })
        auth = resp.get("auth") or {}
        client_token = auth.get("client_token", "")
        accessor = auth.get("accessor", "")
        if not client_token or not accessor:
            raise VaultError("vault create returned no token")
        return client_token, accessor, float(
            auth.get("lease_duration") or self.ttl)

    def renew_token(self, token: str) -> float:
        resp = self._request("POST", "/v1/auth/token/renew", {
            "token": token, "increment": f"{int(self.ttl)}s",
        })
        auth = resp.get("auth") or {}
        return float(auth.get("lease_duration") or self.ttl)

    def revoke_tokens(self, accessors: List[str]) -> None:
        errors = []
        for acc in accessors:
            try:
                self._request("POST", "/v1/auth/token/revoke-accessor",
                              {"accessor": acc})
            except VaultError as e:
                # Unknown accessor = already revoked/expired: idempotent
                # like the reference's RevokeTokens; every OTHER failure
                # (including other 400s — malformed request, backend
                # errors) is collected so it is reported, and so one bad
                # accessor doesn't strand the rest.
                if "invalid accessor" in str(e).lower():
                    continue
                errors.append(str(e))
        if errors:
            raise VaultError("; ".join(errors))

    # ---------------------------------------------- own-token lifecycle

    def validate(self) -> dict:
        """Startup check of the server's own token (vault.go
        establishConnection lookup-self)."""
        resp = self._request("GET", "/v1/auth/token/lookup-self")
        return resp.get("data") or {}

    def start_renewal(self) -> None:
        """Renew our own token at half-life forever (vault.go
        renewalLoop); idempotent."""
        if self._renew_stop is not None:
            return
        stop = threading.Event()
        self._renew_stop = stop

        def loop():
            backoff = 5.0
            while not stop.is_set():
                try:
                    resp = self._request(
                        "POST", "/v1/auth/token/renew-self",
                        {"increment": f"{int(self.ttl)}s"})
                    lease = float(
                        (resp.get("auth") or {}).get("lease_duration")
                        or self.ttl)
                    wait = max(lease / 2.0, 1.0)
                    backoff = 5.0
                except VaultError as e:
                    self.logger.warning("self-renewal failed: %s", e)
                    wait = backoff
                    backoff = min(backoff * 2, 300.0)
                stop.wait(wait)

        threading.Thread(target=loop, name="vault-renew", daemon=True).start()

    def stop(self) -> None:
        if self._renew_stop is not None:
            self._renew_stop.set()
            self._renew_stop = None


class FakeVaultServer:
    """Vault's token HTTP API served off a StubVault-style store, for
    tests and dev clusters (the FakeConsulServer pattern). Knows one
    privileged root token; requests must present a live token."""

    def __init__(self, root_token: str = "", ttl: float = 3600.0):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        self.root_token = root_token or f"s.{generate_uuid()}"
        self.store = StubVault(ttl=ttl)
        self.tokens_created = 0
        self.renews = 0
        self.self_renews = 0
        self.revokes = 0
        fake = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):
                pass

            def _body(self):
                n = int(self.headers.get("Content-Length") or 0)
                if not n:
                    return {}
                try:
                    return json.loads(self.rfile.read(n))
                except ValueError:
                    return {}

            def _reply(self, code, obj):
                data = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def _auth_ok(self):
                tok = self.headers.get("X-Vault-Token", "")
                if tok == fake.root_token or fake.store.lookup(tok) is not None:
                    return tok
                self._reply(403, {"errors": ["permission denied"]})
                return None

            def _handle(self):
                tok = self._auth_ok()
                if tok is None:
                    return
                path, body = self.path, self._body()
                try:
                    if path == "/v1/auth/token/create":
                        t, acc, ttl = fake.store.create_token(
                            body.get("policies") or [])
                        fake.tokens_created += 1
                        self._reply(200, {"auth": {
                            "client_token": t, "accessor": acc,
                            "lease_duration": int(ttl),
                            "policies": body.get("policies") or [],
                        }})
                    elif path == "/v1/auth/token/renew":
                        ttl = fake.store.renew_token(body.get("token", ""))
                        fake.renews += 1
                        self._reply(200, {"auth": {"lease_duration": int(ttl)}})
                    elif path == "/v1/auth/token/renew-self":
                        if tok != fake.root_token:
                            fake.store.renew_token(tok)
                        fake.self_renews += 1
                        inc = str(body.get("increment") or "").rstrip("s")
                        lease = (int(inc) if inc.isdigit()
                                 else int(fake.store.ttl))
                        self._reply(200, {"auth": {"lease_duration": lease}})
                    elif path == "/v1/auth/token/revoke-accessor":
                        if fake.store._by_accessor.get(
                                body.get("accessor", "")) is None:
                            self._reply(400, {"errors": ["invalid accessor"]})
                            return
                        fake.store.revoke_tokens([body.get("accessor", "")])
                        fake.revokes += 1
                        self._reply(204, {})
                    elif path == "/v1/auth/token/lookup-self":
                        pols = (["root"] if tok == fake.root_token
                                else fake.store.lookup(tok))
                        self._reply(200, {"data": {
                            "policies": pols, "renewable": True}})
                    else:
                        self._reply(404, {"errors": ["unsupported path"]})
                except VaultError as e:
                    self._reply(400, {"errors": [str(e)]})

            do_GET = do_POST = do_PUT = _handle

        self._httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self._httpd.daemon_threads = True
        self.address = f"127.0.0.1:{self._httpd.server_address[1]}"
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="fake-vault", daemon=True)

    def start(self) -> "FakeVaultServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
