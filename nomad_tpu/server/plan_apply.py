"""Plan applier: the leader's serialization point for optimistic
concurrency.

Reference: nomad/plan_apply.go:41 — a long-lived leader loop that
dequeues plans by priority, verifies each node's placements against the
latest state (fanned out over a worker pool, plan_apply_pool.go:18),
partially commits what fits, and hands workers a RefreshIndex when
their snapshot went stale. Pipelining: plan N+1 is evaluated against an
optimistic snapshot while plan N's commit is in flight
(plan_apply.go:19-39).
"""

from __future__ import annotations

import logging
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional, Tuple

from ..structs import Allocation, Plan, PlanResult, allocs_fit, consts, remove_allocs
from ..utils import metrics
from .fsm import ALLOC_UPDATE
from .plan_queue import PendingPlan, PlanQueue


def evaluate_node_plan(snapshot, plan: Plan, node_id: str) -> bool:
    """Whether the plan's changes to one node can be applied against the
    given state (plan_apply.go:318 evaluateNodePlan)."""
    if not plan.node_allocation.get(node_id):
        return True  # evictions only: always safe

    node = snapshot.node_by_id(node_id)
    if node is None:
        return False
    if node.status != consts.NODE_STATUS_READY or node.drain:
        return False

    from ..scheduler.util import proposed_allocs_for_node

    proposed = proposed_allocs_for_node(snapshot, plan, node_id)
    fit, _, _ = allocs_fit(node, proposed)
    return fit


class PlanApplier:
    """Consumes the plan queue; runs as a leader-only thread."""

    def __init__(self, plan_queue: PlanQueue, fsm, log, pool_size: int = 2,
                 logger: Optional[logging.Logger] = None):
        self.plan_queue = plan_queue
        self.fsm = fsm
        self.log = log
        self.logger = logger or logging.getLogger("nomad_tpu.plan_apply")
        self.pool = ThreadPoolExecutor(
            max_workers=max(pool_size, 1), thread_name_prefix="plan-eval"
        )
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lifecycle = threading.Lock()  # start/stop can race on
        # leadership flaps (raft elections)

    def start(self) -> None:
        with self._lifecycle:
            self._stop.clear()
            thread = threading.Thread(
                target=self._run, name="plan-applier", daemon=True
            )
            thread.start()
            self._thread = thread

    def stop(self) -> None:
        with self._lifecycle:
            self._stop.set()
            if self._thread is not None:
                self._thread.join(timeout=5.0)
                self._thread = None

    def _run(self) -> None:
        while not self._stop.is_set():
            pending = self.plan_queue.dequeue(timeout=0.25)
            if pending is None:
                continue
            try:
                result = self._apply_one(pending.plan)
                pending.respond(result, None)
            except Exception as e:  # noqa: BLE001 - fail the one plan
                self.logger.exception("plan apply failed")
                pending.respond(None, e)

    # ------------------------------------------------------------------

    def _apply_one(self, plan: Plan) -> PlanResult:
        snapshot = self.fsm.state.snapshot()
        start = time.monotonic()
        result = self._evaluate_plan(snapshot, plan)
        metrics.measure_since(("plan", "evaluate"), start)
        if result.is_no_op():
            return result
        start = time.monotonic()
        alloc_index = self._commit(plan, result)
        metrics.measure_since(("plan", "submit"), start)
        result.alloc_index = alloc_index
        return result

    def _evaluate_plan(self, snapshot, plan: Plan) -> PlanResult:
        """Per-node verification with partial commit
        (plan_apply.go:194 evaluatePlan)."""
        result = PlanResult(
            node_update=dict(plan.node_update),
            node_allocation=dict(plan.node_allocation),
        )

        node_ids = set(plan.node_update) | set(plan.node_allocation)
        futures = {
            node_id: self.pool.submit(evaluate_node_plan, snapshot, plan, node_id)
            for node_id in node_ids
        }
        for node_id, fut in futures.items():
            if fut.result():
                continue
            # This node's changes don't fit anymore.
            if plan.all_at_once:
                # Gang commit: reject everything, force a refresh.
                result.node_update = {}
                result.node_allocation = {}
                result.refresh_index = snapshot.latest_index()
                return result
            result.node_update.pop(node_id, None)
            result.node_allocation.pop(node_id, None)
            result.refresh_index = snapshot.latest_index()
        return result

    def _commit(self, plan: Plan, result: PlanResult) -> int:
        allocs: List[Allocation] = []
        for update_list in result.node_update.values():
            allocs.extend(update_list)
        for alloc_list in result.node_allocation.values():
            allocs.extend(alloc_list)
        index = self.log.apply(
            ALLOC_UPDATE, {"allocs": allocs, "job": plan.job}
        )
        # Stamp indexes onto the result's alloc objects the way the Go
        # store mutates shared pointers — workers count fresh placements
        # by create_index == alloc_index (scheduler/util.py).
        for alloc_list in result.node_allocation.values():
            for alloc in alloc_list:
                stored = self.fsm.state.alloc_by_id(alloc.id)
                if stored is not None:
                    alloc.create_index = stored.create_index
                    alloc.modify_index = stored.modify_index
        return index
