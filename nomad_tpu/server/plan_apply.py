"""Plan applier: the leader's serialization point for optimistic
concurrency.

Reference: nomad/plan_apply.go:41 — a long-lived leader loop that
dequeues plans by priority, verifies each node's placements against the
latest state (fanned out over a worker pool, plan_apply_pool.go:18),
partially commits what fits, and hands workers a RefreshIndex when
their snapshot went stale. Pipelining: plan N+1 is evaluated against an
optimistic snapshot while plan N's commit is in flight
(plan_apply.go:19-39).
"""

from __future__ import annotations

import logging
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional, Tuple

from .. import trace
from ..structs import Allocation, Plan, PlanResult, allocs_fit, consts, remove_allocs
from ..utils import metrics
from .fsm import ALLOC_UPDATE
from .plan_queue import PendingPlan, PlanQueue


def evaluate_node_preemptions(snapshot, plan: Plan, node_id: str) -> bool:
    """Per-victim verification of a preemption leg: every victim must
    still exist, be non-terminal, and be STRICTLY lower-priority than
    the plan. A victim that completed, died, or was replaced underneath
    the scheduler (chaos site preempt.victim_lost models the same
    shape from the other side: a victim whose freed capacity was
    counted but whose eviction never got staged) rejects the node —
    the freed-capacity discount the placement relied on is void, so
    the whole node replans on fresh state."""
    victims = plan.node_preemptions.get(node_id)
    if not victims:
        return True
    from ..migrate import victim_priority

    # The node's LIVE allocs through whichever view we were handed —
    # the optimistic overlay already hides in-flight evictions, so a
    # victim another pipelined plan is stopping verifies as lost here.
    live = {a.id: a
            for a in snapshot.allocs_by_node_terminal(node_id, False)}
    for victim in victims:
        stored = live.get(victim.id)
        if stored is None or stored.terminal_status():
            return False
        if victim_priority(stored) >= plan.priority:
            return False
    return True


def evaluate_node_plan(snapshot, plan: Plan, node_id: str) -> bool:
    """Whether the plan's changes to one node can be applied against the
    given state (plan_apply.go:318 evaluateNodePlan)."""
    if not evaluate_node_preemptions(snapshot, plan, node_id):
        return False
    if not plan.node_allocation.get(node_id):
        return True  # evictions only: always safe

    node = snapshot.node_by_id(node_id)
    if node is None:
        return False
    if node.status != consts.NODE_STATUS_READY or node.drain:
        return False

    from ..scheduler.util import proposed_allocs_for_node

    proposed = proposed_allocs_for_node(snapshot, plan, node_id)
    fit, _, _ = allocs_fit(node, proposed)
    return fit


class OptimisticSnapshot:
    """Base snapshot + accepted allocations of in-flight plans — the
    read view for verifying plan N+1 while plan N's commit is still in
    flight (plan_apply.go:155-161 optimistic snap.UpsertAllocs).
    Exposes exactly what evaluate_node_plan reads."""

    def __init__(self, base):
        self.base = base
        self._extra_by_node = {}  # node_id -> {alloc_id: alloc}
        self._evicted = set()  # alloc ids stopped by in-flight plans
        self._dirty = False

    def add_result(self, result: PlanResult) -> None:
        for node_id, allocs in result.node_allocation.items():
            d = self._extra_by_node.setdefault(node_id, {})
            for alloc in allocs:
                d[alloc.id] = alloc
        for allocs in result.node_update.values():
            for alloc in allocs:
                self._evicted.add(alloc.id)
        # In-flight preemption evictions hide from the next plan's
        # verification exactly like staged stops do.
        for allocs in result.node_preemptions.values():
            for alloc in allocs:
                self._evicted.add(alloc.id)
        self._dirty = True

    def node_by_id(self, node_id):
        return self.base.node_by_id(node_id)

    def latest_index(self) -> int:
        # With a commit in flight, a plan rejected off this view must
        # refresh PAST the in-flight commit — otherwise the worker's
        # "refresh" is a no-op against pre-commit state and it spins
        # resubmitting the same plan (the reference advances its
        # optimistic snapshot's index the same way).
        return self.base.latest_index() + (1 if self._dirty else 0)

    def allocs_by_node_terminal(self, node_id, terminal):
        live = {
            a.id: a
            for a in self.base.allocs_by_node_terminal(node_id, terminal)
            if a.id not in self._evicted
        }
        if not terminal:
            live.update(self._extra_by_node.get(node_id, {}))
        return list(live.values())


class PlanApplier:
    """Consumes the plan queue; runs as a leader-only thread.

    Pipelined like the reference (plan_apply.go:41-118): one raft
    commit is in flight at a time while the NEXT plan is verified
    against an optimistic snapshot that includes the in-flight plan's
    accepted allocations. A failed commit forces the following plan to
    re-verify on a fresh snapshot."""

    def __init__(self, plan_queue: PlanQueue, fsm, log, pool_size: int = 2,
                 logger: Optional[logging.Logger] = None):
        self.plan_queue = plan_queue
        self.fsm = fsm
        self.log = log
        self.logger = logger or logging.getLogger("nomad_tpu.plan_apply")
        self.pool = ThreadPoolExecutor(
            max_workers=max(pool_size, 1), thread_name_prefix="plan-eval"
        )
        # Dedicated single-thread executor: commits stay ordered.
        self._commit_pool = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="plan-commit"
        )
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # The outgoing generation's thread, kept so start() can wait
        # out its final in-flight commit before spawning a successor.
        self._draining: Optional[threading.Thread] = None
        self._lifecycle = threading.Lock()  # start/stop can race on
        # leadership flaps (raft elections)
        # Conflict observability (feeds the dispatch pipeline's
        # retries-per-eval accounting and the bench's A/B column):
        # counters only ever touched on the applier thread.
        self.plans_evaluated = 0
        self.plans_rejected = 0  # plans that lost >= 1 node (refresh)
        self.nodes_rejected = 0  # node verifications that failed
        # Gang atomicity (nomad_tpu/gang): whole gangs removed because
        # a member's node failed verification — every one of these is a
        # proven nothing-partial-committed event.
        self.gangs_rejected = 0

    def start(self) -> None:
        with self._lifecycle:
            # Idempotent: a re-confirmed leadership (start without an
            # intervening stop) must not spawn a second loop — with
            # per-generation stop events the first one would become
            # permanently unstoppable.
            if self._thread is not None and self._thread.is_alive():
                return
            draining, self._draining = self._draining, None
        if draining is not None and draining.is_alive():
            # Wait out the predecessor's final in-flight commit OUTSIDE
            # the lock: two live loops would verify plans against
            # snapshots that miss each other's commits — the serial
            # verification invariant the single applier exists for.
            draining.join(timeout=5.0)
            if draining.is_alive():
                # Still wedged past the bound: REFUSE to spawn a
                # concurrent successor. One missing applier stalls the
                # plan queue visibly; two live ones double-place
                # silently. The next leadership confirmation retries.
                with self._lifecycle:
                    self._draining = draining
                self.logger.error(
                    "plan applier predecessor still draining after "
                    "5s; refusing to start a concurrent loop")
                return
        with self._lifecycle:
            if self._thread is not None and self._thread.is_alive():
                return  # lost a start/start race while joining
            # Fresh Event PER thread generation: clearing a shared
            # event here could race a stop()'s set before the outgoing
            # thread observed it (stop joins OUTSIDE the lock), leaving
            # two _run loops alive after a leadership flap.
            stop = threading.Event()
            self._stop = stop
            thread = threading.Thread(
                target=self._run, args=(stop,), name="plan-applier",
                daemon=True
            )
            thread.start()
            self._thread = thread

    def stop(self) -> None:
        # Detach under the lock, join outside it: holding _lifecycle
        # across the join would block a concurrent start() for the
        # whole drain instead of serializing just the handoff. The
        # detached thread is remembered in _draining so a prompt
        # restart waits for its final commit.
        with self._lifecycle:
            self._stop.set()
            thread, self._thread = self._thread, None
            if thread is not None:
                self._draining = thread
        if thread is not None:
            thread.join(timeout=5.0)

    def _run(self, stop: Optional[threading.Event] = None) -> None:
        stop = stop if stop is not None else self._stop
        inflight = None  # (future, pending) of the in-flight commit
        optimistic: Optional[OptimisticSnapshot] = None
        while not stop.is_set():
            pending = self.plan_queue.dequeue(
                timeout=0.02 if inflight else 0.25)
            if pending is None:
                if inflight is not None:
                    self._wait_commit(inflight)
                    inflight = None
                optimistic = None  # queue drained: next gets fresh state
                continue
            if inflight is None:
                # Nothing outstanding: every plan verifies against
                # fresh state (the pre-pipelining invariant). The
                # optimistic overlay only ever spans ONE in-flight
                # commit — a rejected or no-op plan must not pin the
                # next one to a stale base.
                optimistic = OptimisticSnapshot(self.fsm.state.snapshot())
            try:
                start = time.monotonic()
                # Verified against the optimistic view WHILE the
                # previous plan's raft commit is still in flight — the
                # reference's verify-(N+1)-during-commit-(N) overlap.
                result = self._evaluate_plan(optimistic, pending.plan)
                metrics.measure_since(("plan", "evaluate"), start)
            except Exception as e:  # noqa: BLE001 - fail the one plan
                self.logger.exception("plan evaluate failed")
                pending.respond(None, e)
                continue
            if inflight is not None:
                ok = self._wait_commit(inflight)
                inflight = None
                # Rebase on committed state either way: staleness is
                # bounded to one commit's duration (the old per-plan
                # fresh snapshot invariant, now per-commit), and node
                # drains/client updates applied meanwhile are seen.
                optimistic = OptimisticSnapshot(self.fsm.state.snapshot())
                if not ok:
                    # The old view contained allocs that never landed:
                    # this plan's verification must be redone.
                    try:
                        result = self._evaluate_plan(optimistic,
                                                     pending.plan)
                    except Exception as e:  # noqa: BLE001
                        pending.respond(None, e)
                        continue
            if result.is_no_op():
                pending.respond(result, None)
                continue
            fut = self._commit_pool.submit(self._commit, pending.plan, result)
            # The waiter is answered the INSTANT the commit lands, not
            # when this loop next wakes: a worker ping-ponging plans
            # with an idle-queue applier would otherwise pay the full
            # dequeue timeout per plan in response latency (~20 ms,
            # which capped the whole control plane near 50 plans/s).
            fut.add_done_callback(self._make_responder(pending, result))
            optimistic.add_result(result)
            inflight = (fut, pending)
        if inflight is not None:
            self._wait_commit(inflight)

    @staticmethod
    def _make_responder(pending, result: PlanResult):
        def _respond(fut) -> None:
            try:
                result.alloc_index = fut.result()
                pending.respond(result, None)
            except Exception as e:  # noqa: BLE001 - fail the one plan
                pending.respond(None, e)

        return _respond

    def _wait_commit(self, inflight) -> bool:
        """Wait out an in-flight raft commit; False when it failed
        (asyncPlanWait, plan_apply.go:166). The waiter was already
        answered by the commit future's done-callback. No extra timeout
        here: log.apply has its own bounded timeouts, and abandoning a
        still-running commit would let it land after the pipeline moved
        on (double-commit on retry)."""
        fut, _pending = inflight
        try:
            fut.result()
            return True
        except Exception:  # noqa: BLE001 - logged; waiter already told
            self.logger.exception("plan commit failed")
            return False

    def _note_stale_state(self) -> None:
        """A node verification failed in a way ordinary optimistic
        concurrency cannot explain: the matrix claimed a fit that its
        OWN snapshot refutes. Mark the resident delta chain suspect so
        the next cacheable matrix build pays one full rebuild instead
        of trusting it (models/resident.py; the carve-over of the
        reference's plan_apply.go:318 exactness)."""
        from ..models.resident import note_rejection

        note_rejection()

    @staticmethod
    def _ordinary_conflict(snapshot, plan: Plan, node_id: str) -> bool:
        """Whether this node's rejection is explained by state the
        scheduler's matrix could not have seen: an in-flight pipelined
        plan's accepted allocs, or node/alloc changes committed after
        the plan's matrix watermark. True means a routine optimistic-
        concurrency loss (the replan refreshes past it) — purging the
        whole device-resident base cache for it would degenerate a
        conflict-heavy storm back into rebuild-per-snapshot. False (or
        no watermark) means the resident chain itself is suspect."""
        if plan.matrix_index < 0:
            return False
        extra = getattr(snapshot, "_extra_by_node", None)
        if extra and extra.get(node_id):
            return True
        base = getattr(snapshot, "base", snapshot)
        node = base.node_by_id(node_id)
        if node is not None and node.modify_index > plan.matrix_index:
            return True
        return any(a.modify_index > plan.matrix_index
                   for a in base.allocs_by_node(node_id))

    def _evaluate_plan(self, snapshot, plan: Plan) -> PlanResult:
        """Per-node verification with partial commit
        (plan_apply.go:194 evaluatePlan)."""
        _t0 = time.monotonic()
        result = PlanResult(
            node_update=dict(plan.node_update),
            node_allocation=dict(plan.node_allocation),
            node_preemptions=dict(plan.node_preemptions),
        )

        node_ids = (set(plan.node_update) | set(plan.node_allocation)
                    | set(plan.node_preemptions))
        futures = {
            node_id: self.pool.submit(evaluate_node_plan, snapshot, plan, node_id)
            for node_id in node_ids
        }
        self.plans_evaluated += 1
        rejected = 0
        suspect = False
        rejected_nodes = set()
        for node_id, fut in futures.items():
            if fut.result():
                continue
            # This node's changes don't fit anymore.
            rejected += 1
            metrics.incr_counter(("plan", "node_rejected"))
            if not self._ordinary_conflict(snapshot, plan, node_id):
                suspect = True
            if plan.all_at_once:
                # Whole-plan gang commit: reject everything, force a
                # refresh.
                result.node_update = {}
                result.node_allocation = {}
                result.node_preemptions = {}
                result.refresh_index = snapshot.latest_index()
                self.plans_rejected += 1
                self.nodes_rejected += rejected
                if suspect:
                    self._note_stale_state()
                trace.record_span(
                    plan.eval_id, trace.STAGE_PLAN_EVALUATE, _t0,
                    ann={"nodes_rejected": rejected, "gang": True},
                    create=False)
                return result
            rejected_nodes.add(node_id)
        # Gang atomicity leg (nomad_tpu/gang): which nodes host which
        # gang's members — decided from the PLAN (gang_groups stages
        # alloc ids), applied to the RESULT below. The chaos site
        # models an applier-side under-fit on exactly one member node;
        # the invariant under test is that the whole gang rejects.
        gang_nodes: Dict[str, set] = {}
        if plan.gang_groups:
            id_to_gang = {aid: gk
                          for gk, ids in plan.gang_groups.items()
                          for aid in ids}
            for node_id, placed in plan.node_allocation.items():
                for alloc in placed:
                    gk = id_to_gang.get(alloc.id)
                    if gk is not None:
                        gang_nodes.setdefault(gk, set()).add(node_id)
            from ..chaos import chaos

            if chaos.enabled and chaos.fire(
                    "gang.partial_commit",
                    eval_id=plan.eval_id) == "drop":
                for gk in sorted(gang_nodes):
                    nodes = sorted(gang_nodes[gk] - rejected_nodes)
                    if nodes:
                        rejected += 1
                        rejected_nodes.add(nodes[0])
                        break
        for node_id in rejected_nodes:
            result.node_update.pop(node_id, None)
            result.node_allocation.pop(node_id, None)
            result.node_preemptions.pop(node_id, None)
            result.refresh_index = snapshot.latest_index()
        # All-K-or-nothing: a gang with ANY member on a rejected node
        # loses EVERY member — filtered off accepted nodes too.
        # Removing allocs only frees capacity, so the surviving
        # placements that verified alongside them still fit.
        doomed = sorted(gk for gk, nodes in gang_nodes.items()
                        if nodes & rejected_nodes)
        for gk in doomed:
            ids = set(plan.gang_groups.get(gk, ()))
            for node_id in sorted(gang_nodes[gk] - rejected_nodes):
                placed = result.node_allocation.get(node_id)
                if not placed:
                    continue
                kept = [a for a in placed if a.id not in ids]
                if kept:
                    result.node_allocation[node_id] = kept
                else:
                    del result.node_allocation[node_id]
            result.refresh_index = snapshot.latest_index()
        if doomed:
            self.gangs_rejected += len(doomed)
            metrics.incr_counter(("plan", "gang_rejected"), len(doomed))
        if rejected:
            self.plans_rejected += 1
            self.nodes_rejected += rejected
            if suspect:
                self._note_stale_state()
        # create=False: the applier serves remote (follower-worker)
        # plans too — their lifecycle trace lives in the follower's
        # process, not this one.
        ann = None
        if rejected or doomed:
            ann = {"nodes_rejected": rejected}
            if doomed:
                ann["gangs_rejected"] = len(doomed)
        trace.record_span(
            plan.eval_id, trace.STAGE_PLAN_EVALUATE, _t0,
            ann=ann, create=False)
        return result

    def stats(self) -> dict:
        """Conflict counters: how often optimistic plans lost node
        verifications (each rejection is a replan round-trip somewhere
        upstream — the dispatch pipeline's A/B measures these)."""
        return {
            "plans_evaluated": self.plans_evaluated,
            "plans_rejected": self.plans_rejected,
            "nodes_rejected": self.nodes_rejected,
            "gangs_rejected": self.gangs_rejected,
        }

    def _commit(self, plan: Plan, result: PlanResult) -> int:
        start = time.monotonic()
        allocs: List[Allocation] = []
        for update_list in result.node_update.values():
            allocs.extend(update_list)
        n_preempted = 0
        for victim_list in result.node_preemptions.values():
            # Victims ride the SAME raft apply as the placements they
            # make room for: one log entry, one terminal stamp — the
            # exactly-once contract the preemption soak asserts.
            allocs.extend(victim_list)
            n_preempted += len(victim_list)
        for alloc_list in result.node_allocation.values():
            allocs.extend(alloc_list)
        index = self.log.apply(
            ALLOC_UPDATE, {"allocs": allocs, "job": plan.job}
        )
        if n_preempted:
            from ..migrate import note_preemption_committed

            note_preemption_committed(n_preempted)
        trace.record_span(plan.eval_id, trace.STAGE_PLAN_COMMIT, start,
                          ann={"allocs": len(allocs)}, create=False)
        # Stamp indexes onto the result's alloc objects the way the Go
        # store mutates shared pointers — workers count fresh placements
        # by create_index == alloc_index (scheduler/util.py).
        for alloc_list in result.node_allocation.values():
            for alloc in alloc_list:
                stored = self.fsm.state.alloc_by_id(alloc.id)
                if stored is not None:
                    alloc.create_index = stored.create_index
                    alloc.modify_index = stored.modify_index
        metrics.measure_since(("plan", "submit"), start)
        return index
