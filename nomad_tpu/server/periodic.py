"""PeriodicDispatch: leader-only cron launcher for periodic jobs.

Reference: nomad/periodic.go:135 — a heap of (next launch time, job);
children are derived as '<id>/periodic-<epoch>' (periodic.go:400) and
forced through the normal register+eval path; prohibit_overlap skips a
launch while a previous child is non-terminal.
"""

from __future__ import annotations

import heapq
import logging
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..structs import Job, consts

PERIODIC_LAUNCH_SUFFIX = "/periodic-"


def derive_job(parent: Job, launch_time: float) -> Job:
    child = parent.copy()
    child.parent_id = parent.id
    child.id = f"{parent.id}{PERIODIC_LAUNCH_SUFFIX}{int(launch_time)}"
    child.name = child.id
    child.periodic = None
    child.status = ""
    return child


class PeriodicDispatch:
    def __init__(self, server):
        self.server = server
        self.logger = logging.getLogger("nomad_tpu.periodic")
        self._lock = threading.RLock()
        self._cond = threading.Condition(self._lock)
        self._enabled = False
        self._running = False
        self._tracked: Dict[str, Job] = {}
        self._heap: List[Tuple[float, str]] = []  # (next launch, job id)
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------------

    def set_enabled(self, enabled: bool) -> None:
        with self._lock:
            self._enabled = enabled
            if enabled and not self._running:
                self._running = True
                self._thread = threading.Thread(
                    target=self._run, name="periodic-dispatch", daemon=True
                )
                self._thread.start()
            if not enabled:
                self._tracked.clear()
                self._heap = []
                self._running = False
                self._cond.notify_all()

    def tracked(self) -> List[Job]:
        with self._lock:
            return list(self._tracked.values())

    def add(self, job: Job) -> None:
        with self._lock:
            if not self._enabled:
                return
            if not job.is_periodic():
                self._untrack(job.id)
                return
            self._tracked[job.id] = job
            nxt = job.periodic.next_launch(time.time())
            if nxt is not None:
                heapq.heappush(self._heap, (nxt, job.id))
                self._cond.notify_all()

    def remove(self, job_id: str) -> None:
        with self._lock:
            self._untrack(job_id)

    def _untrack(self, job_id: str) -> None:
        self._tracked.pop(job_id, None)
        self._heap = [(t, j) for t, j in self._heap if j != job_id]
        heapq.heapify(self._heap)
        self._cond.notify_all()

    # ------------------------------------------------------------------

    def force_run(self, job_id: str) -> Optional[str]:
        """Periodic.Force endpoint: launch now (periodic.go:46)."""
        with self._lock:
            job = self._tracked.get(job_id)
        if job is None:
            raise ValueError(f"job {job_id!r} is not tracked as periodic")
        return self._dispatch(job, time.time())

    def _run(self) -> None:
        while True:
            with self._lock:
                if not self._enabled:
                    return
                if not self._heap:
                    self._cond.wait(1.0)
                    continue
                launch_time, job_id = self._heap[0]
                now = time.time()
                if launch_time > now:
                    self._cond.wait(min(launch_time - now, 1.0))
                    continue
                heapq.heappop(self._heap)
                job = self._tracked.get(job_id)
                if job is None:
                    continue
                nxt = job.periodic.next_launch(launch_time)
                if nxt is not None:
                    heapq.heappush(self._heap, (nxt, job_id))
            try:
                self._dispatch(job, launch_time)
            except Exception:
                self.logger.exception("periodic launch of %s failed", job_id)

    def _dispatch(self, job: Job, launch_time: float) -> Optional[str]:
        if job.periodic.prohibit_overlap:
            children = [
                j for j in self.server.fsm.state.jobs()
                if j.parent_id == job.id and j.status != consts.JOB_STATUS_DEAD
            ]
            if children:
                self.logger.debug(
                    "skipping launch of %s: child still running", job.id
                )
                return None
        child = derive_job(job, launch_time)
        self.server.job_register(child, triggered_by=consts.EVAL_TRIGGER_PERIODIC_JOB)
        self.server.periodic_launch_record(job.id, launch_time)
        return child.id
