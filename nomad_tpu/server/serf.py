"""Gossip membership: the serf/memberlist analog.

Reference: nomad/serf.go (event handler wiring peers/localPeers maps,
server.go:100-104), server tags at server.go:740-760, and Serf's
push-pull anti-entropy protocol. The reference rides hashicorp/serf
(SWIM over UDP/TCP); here membership is a TCP digest gossip: each
member runs a small listener, periodically exchanges an incarnation
digest with one random alive peer (full member records cross the wire
only for rows the digests disagree on — O(changes), not O(members)
state per round), and marks peers failed after consecutive probe
failures. Member records carry lamport-style incarnation numbers so
newer information wins and a live member can refute its own death.
SWIM-style indirect UDP probing is still out of scope: failure
detection is direct-probe only, which is fine at server-pool sizes
(~3-7 per region) though not at client-pool scale.

This layer only tracks *server* membership (within and across regions)
— clients discover servers via the HTTP API, as in the reference.
"""

from __future__ import annotations

import json
import logging
import random
import socket
import socketserver
import struct
import threading
import time
from dataclasses import asdict, dataclass, field
from typing import Callable, Dict, List, Optional

_HEADER = struct.Struct(">I")
CONNECT_TIMEOUT = 1.0

# Member statuses (serf's alive/leaving/left/failed, collapsed).
ALIVE = "alive"
LEFT = "left"
FAILED = "failed"

# Equal-incarnation precedence (SWIM's dead-state dominance): a
# FAILED/LEFT claim at incarnation k beats ALIVE at k — only the
# member ITSELF refutes, by re-asserting ALIVE at k+1 (_merge's
# self-refutation branch). Without this ordering a detector's FAILED
# marking would be erased by any peer still holding ALIVE at the same
# incarnation, and failure information could never spread.
_STATUS_RANK = {ALIVE: 0, FAILED: 1, LEFT: 2}


def _outranks(a: str, b: str) -> bool:
    return _STATUS_RANK.get(a, 0) > _STATUS_RANK.get(b, 0)

# Gossip events (serf.go: serfEventHandler switch).
EVENT_JOIN = "member-join"
EVENT_LEAVE = "member-leave"
EVENT_FAILED = "member-failed"
EVENT_UPDATE = "member-update"


@dataclass
class Member:
    """One server in the gossip pool.

    Tags mirror the reference's serf tags (server.go:740-760): role,
    region, dc, build, bootstrap expectation, plus the addresses other
    layers need (rpc_addr for raft forwarding, http_addr for region
    forwarding of API requests).
    """

    name: str
    region: str = "global"
    datacenter: str = "dc1"
    addr: str = ""  # gossip host:port
    status: str = ALIVE
    incarnation: int = 0
    tags: Dict[str, str] = field(default_factory=dict)

    def to_wire(self) -> dict:
        return asdict(self)

    @classmethod
    def from_wire(cls, d: dict) -> "Member":
        return cls(
            name=d["name"],
            region=d.get("region", "global"),
            datacenter=d.get("datacenter", "dc1"),
            addr=d.get("addr", ""),
            status=d.get("status", ALIVE),
            incarnation=int(d.get("incarnation", 0)),
            tags=dict(d.get("tags") or {}),
        )


def _send_frame(sock: socket.socket, obj: dict) -> None:
    data = json.dumps(obj).encode()
    sock.sendall(_HEADER.pack(len(data)) + data)


def _recv_frame(sock: socket.socket) -> Optional[dict]:
    buf = b""
    while len(buf) < _HEADER.size:
        chunk = sock.recv(_HEADER.size - len(buf))
        if not chunk:
            return None
        buf += chunk
    (length,) = _HEADER.unpack(buf)
    data = b""
    while len(data) < length:
        chunk = sock.recv(length - len(data))
        if not chunk:
            return None
        data += chunk
    return json.loads(data)


class Serf:
    """TCP push-pull gossip pool member.

    on_event(event: str, member: Member) is invoked (outside the lock)
    for join/leave/failed/update transitions — the server wires this to
    its peers/localPeers maps exactly like serf.go's serfEventHandler.
    """

    def __init__(
        self,
        name: str,
        region: str = "global",
        datacenter: str = "dc1",
        tags: Optional[Dict[str, str]] = None,
        on_event: Optional[Callable[[str, Member], None]] = None,
        probe_interval: float = 1.0,
        suspicion_probes: int = 3,
        ssl_server_ctx=None,
        ssl_client_ctx=None,
    ):
        self.logger = logging.getLogger("nomad_tpu.serf")
        self.name = name
        # mTLS (agent tls block): gossip carries the addresses leader
        # and cross-region forwarding dial, so an unauthenticated
        # gossip port would let any network peer inject member records
        # and redirect the very traffic the other channels' TLS
        # protects. Plaintext or wrong-CA peers fail the handshake.
        self.ssl_server_ctx = ssl_server_ctx
        self.ssl_client_ctx = ssl_client_ctx
        self.on_event = on_event
        self.probe_interval = probe_interval
        self.suspicion_probes = suspicion_probes
        self._lock = threading.Lock()
        self._local = Member(
            name=name, region=region, datacenter=datacenter, tags=dict(tags or {})
        )
        self._members: Dict[str, Member] = {name: self._local}
        self._fail_counts: Dict[str, int] = {}
        self._server: Optional[socketserver.ThreadingTCPServer] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------ serving

    def serve(self, host: str = "127.0.0.1", port: int = 0) -> str:
        serf = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                sock = self.request
                try:
                    # Bounded reads: the digest exchange has a second
                    # inbound frame, and an initiator dying mid-exchange
                    # must not pin this handler thread in recv forever.
                    # Armed before the TLS handshake so a silent
                    # connect can't pin the thread either.
                    sock.settimeout(CONNECT_TIMEOUT * 5)
                    if serf.ssl_server_ctx is not None:
                        sock = serf.ssl_server_ctx.wrap_socket(
                            sock, server_side=True)
                    self.request = sock
                    msg = _recv_frame(sock)
                    if msg is None:
                        return
                    if msg.get("kind") == "push_pull":
                        # Legacy full-table exchange (kept for mixed
                        # versions during a rolling upgrade).
                        remote = [Member.from_wire(m) for m in msg["members"]]
                        serf._merge(remote)
                        _send_frame(
                            self.request,
                            {"members": [m.to_wire() for m in serf.members()]},
                        )
                    elif msg.get("kind") == "push_pull_digest":
                        # Digest anti-entropy: the initiator sent only
                        # {name: [incarnation, status]}; full records
                        # cross the wire ONLY where the digests
                        # disagree — O(changes), not O(members²) state
                        # per round at steady gossip.
                        digest = msg.get("digest") or {}
                        updates, want = serf._diff_digest(digest)
                        _send_frame(self.request, {
                            "updates": [m.to_wire() for m in updates],
                            "want": want,
                        })
                        reply = _recv_frame(self.request)
                        if reply and reply.get("updates"):
                            serf._merge([Member.from_wire(m)
                                         for m in reply["updates"]])
                except (OSError, ValueError):
                    pass

        # Reuse-addr: a member restarting on its configured gossip port
        # must not fail on TIME_WAIT sockets from its previous run.
        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True

        self._server = Server((host, port), Handler)
        self._server.daemon_threads = True
        addr = "%s:%d" % self._server.server_address
        with self._lock:
            self._local.addr = addr
        threading.Thread(
            target=self._server.serve_forever, name="serf-listen", daemon=True
        ).start()
        self._thread = threading.Thread(
            target=self._gossip_loop, name="serf-gossip", daemon=True
        )
        self._thread.start()
        return addr

    def shutdown(self) -> None:
        self._stop.set()
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()

    # ------------------------------------------------------------- public

    @property
    def local_member(self) -> Member:
        return self._local

    def members(self) -> List[Member]:
        with self._lock:
            return [
                Member(
                    name=m.name,
                    region=m.region,
                    datacenter=m.datacenter,
                    addr=m.addr,
                    status=m.status,
                    incarnation=m.incarnation,
                    tags=dict(m.tags),
                )
                for m in self._members.values()
            ]

    def alive_members(self) -> List[Member]:
        return [m for m in self.members() if m.status == ALIVE]

    def join(self, addrs: List[str]) -> int:
        """Push-pull sync with each address; returns contact count."""
        joined = 0
        for addr in addrs:
            if self._push_pull(addr):
                joined += 1
        return joined

    def leave(self) -> None:
        """Graceful leave: bump incarnation, mark left, broadcast."""
        with self._lock:
            self._local.incarnation += 1
            self._local.status = LEFT
            peers = [
                m.addr
                for m in self._members.values()
                if m.name != self.name and m.status == ALIVE and m.addr
            ]
        for addr in peers:
            self._push_pull(addr)
        self.shutdown()

    def force_leave(self, name: str) -> bool:
        """Operator eviction of a failed member (serf RemoveFailedNode)."""
        with self._lock:
            m = self._members.get(name)
            if m is None:
                return False
            m.status = LEFT
            m.incarnation += 1
        self._fire(EVENT_LEAVE, m)
        return True

    def set_tags(self, tags: Dict[str, str]) -> None:
        with self._lock:
            self._local.tags.update(tags)
            self._local.incarnation += 1

    # ----------------------------------------------------------- internal

    def _gossip_loop(self) -> None:
        while not self._stop.wait(self.probe_interval):
            with self._lock:
                candidates = [
                    m
                    for m in self._members.values()
                    if m.name != self.name and m.status == ALIVE and m.addr
                ]
            if not candidates:
                continue
            target = random.choice(candidates)
            if self._push_pull(target.addr):
                self._fail_counts.pop(target.name, None)
            else:
                n = self._fail_counts.get(target.name, 0) + 1
                self._fail_counts[target.name] = n
                if n >= self.suspicion_probes:
                    self._mark_failed(target.name)

    def _digest(self) -> Dict[str, list]:
        with self._lock:
            return {m.name: [m.incarnation, m.status]
                    for m in self._members.values()}

    def _diff_digest(self, digest: Dict[str, list]):
        """(records newer here than the digest, names newer there).
        "Newer" follows incarnation first, then the equal-incarnation
        status precedence (_outranks): failure detection is a status
        edge at the victim's current incarnation, and it must both
        propagate outward and never be pulled back by a stale ALIVE."""
        updates: List[Member] = []
        want: List[str] = []
        with self._lock:
            for m in self._members.values():
                ent = digest.get(m.name)
                if (ent is None or m.incarnation > int(ent[0])
                        or (m.incarnation == int(ent[0])
                            and _outranks(m.status, ent[1]))):
                    updates.append(m)
            for name, ent in digest.items():
                cur = self._members.get(name)
                if (cur is None or int(ent[0]) > cur.incarnation
                        or (int(ent[0]) == cur.incarnation
                            and _outranks(ent[1], cur.status))):
                    want.append(name)
        return updates, want

    def _connect(self, addr: str) -> socket.socket:
        host, port_s = addr.rsplit(":", 1)
        sock = socket.create_connection(
            (host, int(port_s)), timeout=CONNECT_TIMEOUT)
        if self.ssl_client_ctx is not None:
            sock = self.ssl_client_ctx.wrap_socket(
                sock, server_hostname=host)
        return sock

    def _push_pull(self, addr: str) -> bool:
        """Digest-based anti-entropy round (memberlist pushPull with a
        digest instead of the full state): exchange {name:
        incarnation/status} summaries, ship full member records only
        for the rows the summaries disagree on."""
        try:
            with self._connect(addr) as sock:
                sock.settimeout(CONNECT_TIMEOUT)
                _send_frame(sock, {"kind": "push_pull_digest",
                                   "digest": self._digest()})
                resp = _recv_frame(sock)
                if resp is None:
                    # A pre-digest peer drops unknown kinds: fall back
                    # to the legacy full-table exchange rather than
                    # counting a healthy old-version server as a probe
                    # failure (which would mark the whole un-upgraded
                    # pool FAILED during a rolling upgrade).
                    return self._push_pull_full(addr)
                if resp.get("updates"):
                    self._merge([Member.from_wire(m)
                                 for m in resp["updates"]])
                wanted = resp.get("want") or []
                with self._lock:
                    send = [self._members[n].to_wire()
                            for n in wanted if n in self._members]
                _send_frame(sock, {"updates": send})
                return True
        except (OSError, ValueError):
            return False

    def _push_pull_full(self, addr: str) -> bool:
        """Legacy full-table exchange (pre-digest wire protocol)."""
        try:
            with self._connect(addr) as sock:
                sock.settimeout(CONNECT_TIMEOUT)
                with self._lock:
                    local = [m.to_wire() for m in self._members.values()]
                _send_frame(sock, {"kind": "push_pull", "members": local})
                resp = _recv_frame(sock)
                if resp is None:
                    return False
                self._merge([Member.from_wire(m)
                             for m in resp.get("members", [])])
                return True
        except (OSError, ValueError):
            return False

    def _merge(self, remote: List[Member]) -> None:
        events: List[tuple] = []
        with self._lock:
            for rm in remote:
                if rm.name == self.name:
                    # Refute rumors of our own death/leave (serf alive
                    # rebroadcast with a higher incarnation).
                    if (
                        rm.status != ALIVE
                        and rm.incarnation >= self._local.incarnation
                    ):
                        self._local.incarnation = rm.incarnation + 1
                    continue
                cur = self._members.get(rm.name)
                if cur is None:
                    self._members[rm.name] = rm
                    if rm.status == ALIVE:
                        events.append((EVENT_JOIN, rm))
                    continue
                if rm.incarnation < cur.incarnation:
                    continue
                if (rm.incarnation == cur.incarnation
                        and not _outranks(rm.status, cur.status)):
                    continue
                old_status = cur.status
                cur.incarnation = rm.incarnation
                cur.status = rm.status
                cur.addr = rm.addr or cur.addr
                cur.region = rm.region
                cur.datacenter = rm.datacenter
                cur.tags = dict(rm.tags)
                if old_status != cur.status:
                    if cur.status == ALIVE:
                        events.append((EVENT_JOIN, cur))
                    elif cur.status == LEFT:
                        events.append((EVENT_LEAVE, cur))
                    elif cur.status == FAILED:
                        events.append((EVENT_FAILED, cur))
                else:
                    events.append((EVENT_UPDATE, cur))
        for ev, m in events:
            self._fire(ev, m)

    def _mark_failed(self, name: str) -> None:
        with self._lock:
            m = self._members.get(name)
            if m is None or m.status != ALIVE:
                return
            m.status = FAILED
        self.logger.warning("serf: member %s failed (no ack)", name)
        self._fire(EVENT_FAILED, m)

    def _fire(self, event: str, member: Member) -> None:
        if self.on_event is not None:
            try:
                self.on_event(event, member)
            except Exception:  # noqa: BLE001
                self.logger.exception("serf event handler error")
