"""Server configuration.

Reference: nomad/config.go (defaults at :225-238) and
command/agent/agent.go:129 (num_schedulers overlay).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional


@dataclass
class ServerConfig:
    region: str = "global"
    datacenter: str = "dc1"
    node_name: str = ""
    bootstrap_expect: int = 1

    # Scheduling workers (reference default 1; the agent sets NumCPU).
    num_schedulers: int = 1
    # Which scheduler types this server's workers service.
    enabled_schedulers: List[str] = field(
        default_factory=lambda: ["service", "batch", "system", "_core"]
    )
    # Per-type factory overrides, e.g. {"service": "service-tpu"} routes
    # service evals to the TPU placement backend (BASELINE north star:
    # new factories, unchanged control plane).
    scheduler_factories: Dict[str, str] = field(default_factory=dict)

    # Eval broker (config.go:233-234)
    eval_nack_timeout: float = 60.0
    eval_delivery_limit: int = 3

    # Max evals a worker drains per broker visit when the eval's
    # factory is a dense (TPU) one, so their placement programs share
    # one batched device dispatch (extension over the reference's
    # single dequeue, eval_broker.go:259). 1 disables batching.
    # Default = the batcher's MAX_BATCH: a 10k-node storm through a
    # remote-device tunnel measured 0.47x (CPU) at 16-deep drains and
    # 0.92x at 64 — per-dispatch transport dominates, so fewer, fuller
    # dispatches win. Lone/interactive evals never see this (the
    # dense_min_batch router sends them to the host pipeline).
    eval_batch_size: int = 64

    # Latency-aware routing: a dense factory only pays off when the
    # device dispatch amortizes over a batch; a lone interactive eval
    # would eat the full batch-window + dispatch latency for nothing.
    # Drained groups smaller than this run on the host (CPU iterator)
    # factory instead — same placement semantics (CPU/TPU parity is a
    # test invariant), millisecond latency. 1 forces dense always.
    dense_min_batch: int = 2

    # Central dispatch pipeline (nomad_tpu/dispatch): dense-path evals
    # from EVERY worker flow into one leader-side accumulator that
    # packs full device batches, launches them pipelined (next batch
    # accumulates during the in-flight device sync + plan submits),
    # and requeues plan-conflict retries into the ACCUMULATING batch.
    # False reverts to the per-worker drain-then-place loop.
    dispatch_pipeline: bool = True
    # Batches allowed in flight at once: overlap hides the device
    # round-trip + plan-submit tail behind the next accumulation.
    dispatch_max_inflight: int = 2
    # Accumulation window while another batch is in flight (its
    # round-trip is the budget being amortized); the idle grace is all
    # a batch waits when nothing is in flight — a lone interactive
    # eval pays only this before routing to the host path.
    dispatch_window: float = 0.05
    dispatch_idle_grace: float = 0.004
    # Conflict-rejected evals rejoin the accumulating batch at most
    # this many times before falling back to the scheduler's own
    # inline retry loop (bounded like MAX_SERVICE_SCHEDULE_ATTEMPTS).
    dispatch_max_requeues: int = 3

    # ---- Scheduler executive (nomad_tpu/server/executive.py) ----
    # Replace the thread-per-eval dense worker model with a batched
    # event-loop executive: one drain-owner thread pulls whole cohorts
    # from the broker, reconciles them as arrays host-side
    # (scheduler/util.py cohort_reconcile), hands complete batches
    # straight to the device via the batcher's no-park cohort dispatch
    # (place_cohort), and fans results back out through per-eval
    # plan-submit + ack — an evaluation's identity is a batch row, not
    # a parked thread (the BENCH_r13 convoy). False (the default, for
    # A/B and until the rollout flips) keeps the dispatch-pipeline +
    # worker fan-out path; the Worker pool always remains the host/
    # system/fallback scheduler either way.
    scheduler_executive: bool = False
    # Host-side helper threads the executive uses for per-eval matrix
    # builds and plan-submit/ack fan-out WITHIN a cohort (numpy releases
    # the GIL, so a few help; 64 was the convoy). The drain itself is
    # always one thread. Replaces num_schedulers as the dense path's
    # parallelism knob when the executive is on (num_schedulers then
    # only sizes the host/system worker pool — see README migration
    # note).
    executive_threads: int = 4

    # In-batch conflict pre-resolution: serialize the eval axis of a
    # shared-base device dispatch so batch members see each other's
    # capacity claims (ops/binpack.py PlacementConfig.pre_resolve) —
    # cuts plan-applier rejections, each of which costs a replan +
    # dispatch round-trip. False = independent (vmapped) evals.
    dense_pre_resolve: bool = True

    # ---- Placement kernel (nomad_tpu/kernels) ----
    # Which dense placement kernel the *-tpu factories run: "greedy"
    # (the sequential masked-argmax reference reformulation) or
    # "convex" (the convex-relaxation bin-packer), plus any kernel a
    # plugin registered. Validated at server init — a typo fails
    # before the first eval, not inside it. None = leave the
    # process-global active kernel alone (it starts as "greedy"); an
    # EXPLICIT value — including "greedy" — sets it. Per-scheduler-
    # type pins are also available through scheduler_factories (e.g.
    # {"service": "service-convex-tpu"}).
    placement_kernel: Optional[str] = None

    # ---- Device-resident node state (models/resident.py) ----
    # The dense path's [N, R] node matrix lives on device; plan commits
    # and node up/down/drain transitions apply as small scatter deltas
    # keyed on raft index instead of re-shipping the full matrix per
    # batch. False reverts to per-snapshot rebuild + re-upload (the
    # bench A/B arm).
    device_resident: bool = True
    # Max delta-refilled rows before a full rebuild is the better deal;
    # 0 = auto (max(64, N/4)).
    resident_rebuild_rows: int = 0

    # ---- Churn control (nomad_tpu/migrate) ----
    # In-flight migration budget: how many drain-displaced allocs may
    # be claimed by scheduling attempts at once, cluster-wide (the
    # reference's drain max_parallel analog). Displaced allocs past
    # the budget ride follow-up migration evals — a 100-node drain
    # storm re-places in bounded waves instead of thundering-herding
    # the plan queue. 0 = unbounded.
    migrate_max_parallel: int = 32
    # Priority preemption (ops/preempt.py): allow a red-pressure,
    # above-threshold-priority eval whose placements found no room to
    # evict lowest-priority allocs in the same dense pass. Off by
    # default: with it off, a red cluster sheds exactly per the PR 5
    # admission policy.
    preemption_enabled: bool = False
    # Evals must STRICTLY outrank this to preempt (50 = the default
    # job priority, so only above-normal work may evict).
    preempt_priority_threshold: int = 50

    # ---- Continuous defragmentation (nomad_tpu/defrag) ----
    # Leader-side background optimizer: periodically solves the relaxed
    # GLOBAL re-placement (the convex kernel's mirror-descent program,
    # warm-started across rounds) over the device-resident node state
    # and proposes bounded migration waves through the migration budget
    # + verified eviction legs. Off by default — it moves healthy
    # allocs, which is an operator's call to enable.
    defrag_enabled: bool = False
    # Seconds between optimization rounds on a green, led cluster
    # (yellow/red pressure backs off multiplicatively).
    defrag_interval: float = 30.0
    # Minimum NET fragmentation gain (0..1, the quality scoreboard's
    # fragmentation units) a round must measure before it proposes any
    # wave — below it, churning allocs isn't worth the disruption.
    defrag_min_gain: float = 0.01
    # Per-wave move cap; each wave also claims MigrationGovernor slots,
    # so disruption is additionally bounded by migrate_max_parallel
    # (one budget shared with drain storms).
    defrag_max_moves_per_wave: int = 16

    # ---- Read plane (nomad_tpu/readplane) ----
    # Parked-watcher multiplexer: blocking queries past their ?index
    # register a continuation with the mux and free their HTTP handler
    # thread; one wake-owner thread + a small serve pool re-run them
    # on scope notifications. False reverts to thread-parking long
    # polls (the bench --read-storm baseline arm).
    read_mux_enabled: bool = True
    # Serve-pool threads re-running satisfied/expired queries.
    read_mux_workers: int = 4
    # Continuations parked at once before new blocking queries fall
    # back to thread-parking (bounds mux memory under a watcher storm).
    read_mux_max_parked: int = 4096
    # Scoped modify-index tracking: blocking queries wake on — and
    # X-Nomad-Index reports — their watch scope's index instead of the
    # global raft index. False restores global-index wakes (the
    # spurious-wakeup A/B arm); the mux requires scoped tracking, so
    # False also implies thread-parking long polls.
    read_scoped_index: bool = True

    # ---- Overload protection (nomad_tpu/admission) ----
    # Bounded broker ready queues: default per-scheduler-type depth cap
    # (0 = unbounded) plus per-type overrides. A full queue sheds the
    # lowest-priority newest eval with a structured outcome.
    eval_ready_cap: int = 0
    eval_ready_caps: Dict[str, int] = field(default_factory=dict)
    # Eval deadline base TTL in seconds (0 = no deadlines). The
    # effective TTL scales with priority (admission/deadline.py):
    # default-priority evals get exactly this, priority 100 gets 1.5x.
    eval_deadline_ttl: float = 0.0
    # Token-bucket admission control on the HTTP/RPC intake. Buckets
    # only engage past green pressure, so the defaults are inert on an
    # unloaded server; leader-forward + raft + client control traffic
    # and the observability routes are always exempt.
    admission_enabled: bool = True
    admission_write_rate: float = 50.0
    admission_write_burst: float = 100.0
    admission_read_rate: float = 200.0
    admission_read_burst: float = 400.0
    # Retry-After hint (seconds) on red-pressure 503 sheds.
    admission_red_retry_after: float = 1.0
    # Absolute broker-depth thresholds (ready+unacked) used when ready
    # queues are UNcapped; capped queues use fractions of the cap.
    admission_depth_yellow: int = 256
    admission_depth_red: int = 1024
    # Rolling e2e p99 thresholds in ms (0 disables the latency input —
    # absolute latency bars are deployment-specific).
    admission_p99_yellow_ms: float = 0.0
    admission_p99_red_ms: float = 0.0
    # Device-path circuit breaker (admission/breaker.py): trip to the
    # host path after this many CONSECUTIVE device failures (or slow
    # batches when breaker_slow_ms > 0), cool down, then half-open
    # probe back.
    breaker_enabled: bool = True
    breaker_failure_threshold: int = 5
    breaker_slow_ms: float = 0.0
    breaker_slow_batches: int = 8
    breaker_cooldown: float = 5.0

    # ---- Contention observatory (nomad_tpu/profile) ----
    # Always-on lock/GIL/pipeline profiler, like the flight recorder:
    # ProfiledLock wait/hold histograms on the hot locks, the
    # GIL-pressure sampler thread, and the batch-boundary convoy
    # detector. False disables recording (the bench --profile-off arm)
    # and stops the sampler; the lock wrappers stay in place either
    # way.
    profile_enabled: bool = True
    # GIL sampler sleep-request interval in seconds (~200 wakes/s at
    # the default; the overshoot distribution is the measurement).
    # Values <= 0 are ignored (a zero interval would spin); to stop
    # the sampler, disable the observatory via profile_enabled.
    gil_sampler_interval: float = 0.005
    # Pressure-monitor thresholds on the WORST per-site contended
    # lock-wait p99 in ms (0 disables the input — like the e2e p99
    # thresholds, absolute bars are deployment-specific). When set,
    # yellow/red pressure reasons cite the hottest lock site.
    admission_lock_wait_yellow_ms: float = 0.0
    admission_lock_wait_red_ms: float = 0.0

    # Telemetry gauge emission period (command.go:570 setupTelemetry)
    telemetry_interval: float = 10.0
    statsd_addr: str = ""

    # Heartbeats (config.go:235-238)
    min_heartbeat_ttl: float = 10.0
    max_heartbeats_per_second: float = 50.0
    heartbeat_grace: float = 10.0

    # GC (config.go:227-232)
    eval_gc_interval: float = 300.0
    eval_gc_threshold: float = 3600.0
    job_gc_interval: float = 300.0
    job_gc_threshold: float = 4 * 3600.0
    node_gc_interval: float = 300.0
    node_gc_threshold: float = 24 * 3600.0

    # Plan verification pool size (plan_apply.go:48: NumCPU/2).
    plan_verify_workers: int = 2

    # Blocked-evals failed-eval unblock cadence (leader.go:441).
    failed_eval_unblock_interval: float = 60.0

    # Vault token authority (nomad/vault.go). With vault_addr set the
    # server talks to a real Vault over HTTP using vault_token as its
    # own token (renewed at half-life); otherwise an in-process stub
    # keeps the derive→renew→revoke lifecycle working vault-less.
    vault_enabled: bool = True
    vault_addr: str = ""
    vault_token: str = ""
    vault_token_ttl: float = 3600.0
    # None = any policy except root; else an allowlist.
    vault_allowed_policies: Optional[List[str]] = None

    def factory_for(self, eval_type: str) -> str:
        return self.scheduler_factories.get(eval_type, eval_type)
