"""FSM: applies replicated log entries to the state store, with
leader-side hooks into the broker / blocked-evals / periodic services.

Reference: nomad/fsm.go:44 (nomadFSM), :102 (Apply switch over the
message types of structs.go:40-56), :506/:520 (Snapshot/Restore).
"""

from __future__ import annotations

import logging
import threading
import time
from collections import OrderedDict
from typing import Callable, Dict, List, Optional

from ..state import PeriodicLaunch, StateStore
from ..utils import metrics
from ..structs import Allocation, Evaluation, Job, Node, consts
from .. import trace
from .timetable import TimeTable

# ntalint raft-funnel manifest (analysis/protocol.py): THE sanctioned
# commit path. State-store mutators and terminal status stamps are
# only legal inside these handlers' whole-program call closure (or,
# for stamps, on a copy that flows into an eval_update/alloc_update
# submit in the same function). Everything here runs on the serialized
# raft apply thread on every replica — the one place a write cannot
# diverge or double-commit.
NTA_RAFT_FUNNELS = (
    "FSM.apply",
    "FSM._apply_node_register",
    "FSM._apply_node_deregister",
    "FSM._apply_node_status",
    "FSM._apply_node_drain",
    "FSM._apply_job_register",
    "FSM._apply_job_deregister",
    "FSM._apply_eval_update",
    "FSM._apply_eval_delete",
    "FSM._apply_alloc_update",
    "FSM._apply_alloc_client_update",
    "FSM._apply_periodic_launch",
    "FSM._apply_periodic_launch_delete",
    "FSM._apply_vault_accessor_register",
    "FSM._apply_vault_accessor_deregister",
    "FSM.restore",
)

# Log message types (structs.go:40-53)
NODE_REGISTER = "node_register"
NODE_DEREGISTER = "node_deregister"
NODE_UPDATE_STATUS = "node_update_status"
NODE_UPDATE_DRAIN = "node_update_drain"
JOB_REGISTER = "job_register"
JOB_DEREGISTER = "job_deregister"
EVAL_UPDATE = "eval_update"
EVAL_DELETE = "eval_delete"
ALLOC_UPDATE = "alloc_update"
ALLOC_CLIENT_UPDATE = "alloc_client_update"
PERIODIC_LAUNCH = "periodic_launch"
PERIODIC_LAUNCH_DELETE = "periodic_launch_delete"
VAULT_ACCESSOR_REGISTER = "vault_accessor_register"
VAULT_ACCESSOR_DEREGISTER = "vault_accessor_deregister"


class FSM:
    def __init__(self, logger: Optional[logging.Logger] = None):
        self.logger = logger or logging.getLogger("nomad_tpu.fsm")
        self.state = StateStore()
        self.timetable = TimeTable()
        # Leader-only services, attached while this server is leader
        # (fsm.go enqueues into the broker only on the leader).
        self.broker = None
        self.blocked_evals = None
        self.periodic = None
        self.last_applied_index = 0
        # Recent apply outcomes (rejections), bounded; keyed by index.
        self._outcomes: "OrderedDict[int, object]" = OrderedDict()
        self._handlers: Dict[str, Callable] = {
            NODE_REGISTER: self._apply_node_register,
            NODE_DEREGISTER: self._apply_node_deregister,
            NODE_UPDATE_STATUS: self._apply_node_status,
            NODE_UPDATE_DRAIN: self._apply_node_drain,
            JOB_REGISTER: self._apply_job_register,
            JOB_DEREGISTER: self._apply_job_deregister,
            EVAL_UPDATE: self._apply_eval_update,
            EVAL_DELETE: self._apply_eval_delete,
            ALLOC_UPDATE: self._apply_alloc_update,
            ALLOC_CLIENT_UPDATE: self._apply_alloc_client_update,
            PERIODIC_LAUNCH: self._apply_periodic_launch,
            PERIODIC_LAUNCH_DELETE: self._apply_periodic_launch_delete,
            VAULT_ACCESSOR_REGISTER: self._apply_vault_accessor_register,
            VAULT_ACCESSOR_DEREGISTER: self._apply_vault_accessor_deregister,
        }

    def apply(self, index: int, msg_type: str, payload: dict) -> object:
        self.timetable.witness(index)
        handler = self._handlers.get(msg_type)
        if handler is None:
            raise ValueError(f"unknown log message type {msg_type!r}")
        start = time.monotonic()
        result = handler(index, payload)
        metrics.measure_since(("fsm", msg_type), start)
        self.last_applied_index = index
        return result

    def outcome(self, index: int) -> object:
        """Deterministic apply outcome for a recent log index (e.g. an
        enforce-index rejection). Every replica computes the same value
        from identical state, so reading it locally is safe."""
        return self._outcomes.get(index)

    # ------------------------------------------------------------ nodes

    def _apply_node_register(self, index: int, payload: dict):
        node: Node = payload["node"]
        self.state.upsert_node(index, node)
        # New capacity may unblock waiting evals.
        if self.blocked_evals is not None and node.status == consts.NODE_STATUS_READY:
            stored = self.state.node_by_id(node.id)
            self.blocked_evals.unblock(stored.computed_class, index)
        return None

    def _apply_node_deregister(self, index: int, payload: dict):
        self.state.delete_node(index, payload["node_id"])
        return None

    def _apply_node_status(self, index: int, payload: dict):
        node_id, status = payload["node_id"], payload["status"]
        self.state.update_node_status(index, node_id, status)
        if self.blocked_evals is not None and status == consts.NODE_STATUS_READY:
            node = self.state.node_by_id(node_id)
            if node is not None:
                self.blocked_evals.unblock(node.computed_class, index)
        return None

    def _apply_node_drain(self, index: int, payload: dict):
        self.state.update_node_drain(index, payload["node_id"], payload["drain"])
        return None

    # ------------------------------------------------------------ vault

    def _apply_vault_accessor_register(self, index: int, payload: dict):
        """fsm.go applyUpsertVaultAccessor."""
        self.state.upsert_vault_accessors(index, payload["accessors"])
        return None

    def _apply_vault_accessor_deregister(self, index: int, payload: dict):
        """fsm.go applyDeregisterVaultAccessor."""
        self.state.delete_vault_accessors(index, payload["accessors"])
        return None

    # ------------------------------------------------------------- jobs

    def _apply_job_register(self, index: int, payload: dict):
        job: Job = payload["job"]
        # Enforce-index gate (job_endpoint.go:60-79) is evaluated here,
        # inside the serialized apply path, so the check-and-commit is
        # atomic and identical on every replica — two concurrent
        # `run -check-index N` submissions commit at different log
        # positions and the second deterministically loses.
        if payload.get("enforce_index"):
            jmi = int(payload.get("job_modify_index") or 0)
            cur = self.state.job_by_id(job.id)
            err = None
            if jmi == 0 and cur is not None:
                err = "Enforcing job modify index 0: job already exists"
            elif jmi != 0 and cur is None:
                err = f"Enforcing job modify index {jmi}: job does not exist"
            elif jmi != 0 and cur.job_modify_index != jmi:
                err = (
                    f"Enforcing job modify index {jmi}: job exists "
                    f"with conflicting job modify index: {cur.job_modify_index}"
                )
            if err is not None:
                self._outcomes[index] = err
                while len(self._outcomes) > 1024:
                    self._outcomes.popitem(last=False)
                return err
        self.state.upsert_job(index, job)
        if self.periodic is not None and job.is_periodic():
            self.periodic.add(self.state.job_by_id(job.id))
        return None

    def _apply_job_deregister(self, index: int, payload: dict):
        job_id = payload["job_id"]
        self.state.delete_job(index, job_id)
        if self.periodic is not None:
            self.periodic.remove(job_id)
            self.state.delete_periodic_launch(index, job_id)
        if self.blocked_evals is not None:
            self.blocked_evals.untrack(job_id)
        return None

    # ------------------------------------------------------------ evals

    def _apply_eval_update(self, index: int, payload: dict):
        evals: List[Evaluation] = payload["evals"]
        self.state.upsert_evals(index, evals)
        if self.broker is None:
            return None
        for ev in evals:
            if ev.should_enqueue():
                self.broker.enqueue(ev, payload.get("token", ""))
            elif ev.should_block() and self.blocked_evals is not None:
                stored = self.state.eval_by_id(ev.id)
                self.blocked_evals.block(stored)
        return None

    def _apply_eval_delete(self, index: int, payload: dict):
        self.state.delete_evals(index, payload["eval_ids"], payload["alloc_ids"])
        return None

    # ----------------------------------------------------------- allocs

    def _apply_alloc_update(self, index: int, payload: dict):
        allocs: List[Allocation] = payload["allocs"]
        job = payload.get("job")
        for alloc in allocs:
            if alloc.job is None:
                if job is not None and alloc.job_id == job.id:
                    alloc.job = job
                else:
                    # A plan may carry OTHER jobs' allocs (preemption
                    # victims): re-denormalize from the stored record,
                    # never from the submitting plan's job — a victim
                    # stamped with the preemptor's job would lie about
                    # its own priority to every later scheduler pass.
                    stored = self.state.alloc_by_id(alloc.id)
                    if stored is not None:
                        alloc.job = stored.job
        t0 = time.monotonic()
        self.state.upsert_allocs(index, allocs)
        # Trace: the state-store write is the lifecycle's last
        # side-effecting stage; one span per eval whose allocs landed
        # in this apply (a plan's allocs share one eval). create=False:
        # this handler ALSO runs on followers and on raft-log replay,
        # where no broker opened the trace — only an active (leader,
        # live) lifecycle records here.
        for eval_id in {a.eval_id for a in allocs if a.eval_id}:
            trace.record_span(eval_id, trace.STAGE_ALLOC_UPSERT, t0,
                              ann={"index": index}, create=False)
        return None

    def _apply_alloc_client_update(self, index: int, payload: dict):
        allocs: List[Allocation] = payload["allocs"]
        self.state.update_allocs_from_client(index, allocs)
        # A terminal client status frees capacity: unblock by the node's
        # computed class (fsm.go applyAllocClientUpdate -> Unblock).
        if self.blocked_evals is not None:
            for alloc in allocs:
                if alloc.client_status in (
                    consts.ALLOC_CLIENT_COMPLETE,
                    consts.ALLOC_CLIENT_FAILED,
                    # Lost frees capacity too: a client re-syncing after
                    # its node was downed (heartbeat TTL) reports its
                    # allocs lost, and evals blocked on that class must
                    # re-trigger — the node-down -> alloc-lost ->
                    # blocked-eval chain ends here.
                    consts.ALLOC_CLIENT_LOST,
                ):
                    # Client sync updates are SPARSE (id + status +
                    # task_states, client/agent.py _flush_dirty): the
                    # node comes from the stored record, which the
                    # upsert above just refreshed. Looking at the wire
                    # alloc's empty node_id here silently skipped every
                    # unblock, wedging capacity-blocked evals forever.
                    node_id = alloc.node_id
                    if not node_id:
                        stored = self.state.alloc_by_id(alloc.id)
                        node_id = stored.node_id if stored else ""
                    node = self.state.node_by_id(node_id)
                    if node is not None:
                        self.blocked_evals.unblock(node.computed_class, index)
        return None

    # --------------------------------------------------------- periodic

    def _apply_periodic_launch(self, index: int, payload: dict):
        self.state.upsert_periodic_launch(
            index, PeriodicLaunch(id=payload["job_id"], launch=payload["launch"])
        )
        return None

    def _apply_periodic_launch_delete(self, index: int, payload: dict):
        self.state.delete_periodic_launch(index, payload["job_id"])
        return None

    # --------------------------------------------------------- snapshot

    def snapshot_data(self) -> dict:
        return self.state.persist()

    def restore(self, data: dict) -> None:
        self.state = StateStore.restore(data)
        self.last_applied_index = self.state.latest_index()


class DevLog:
    """Single-node, in-memory replicated-log stand-in: applies entries
    synchronously to the local FSM (the reference's dev mode uses
    raft.InmemStore with a single peer, server.go:657-663). The raft
    implementation (stage 5) replaces this behind the same interface."""

    def __init__(self, fsm: FSM):
        self.fsm = fsm
        self._lock = threading.Lock()
        self._index = 0

    def apply(self, msg_type: str, payload: dict) -> int:
        with self._lock:
            self._index += 1
            index = self._index
        self.fsm.apply(index, msg_type, payload)
        return index

    def last_index(self) -> int:
        with self._lock:
            return self._index

    def barrier(self) -> int:
        return self.last_index()
