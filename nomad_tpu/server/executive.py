"""Scheduler executive: the batched event-loop replacement for the
thread-per-eval dense worker model.

BENCH_r13 (the contention observatory) measured the old model's cost
directly: every dense dispatch parked 63 of 64 eval threads on batcher
events (`convoy_width` 63), and the `device.dispatch` p99−p50 gap was
fully covered by `runq.batch_park` — ready results waiting for the GIL
to hand parked workers a slot. The worker-per-eval shape is a Go-ism
inherited from the reference's `worker.go`; goroutines are free, OS
threads under one GIL are not.

The executive inverts the identity: an evaluation is a **batch row,
not a thread**.

- One drain-owner thread (`_run`) is seeded by worker handoff exactly
  like the dispatch pipeline, then tops the cohort up with bulk
  `eval_dequeue_many` drains — the broker's ready queue is emptied in
  one critical section per pass, not one dequeue per thread.
- The whole cohort reconciles host-side **as arrays**
  (scheduler/util.py `cohort_reconcile`): one pass over a stacked
  existing-allocs table classifies every member; evals whose diff has
  semantics beyond pure placement (stops, updates, migrations and
  their budget claims, preemption, batch-job history, sticky disks)
  route to the untouched per-eval scheduler on a SMALL legacy lane —
  those code paths stay the single source of truth.
- Fast members build their matrices/asks fanned over a SMALL
  (`executive_threads`) pool — numpy releases the GIL, so a few
  threads buy real multicore parallelism without the 64-thread
  park/wake storm — and the complete batch goes to the device through
  the batcher's no-park cohort dispatch
  (`PlacementBatcher.place_cohort`): one inline `_run_batch` on the
  loop thread, zero events, zero parked threads.
- Results fan back out through per-eval plan-submit + ack on a small
  (`executive_threads`) pool; nothing ever parks 64 threads on one
  event. Plan conflicts fall back to the per-eval scheduler on the
  refreshed snapshot (the committed allocs re-diff as existing state,
  so only the rejected remainder replans).

The legacy `Worker` pool stays — behind `scheduler_executive = false`
for A/B, and always as the host-path / system-scheduler / fallback
engine. Broker backpressure (`saturated()`), the storm-quiesce
`set_pause()`/`parked()` contract, the chaos sites
(`dispatch.launch` / `dispatch.submit` / `dispatch.finish` /
`admission.slow_consumer`), deadline enforcement, breaker routing and
the trace record points all move with the drain.
"""

from __future__ import annotations

import logging
import os
import random
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from .. import profile, trace
from ..chaos import chaos
from ..profile import ProfiledCondition, ProfiledLock
from ..scheduler import new_scheduler
from ..structs import AllocMetric, Evaluation, consts
from ..utils import metrics
from ..utils.backoff import poll_until
from ..utils.pool import WorkPool
from .worker import (
    EvalSession,
    factory_kernel,
    host_factory,
    is_dense_factory,
)

DEQUEUE_TOPUP_SLICE = 0.002  # cond-wait granularity while accumulating
SEED_WAIT_SLICE = 0.25  # cond-wait granularity while idle
WAIT_INDEX_TIMEOUT = 5.0

# ntalint lock-discipline manifest (analysis/locks.py): the drain owns
# the executive's clock — everything reachable from it runs on the
# event-loop thread between cohorts and must never block (bounded
# cond-waits on the executive's own lock are the sanctioned scheduling
# primitive). Cohort PROCESSING deliberately blocks (snapshotting,
# device sync, plan submits) — that work is the loop's payload, not its
# clock, and it is not reachable from this entrypoint.
NTA_DISPATCHER_ENTRYPOINTS = ("SchedulerExecutive._drain",)

# ntalint record-path manifest (analysis/robustness.py): the drain's
# stats stamp runs on the event-loop thread between bulk broker drains;
# its closure must never park (leaf `with lock:` around constant work
# only) and never grow a container.
NTA_RECORD_PATH = ("SchedulerExecutive._note_drain",)


class _Entry:
    __slots__ = ("eval", "token", "enqueued_at")

    def __init__(self, ev: Evaluation, token: str):
        self.eval = ev
        self.token = token
        self.enqueued_at = time.monotonic()


class _Row:
    """One fast-path cohort member's in-flight state: the batch row."""

    __slots__ = ("entry", "member", "plan", "matrix", "tg_indices",
                 "bulk", "config", "asks", "key", "rng", "elig",
                 "failed", "queued", "choices", "scores", "ctx", "stack",
                 "t_start")

    def __init__(self, entry, member):
        self.entry = entry
        self.member = member
        self.failed: Dict[str, AllocMetric] = {}
        self.queued = dict(member.queued)
        self.ctx = None
        self.stack = None
        self.t_start = time.monotonic()


class ExecutiveSession(EvalSession):
    """Per-eval Planner for executive-processed evals. Inherits the
    whole Planner contract (pause-nack framing, eval updates, reblock,
    pre_resolve wiring) from server/worker.py EvalSession — the
    executive satisfies the `worker` duck type (`.server`,
    `._wait_for_index`) — and adds the chaos site the pipeline's
    session fired, so seeded leader-flap-mid-submit schedules exercise
    the executive path identically."""

    def submit_plan(self, plan):
        if chaos.enabled:
            # 'error' = the submit RPC fails (leader flap mid-cohort);
            # the eval nacks and redelivers. 'delay' = slow plan queue.
            chaos.fire("dispatch.submit", eval_id=self.eval.id)
        return super().submit_plan(plan)


class SchedulerExecutive:
    def __init__(self, server):
        self.server = server
        cfg = server.config
        self.logger = logging.getLogger("nomad_tpu.executive")
        self.max_batch = max(1, cfg.eval_batch_size)
        self.threads = max(1, cfg.executive_threads)
        self.window = cfg.dispatch_window
        self.idle_grace = cfg.dispatch_idle_grace

        self.types: List[str] = [
            t for t in cfg.enabled_schedulers
            if is_dense_factory(cfg.factory_for(t))
        ]
        self.enabled = bool(
            cfg.scheduler_executive and self.types and cfg.eval_batch_size > 1
        )

        # Profiled (nomad_tpu/profile): the handoff/accumulator lock.
        self._lock = ProfiledLock("server.executive")
        self._cond = ProfiledCondition(self._lock, "server.executive")
        self._pending: List[_Entry] = []  # guarded-by: _lock
        self._notified_at = 0.0  # guarded-by: _lock
        self._drain_waiting = False  # guarded-by: _lock
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # Storm-quiesce contract (worker.py set_pause/parked): benches
        # and soaks park the drain to fill the broker, then release.
        self._paused = False  # guarded-by: _pause_lock
        self._pause_lock = threading.Lock()
        self._pause_cond = threading.Condition(self._pause_lock)
        self._parked = threading.Event()
        # Lock-free mirror of _paused for the drain's seed-wait bail
        # (an Event read takes no lock, so the seed wait never nests
        # _pause_lock inside the accumulator condition).
        self._pause_flag = threading.Event()
        # Host-side fan-out WITHIN a cohort: matrix-build help is not
        # needed (numpy on the loop thread), but plan submits wait on
        # the plan queue and a handful of concurrent submits keep the
        # pipelined applier fed without re-creating the convoy.
        self._pool = WorkPool(self.threads, name="executive")

        # ---- stats ----
        self.evals_in = 0  # guarded-by: _lock (handoffs + bulk drains)
        self.cohorts = 0  # guarded-by: _lock (cohorts processed)
        self.cohort_evals = 0  # guarded-by: _lock (sum cohort sizes)
        self.largest_cohort = 0  # guarded-by: _lock
        self.fast_evals = 0  # guarded-by: _lock (array-path end to end)
        self.legacy_evals = 0  # guarded-by: _lock (per-eval scheduler)
        self.legacy_reasons: Dict[str, int] = {}  # guarded-by: _lock
        self.routed_host = 0  # guarded-by: _lock (sub-min / breaker)
        self.host_fallbacks = 0  # guarded-by: _lock (device fault)
        self.plan_conflicts = 0  # guarded-by: _lock (refresh-index'd)
        self.expired_dropped = 0  # guarded-by: _lock
        self.acked = 0  # guarded-by: _lock
        self.nacked = 0  # guarded-by: _lock
        self.finish_dropped = 0  # guarded-by: _lock (chaos dispatch.finish)
        self.drained = 0  # guarded-by: _lock (leadership-loss requeues)
        self.t_drain = 0.0  # guarded-by: _lock (eval wait in accumulator)
        self.t_build = 0.0  # guarded-by: _lock (matrix/ask builds)
        self.t_dispatch = 0.0  # guarded-by: _lock (cohort device calls)
        self.t_finalize = 0.0  # guarded-by: _lock (submit/status/ack)

    # ------------------------------------------------------- lifecycle

    def start(self) -> None:
        if not self.enabled or self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="scheduler-executive", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        with self._cond:
            self._cond.notify_all()
        self.set_pause(False)
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        self.drain()

    def drain(self) -> int:
        """Leadership loss (or shutdown): hand every accumulated eval's
        lease back to the broker (same contract as the dispatch
        pipeline's drain — on a real flap the nack fails cleanly and
        the new leader re-seeds from raft state)."""
        with self._cond:
            pending, self._pending = self._pending, []
            self._cond.notify_all()
        for entry in pending:
            self._finish(entry, acked=False)
        if pending:
            with self._lock:
                self.drained += len(pending)
            self.logger.info(
                "drained %d accumulated evals back to the broker",
                len(pending))
        return len(pending)

    # ---------------------------------------------------- pause/parked

    def set_pause(self, paused: bool) -> None:
        """The worker-pool quiesce contract (worker.py): storms park
        the drain so the broker fills, then release it into a deep
        ready queue — the regime the cohort drain exists for."""
        with self._pause_lock:
            self._paused = paused
            if paused:
                self._pause_flag.set()
            else:
                self._pause_flag.clear()
            self._pause_cond.notify_all()
        with self._cond:
            self._cond.notify_all()

    def parked(self) -> bool:
        """True while the run loop waits inside the paused state — the
        drain is provably not mid-cohort and not holding broker
        leases (worker.py parked()). A disabled/never-started
        executive has no drain to park: trivially True, so quiesce
        helpers can pause workers+executive uniformly in both A/B
        arms."""
        if not self.enabled or self._thread is None:
            return True
        return self._parked.is_set()

    def _check_paused(self) -> None:
        with self._pause_lock:
            if not (self._paused and not self._stop.is_set()):
                return
            self._parked.set()
            try:
                while self._paused and not self._stop.is_set():
                    self._pause_cond.wait(0.5)
            finally:
                self._parked.clear()

    # ------------------------------------------------------ admission

    def submit(self, ev: Evaluation, token: str) -> None:
        """Worker handoff: a worker that dequeued a dense-factory eval
        seeds the executive's cohort instead of processing it."""
        entry = _Entry(ev, token)
        with self._cond:
            self._pending.append(entry)
            self.evals_in += 1
            if self._drain_waiting and not self._notified_at:
                self._notified_at = time.monotonic()
            self._cond.notify_all()

    def pending_count(self) -> int:
        with self._lock:
            return len(self._pending)

    def saturated(self) -> bool:
        """Intake backpressure for the worker handoff: evals held here
        are invisible to the bounded broker queues, so an unbounded
        accumulation would reopen the intake the depth caps close."""
        with self._lock:
            return len(self._pending) >= 2 * self.max_batch

    # ------------------------------------------------------ event loop

    def _run(self) -> None:
        outstanding: List[object] = []
        while not self._stop.is_set():
            self._check_paused()
            # Prune settled finalize tails: the list is ONLY the
            # drain-window signal (work in flight -> accumulate the
            # full window to amortize it; idle -> the short grace).
            # The loop NEVER blocks on these futures — the drain owns
            # the executive's clock, and a single finalize wedged on a
            # leader-flap plan timeout must not stall cohort cuts
            # while redelivered evals burn their 2s nack cycles
            # straight into the delivery limit (the dead-letter storm
            # the chaos soak reproduced). Unbounded pile-up is closed
            # elsewhere: worker handoff naps on saturated(), and the
            # broker's bounded queues own the rest.
            outstanding = [f for f in outstanding if not f.done()]
            batch = []
            try:
                batch = self._drain(window=(
                    self.window if outstanding else self.idle_grace))
                if not batch:
                    continue
                outstanding.extend(self._process_cohort(batch))
            except Exception:
                # The drain thread is a singleton and the worker
                # handoff backpressures on saturated(): an escaped
                # exception here must never kill the loop, or every
                # worker eventually naps forever against a dead
                # executive (the pipeline guards its launch path for
                # the same reason). Nack whatever we held — the nack
                # timer reclaims anything mid-flight — and keep
                # draining; the pause slows a tight error loop.
                self.logger.exception(
                    "cohort processing failed; nacking %d evals and "
                    "continuing", len(batch))
                for entry in batch:
                    self._finish(entry, acked=False)
                self._stop.wait(0.05)

    def _drain(self, window: float) -> List[_Entry]:
        """Accumulate the next cohort: bounded seed wait, then bulk
        broker top-ups. This is the executive's never-blocking clock
        (NTA_DISPATCHER_ENTRYPOINTS)."""
        with self._cond:
            self._drain_waiting = True
            try:
                while (not self._pending and not self._stop.is_set()
                       and not self._pause_flag.is_set()):
                    self._cond.wait(SEED_WAIT_SLICE)
            finally:
                self._drain_waiting = False
            if not self._pending:
                self._notified_at = 0.0
                return []
            if self._notified_at:
                # Seed-wake run-queue delay: notify-while-parked ->
                # this thread actually running (the executive analog of
                # the pipeline's broker_drain stamp).
                profile.record_runq(
                    "broker_drain",
                    (time.monotonic() - self._notified_at) * 1000.0)
                self._notified_at = 0.0
            profile.event("accumulate_open", "executive",
                          a=len(self._pending))
        start = time.monotonic()
        # Empty-drain backoff: on a follower every eval_dequeue_many is
        # an RPC to the leader — once a drain comes back empty, don't
        # re-issue it every 2ms slice for the rest of the window. A
        # handoff notify (new lease in hand) re-arms immediately; a
        # plain timeout re-arms at a 5x coarser cadence.
        next_drain = start
        # Dry-broker early cut (the BENCH_r14 config-5 churn fix): once
        # a bulk drain comes back EMPTY with a cohort in hand, holding
        # that cohort for the rest of the window buys nothing — there
        # is no work left to pack. Under churn the eval graph is a
        # CHAIN (drain eval -> migration follow-up -> follow-up), so a
        # full-window hold per hop compounds into the measured x0.71;
        # the pipeline's dispatch_idle_grace is the same tradeoff,
        # applied here mid-window. A handoff notify (fresh lease in
        # hand) re-opens the window — in-flight work beats the grace.
        empty_since = 0.0
        while not self._stop.is_set():
            now = time.monotonic()
            with self._lock:
                room = self.max_batch - len(self._pending)
            if room > 0 and now >= next_drain:
                # The bulk drain: everything ready across the broker in
                # one visit — the cohort packs toward max_batch rows.
                got = self.server.eval_dequeue_many(self.types, room)
                if got:
                    now = time.monotonic()
                    empty_since = 0.0
                    with self._cond:
                        for ev, token in got:
                            entry = _Entry(ev, token)
                            entry.enqueued_at = now
                            self._pending.append(entry)
                            self.evals_in += 1
                else:
                    if not empty_since:
                        empty_since = now
                    next_drain = now + 5 * DEQUEUE_TOPUP_SLICE
            with self._cond:
                if len(self._pending) >= self.max_batch:
                    break
                now = time.monotonic()
                if now - start >= window:
                    break
                if (self._pending and empty_since
                        and now - empty_since >= self.idle_grace):
                    break
                if self._cond.wait(DEQUEUE_TOPUP_SLICE):
                    # Notified: a worker handed a fresh lease over —
                    # the broker plainly has work again.
                    next_drain = 0.0
                    empty_since = 0.0
        with self._cond:
            batch = self._pending[: self.max_batch]
            del self._pending[: len(batch)]
        if batch:
            self._note_drain(batch)
        return batch

    def _note_drain(self, batch: List[_Entry]) -> None:
        """Cohort-cut stats stamp (NTA_RECORD_PATH: leaf lock, constant
        work, no container growth)."""
        now = time.monotonic()
        with self._lock:
            self.cohorts += 1
            cohorts = self.cohorts
            self.cohort_evals += len(batch)
            if len(batch) > self.largest_cohort:
                self.largest_cohort = len(batch)
            for entry in batch:
                self.t_drain += now - entry.enqueued_at
        profile.event("accumulate_close", "executive",
                      a=len(batch), b=cohorts)

    # -------------------------------------------------------- cohorts

    def _process_cohort(self, batch: List[_Entry]) -> List[object]:
        """Run one cohort end to end on the loop thread; returns the
        finalize futures (submit/status/ack tails) still in flight."""
        t_launch = time.monotonic()
        cfg = self.server.config
        if chaos.enabled:
            try:
                # 'error' = the cohort prologue dies (snapshot/catch-up
                # failure): every eval nacks and redelivers.
                chaos.fire("dispatch.launch", batch=len(batch))
            except Exception:
                self.logger.exception(
                    "cohort launch chaos; nacking %d evals", len(batch))
                for entry in batch:
                    self._finish(entry, acked=False)
                return []
        batch = self._drop_expired(batch, t_launch)
        if not batch:
            return []
        for entry in batch:
            trace.record_span(
                entry.eval.id, trace.STAGE_DISPATCH_ACCUMULATE,
                entry.enqueued_at, t_launch,
                ann={"batch": len(batch), "executive": True},
                trace_id=entry.eval.trace_id)
        # One MVCC snapshot for the whole cohort (same invariant as the
        # worker drain and the pipeline launch: shared base token, one
        # device upload; optimistic concurrency keeps it safe).
        max_index = max(e.eval.modify_index for e in batch)
        if not self._wait_for_index(max_index, WAIT_INDEX_TIMEOUT):
            for entry in batch:
                self._finish(entry, acked=False)
            return []
        snapshot = self.server.fsm.state.snapshot()

        route_host = len(batch) < cfg.dense_min_batch
        if not route_host:
            from ..admission import get_breaker

            if get_breaker().should_route_host():
                # Open breaker inside its cool-down: the whole cohort
                # takes the host factories up front (the non-consuming
                # hint, exactly like the pipeline's launch prologue).
                route_host = True
                metrics.incr_counter(
                    ("executive", "breaker_route_host"), len(batch))
        if route_host:
            with self._lock:
                self.routed_host += len(batch)
            metrics.incr_counter(("executive", "route_host"), len(batch))
            return [self._pool.submit(
                self._process_legacy, entry, snapshot,
                host_factory(cfg.factory_for(entry.eval.type)))
                for entry in batch]

        # Cohort reconcile AS ARRAYS: one stacked-table pass classifies
        # every member (scheduler/util.py cohort_reconcile).
        from ..migrate import preemption_eligible
        from ..scheduler.util import cohort_reconcile

        members = cohort_reconcile(snapshot, [e.eval for e in batch])
        futs: List[object] = []
        fast: List[_Row] = []
        for entry, m in zip(batch, members):
            if m.fast and preemption_eligible(m.eval.priority):
                # The eviction leg belongs to the per-eval dense
                # scheduler (ops/preempt.py); rare by construction
                # (red pressure + outranking priority only).
                m.fast = False
                m.reason = "preemption-eligible"
            if not m.fast:
                self._note_legacy(m.reason)
                futs.append(self._pool.submit(
                    self._process_legacy, entry, snapshot, None))
            else:
                fast.append(_Row(entry, m))
        if not fast:
            return futs

        # ---- build: matrices + asks for every fast row, fanned over
        # the SMALL executive pool. numpy releases the GIL, so a few
        # threads buy real multicore parallelism for the array builds
        # without the 64-thread park/wake storm — the cohort cut (and
        # the single dispatch below) stay on this loop thread.
        t0 = time.monotonic()
        build: List[tuple] = []
        for row in fast:
            if not row.member.place:
                # Pure no-op (all slots already placed): complete + ack.
                futs.append(self._pool.submit(self._finalize_noop, row))
                continue
            build.append((row, self._pool.submit(
                self._build_row, row, snapshot)))
        rows: List[_Row] = []
        for row, f in build:
            dead = False
            # Bounded with a shutdown re-check (ntalint unbounded-wait).
            while not f.wait(1.0):
                if self._stop.is_set():
                    dead = True
                    break
            try:
                if not dead:
                    f.result(0)
            except Exception:
                self.logger.exception(
                    "cohort row build for %s failed; nacking",
                    row.entry.eval.id)
                dead = True
            if dead:
                self._finish(row.entry, acked=False)
            else:
                rows.append(row)
        with self._lock:
            self.t_build += time.monotonic() - t0
        if not rows:
            return futs

        # ---- dispatch: ONE no-park device call for the whole cohort.
        from ..admission import get_breaker

        breaker = get_breaker()
        if not breaker.acquire():
            metrics.incr_counter(
                ("executive", "breaker_rejected"), len(rows))
            futs.extend(self._route_rows_host(rows, snapshot))
            return futs
        from ..scheduler.batcher import get_batcher

        t1 = time.monotonic()
        try:
            results = get_batcher().place_cohort([
                (row.matrix, row.asks, row.key, row.config,
                 (row.entry.eval.id, row.entry.eval.trace_id))
                for row in rows])
        except Exception:
            # Device fault: the host iterators have identical placement
            # semantics (parity-tested) — the whole fast set falls back
            # and the breaker counts one failure, exactly like the
            # per-eval dense path's except arm.
            breaker.record_failure()
            self.logger.warning(
                "cohort device dispatch failed; falling back to the "
                "host path for %d evals", len(rows), exc_info=True)
            with self._lock:
                self.host_fallbacks += len(rows)
            metrics.incr_counter(
                ("executive", "host_fallback"), len(rows))
            futs.extend(self._route_rows_host(rows, snapshot))
            return futs
        dt = time.monotonic() - t1
        breaker.record_success(dt * 1000.0)
        with self._lock:
            self.t_dispatch += dt
        for row, (choices, scores) in zip(rows, results):
            row.choices = np.asarray(choices)
            row.scores = np.asarray(scores)
            trace.record_span(
                row.entry.eval.id, trace.STAGE_DEVICE_DISPATCH, t1,
                ann={"cohort": len(rows)},
                trace_id=row.entry.eval.trace_id)

        # ---- materialize + finalize, fanned per row on the pool:
        # exact ports + Allocation literals, then plan submit + status
        # + ack — each row waits on its OWN plan's commit (the plan
        # queue's natural shape, never one shared event). The loop
        # thread goes straight back to accumulating the next cohort.
        for row in rows:
            futs.append(self._pool.submit(
                self._finalize_fast, row, snapshot))
        return futs

    def _route_rows_host(self, rows: List[_Row], snapshot):
        cfg = self.server.config
        return [self._pool.submit(
            self._process_legacy, row.entry, snapshot,
            host_factory(cfg.factory_for(row.entry.eval.type)))
            for row in rows]

    def _note_legacy(self, reason: str) -> None:
        with self._lock:
            self.legacy_evals += 1
            self.legacy_reasons[reason] = (
                self.legacy_reasons.get(reason, 0) + 1)

    # ------------------------------------------------------ fast path

    def _build_row(self, row: _Row, snapshot) -> None:
        from ..models.matrix import ClusterMatrix
        from ..ops.binpack import host_prng_key, make_asks
        from ..scheduler.context import EvalEligibility
        from ..scheduler.tpu import build_placement_config

        entry, m = row.entry, row.member
        ev, job = m.eval, m.job
        _t0 = time.monotonic()
        row.plan = ev.make_plan(job)
        row.matrix = ClusterMatrix(snapshot, job, row.plan)
        _t_base = time.monotonic()
        row.tg_indices = {tg.name: i
                          for i, tg in enumerate(job.task_groups)}
        row.bulk = list(m.place)
        placements = [row.tg_indices[t.task_group.name] for t in row.bulk]
        ask_arrays = row.matrix.build_asks(placements)
        row.asks = make_asks(*ask_arrays)
        trace.record_span(ev.id, trace.STAGE_MATRIX_BUILD, _t0,
                          ann={"placements": len(row.bulk),
                               "executive": True},
                          trace_id=ev.trace_id)
        kind = getattr(row.matrix, "build_kind", None)
        if kind is not None:
            trace.record_span(
                ev.id, trace.STAGE_MATRIX_UPDATE, _t0, _t_base,
                ann={"kind": kind, "rows": row.matrix.delta_rows},
                trace_id=ev.trace_id)
        # Compression-plane marker, mirroring scheduler/tpu.py: the
        # executive's evals carry the same C/N/ratio annotation.
        cidx = getattr(row.matrix, "class_index", None)
        if cidx is not None:
            trace.record_span(
                ev.id, trace.STAGE_MATRIX_COMPRESS, _t_base, _t_base,
                ann=cidx.stats(), trace_id=ev.trace_id)
        # The factory's kernel pin ("service-convex-tpu" -> convex)
        # rides into the config exactly as BatchedTPUScheduler.kernel
        # would — the fast path must run the SAME program the per-eval
        # scheduler (and this eval's own conflict re-run) runs.
        row.config = build_placement_config(
            job.type == consts.JOB_TYPE_BATCH,
            self.server.config.dense_pre_resolve,
            factory_kernel(self.server.config.factory_for(ev.type)),
            placements, ask_arrays)
        # Independent PRNG per eval (worker.py: correlated tie-break
        # streams spike plan conflicts).
        row.rng = random.Random(int.from_bytes(os.urandom(8), "little"))
        row.key = host_prng_key(row.rng.getrandbits(31))
        row.elig = EvalEligibility()
        row.elig.set_job(job)

    def _materialize(self, row: _Row, snapshot) -> None:
        """Choices -> exact per-task network offers -> Allocation
        literals on the plan. Mirrors scheduler/tpu.py's committed
        loop: failed TGs coalesce, the dense port-count approximation's
        misses fall back to the exact host selector for that one
        placement, and class eligibility feeds the blocked-eval
        machinery from the feasibility mask."""
        from ..scheduler.tpu import (
            _build_allocation,
            _offer_networks,
            note_quality,
        )

        matrix = row.matrix
        net_indexes: Dict[str, object] = {}
        committed = []
        for j, missing in enumerate(row.bulk):
            name = missing.task_group.name
            if name in row.failed:
                row.failed[name].coalesced_failures += 1
                continue
            choice = int(row.choices[j])
            node = (matrix.nodes[choice]
                    if 0 <= choice < matrix.n_real else None)
            m = AllocMetric()
            m.nodes_evaluated = matrix.n_real
            m.nodes_available = matrix.nodes_by_dc
            if node is None:
                self._record_failure(row, missing, m)
                continue
            m.score_node(node, "binpack", float(row.scores[j]))
            task_resources = _offer_networks(
                row.rng, missing, node, net_indexes, matrix)
            if task_resources is None:
                # Dense port approximation missed a real collision:
                # exact host selector for this one placement.
                if not self._stack_place(row, missing, snapshot, m):
                    self._record_failure(row, missing, m)
                continue
            row.plan.append_alloc(_build_allocation(
                _SchedStub(row.member.eval, row.member.job), missing,
                node, task_resources, m))
            committed.append((j, choice))
        note_quality(self.logger, row.member.job, row.config.kernel,
                     matrix, np.asarray(row.asks.resources), committed)

    def _stack_place(self, row: _Row, missing, snapshot, m) -> bool:
        """Exact host-path selection for one placement (the per-eval
        dense scheduler's port-collision fallback, generic.py
        _compute_placements shape)."""
        from ..scheduler.context import EvalContext
        from ..scheduler.stack import GenericStack
        from ..scheduler.util import ready_nodes_in_dcs
        from ..structs import Allocation, Resources
        from ..utils.ids import generate_uuid

        job = row.member.job
        if row.stack is None:
            row.ctx = EvalContext(snapshot, row.plan, self.logger,
                                  rng=row.rng)
            row.stack = GenericStack(
                job.type == consts.JOB_TYPE_BATCH, row.ctx)
            row.stack.set_job(job)
            nodes, _by_dc = ready_nodes_in_dcs(snapshot, job.datacenters)
            row.stack.set_nodes(nodes)
        option, _ = row.stack.select(missing.task_group)
        if option is None:
            return False
        alloc = Allocation(
            id=generate_uuid(),
            eval_id=row.member.eval.id,
            name=missing.name,
            job_id=job.id,
            task_group=missing.task_group.name,
            metrics=m,
            node_id=option.node.id,
            task_resources=option.task_resources,
            desired_status=consts.ALLOC_DESIRED_RUN,
            client_status=consts.ALLOC_CLIENT_PENDING,
            shared_resources=Resources(
                disk_mb=missing.task_group.ephemeral_disk.size_mb),
        )
        if missing.alloc is not None and missing.alloc.id:
            alloc.previous_allocation = missing.alloc.id
        row.plan.append_alloc(alloc)
        return True

    def _record_failure(self, row: _Row, missing, m) -> None:
        name = missing.task_group.name
        gi = row.tg_indices[name]
        matrix = row.matrix
        infeasible = int(
            matrix.n_real - matrix.feasible[: matrix.n_real, gi].sum())
        m.nodes_filtered = infeasible
        m.nodes_exhausted = matrix.n_real - infeasible
        row.failed[name] = m
        for i, node in enumerate(matrix.nodes):
            if node.computed_class:
                row.elig.set_task_group_eligibility(
                    bool(matrix.feasible[i, gi]), name,
                    node.computed_class)

    def _finalize_fast(self, row: _Row, snapshot) -> None:
        """Materialize the row's choices into its plan, submit it,
        persist the terminal status, release the broker lease. Runs on
        the executive pool; a plan conflict (RefreshIndex) hands the
        eval to the per-eval scheduler on the refreshed snapshot —
        committed allocs re-diff as existing state there, so only the
        rejected remainder replans."""
        from ..scheduler.generic import BLOCKED_EVAL_FAILED_PLACEMENTS
        from ..scheduler.util import adjust_queued_allocations, set_status

        entry = row.entry
        ev = entry.eval
        session = ExecutiveSession(self, ev, entry.token)
        blocked = None
        try:
            if chaos.enabled:
                # 'delay' = a stalled consumer; 'error' = it dies and
                # the eval nacks/redelivers (overload-soak sites).
                chaos.fire("admission.slow_consumer", eval_id=ev.id)
            self._materialize(row, snapshot)
            if row.failed:
                blocked = ev.create_blocked_eval(
                    row.elig.get_classes(), row.elig.has_escaped())
                blocked.status_description = (
                    BLOCKED_EVAL_FAILED_PLACEMENTS)
                session.create_eval(blocked)
            if row.plan.is_no_op():
                set_status(self.logger, session, ev, None, blocked,
                           row.failed or None,
                           consts.EVAL_STATUS_COMPLETE, "", row.queued)
                self._note_process(row, failed=False)
                self._finish(entry, acked=True)
                return
            result, new_state = session.submit_plan(row.plan)
            adjust_queued_allocations(self.logger, result, row.queued)
            if new_state is not None:
                # Partial commit: per-eval scheduler on the refreshed
                # snapshot owns the remainder (and the eval's status).
                with self._lock:
                    self.plan_conflicts += 1
                metrics.incr_counter(("executive", "plan_conflict"))
                self._note_process(row, failed=False, conflicted=True)
                self._process_legacy(entry, new_state, None,
                                     fire_chaos=False)
                return
            full_commit, expected, actual = result.full_commit(row.plan)
            if not full_commit:
                raise RuntimeError(
                    f"missing state refresh after partial commit "
                    f"({actual}/{expected} placed)")
            set_status(self.logger, session, ev, None, blocked,
                       row.failed or None, consts.EVAL_STATUS_COMPLETE,
                       "", row.queued)
        except Exception:
            self.logger.exception("executive eval %s failed", ev.id)
            self._note_process(row, failed=True)
            self._finish(entry, acked=False)
            return
        self._note_process(row, failed=False)
        self._finish(entry, acked=True)

    def _note_process(self, row: _Row, failed: bool,
                      conflicted: bool = False) -> None:
        now = time.monotonic()
        with self._lock:
            if not failed and not conflicted:
                self.fast_evals += 1
            self.t_finalize += now - row.t_start
        trace.record_span(
            row.entry.eval.id, trace.STAGE_SCHED_PROCESS, row.t_start,
            now,
            ann={"path": "executive", "failed": failed,
                 "conflicted": conflicted},
            trace_id=row.entry.eval.trace_id)

    def _finalize_noop(self, row: _Row) -> None:
        """A fast member whose required slots are all placed already:
        complete + ack without touching the device."""
        from ..scheduler.util import set_status

        entry = row.entry
        session = ExecutiveSession(self, entry.eval, entry.token)
        try:
            set_status(self.logger, session, entry.eval, None, None,
                       None, consts.EVAL_STATUS_COMPLETE, "", row.queued)
        except Exception:
            self.logger.exception(
                "executive no-op status for %s failed", entry.eval.id)
            self._note_process(row, failed=True)
            self._finish(entry, acked=False)
            return
        self._note_process(row, failed=False)
        self._finish(entry, acked=True)

    # ----------------------------------------------------- legacy lane

    def _process_legacy(self, entry: _Entry, snapshot,
                        factory: Optional[str],
                        fire_chaos: bool = True) -> None:
        """The per-eval scheduler, unchanged — the executive's lane for
        everything its array path does not own (stops, updates,
        migrations and their budget claims, preemption, system jobs,
        conflicts, host routing, device-fault fallback). The conflict
        re-run passes fire_chaos=False: its eval already consumed an
        admission.slow_consumer firing in _finalize_fast, and a
        count-bounded seeded spec must hit DISTINCT evals."""
        ev, token = entry.eval, entry.token
        start = time.monotonic()
        try:
            if chaos.enabled and fire_chaos:
                chaos.fire("admission.slow_consumer", eval_id=ev.id)
            if snapshot is None:
                if not self._wait_for_index(ev.modify_index,
                                            WAIT_INDEX_TIMEOUT):
                    self._finish(entry, acked=False)
                    return
                snapshot = self.server.fsm.state.snapshot()
            if factory is None:
                factory = self.server.config.factory_for(ev.type)
            session = ExecutiveSession(self, ev, token)
            rng = random.Random(int.from_bytes(os.urandom(8), "little"))
            sched = new_scheduler(factory, self.logger, snapshot,
                                  session, rng=rng)
            sched.process_eval(ev)
        except Exception:
            self.logger.exception("executive legacy eval %s failed",
                                  ev.id)
            trace.record_span(ev.id, trace.STAGE_SCHED_PROCESS, start,
                              ann={"path": "executive-legacy",
                                   "failed": True},
                              trace_id=ev.trace_id)
            self._finish(entry, acked=False)
            return
        trace.record_span(ev.id, trace.STAGE_SCHED_PROCESS, start,
                          ann={"path": "executive-legacy"},
                          trace_id=ev.trace_id)
        self._finish(entry, acked=True)

    # ------------------------------------------------------- plumbing

    def _drop_expired(self, batch: List[_Entry],
                      t_launch: float) -> List[_Entry]:
        """Deadline enforcement before any matrix build: terminalize
        expired entries with the structured reason + ack (the broker
        enforces the same bound at dequeue; this covers accumulation
        time — dispatch/pipeline.py semantics)."""
        now = time.time()
        live: List[_Entry] = []
        expired: List[_Entry] = []
        for entry in batch:
            (expired if entry.eval.expired(now) else live).append(entry)
        if not expired:
            return batch
        with self._lock:
            self.expired_dropped += len(expired)
        metrics.incr_counter(("executive", "expired_dropped"),
                             len(expired))
        for entry in expired:
            trace.record_span(
                entry.eval.id, trace.STAGE_DISPATCH_ACCUMULATE,
                entry.enqueued_at, t_launch,
                ann={"expired": True, "deadline": entry.eval.deadline},
                trace_id=entry.eval.trace_id)
            self._finish_expired(entry)
        return live

    def _finish_expired(self, entry: _Entry) -> None:
        upd = entry.eval.copy()
        upd.status = consts.EVAL_STATUS_FAILED
        upd.status_description = (
            f"deadline expired before dispatch: deadline "
            f"{entry.eval.deadline:.3f} passed while accumulating "
            f"(originally triggered by {entry.eval.triggered_by!r})")
        try:
            self.server.eval_update([upd])
        except Exception:
            self.logger.warning(
                "expired-eval terminal write for %s failed; broker "
                "deadline check will re-park it", entry.eval.id,
                exc_info=True)
            self._finish(entry, acked=False)
            return
        self._finish(entry, acked=True)

    def _finish(self, entry: _Entry, acked: bool) -> None:
        if chaos.enabled and chaos.fire(
                "dispatch.finish", eval_id=entry.eval.id) == "drop":
            # Injected crash holding an unacked eval: the broker's nack
            # timer is the recovery path (chaos-soak invariant).
            with self._lock:
                self.finish_dropped += 1
            return
        try:
            if acked:
                self.server.eval_ack(entry.eval.id, entry.token)
            else:
                self.server.eval_nack(entry.eval.id, entry.token)
        except ValueError:
            pass  # nack timer fired concurrently
        except Exception:
            # Leader flap: the broker's nack timer reclaims the eval
            # either way; raising out of the loop/pool thread would
            # wedge the cohort instead.
            self.logger.warning(
                "eval %s %s failed; nack timer will reclaim",
                entry.eval.id, "ack" if acked else "nack",
                exc_info=True)
        with self._lock:
            if acked:
                self.acked += 1
            else:
                self.nacked += 1
        profile.event("ack", a=int(acked))

    def _wait_for_index(self, index: int, timeout: float) -> bool:
        return poll_until(
            lambda: self.server.fsm.state.latest_index() >= index,
            timeout, stop=self._stop, base=0.001, max_delay=0.1)

    # ----------------------------------------------------------- stats

    def stats(self) -> dict:
        with self._lock:
            cohorts = self.cohorts
            return {
                "enabled": self.enabled,
                "max_batch": self.max_batch,
                "executive_threads": self.threads,
                "cohorts": cohorts,
                "cohort_evals": self.cohort_evals,
                "occupancy": round(self.cohort_evals / cohorts, 2)
                if cohorts else 0.0,
                "largest_cohort": self.largest_cohort,
                "pending": len(self._pending),
                "evals_in": self.evals_in,
                "fast_evals": self.fast_evals,
                "legacy_evals": self.legacy_evals,
                "legacy_reasons": dict(self.legacy_reasons),
                "routed_host": self.routed_host,
                "host_fallbacks": self.host_fallbacks,
                "plan_conflicts": self.plan_conflicts,
                "expired_dropped": self.expired_dropped,
                "acked": self.acked,
                "nacked": self.nacked,
                "finish_dropped": self.finish_dropped,
                "drained": self.drained,
                "drain_us": int(self.t_drain * 1e6),
                "build_us": int(self.t_build * 1e6),
                "dispatch_us": int(self.t_dispatch * 1e6),
                "finalize_us": int(self.t_finalize * 1e6),
            }


class _SchedStub:
    """The two attributes scheduler/tpu.py's _build_allocation reads
    off a scheduler (`eval`, `job`) — the executive has no scheduler
    instance on its fast path."""

    __slots__ = ("eval", "job")

    def __init__(self, ev, job):
        self.eval = ev
        self.job = job
