"""Raft consensus: leader election, log replication, commit.

Reference: the reference embeds hashicorp/raft (nomad/server.go:634
setupRaft, raft_rpc.go stream layer); this is a from-scratch
implementation of the same protocol surface the control plane needs:
randomized election timeouts, RequestVote/AppendEntries, majority
commit, leadership-change notification driving the leader-only
services, and write forwarding to the leader. Transports are
pluggable: in-memory for in-process clusters/tests, TCP/JSON for
multi-host.

Durability and compaction (reference: raft-boltdb log + FSM snapshot
files, fsm.go:506, server.go:50): with a RaftStorage attached, the
term/vote metadata is fsynced before votes, the log is persisted and
replayed on restart, the FSM snapshots every `snapshot_threshold`
applies (retained files, log truncated), and followers too far behind
the compacted log receive an InstallSnapshot RPC.

Dynamic membership (reference: hashicorp/raft AddPeer/RemovePeer driven
by serf events, nomad/leader.go:551 addRaftPeer / :577 removeRaftPeer):
single-server configuration-change entries (`_raft.config`) carrying
the full member set. A configuration becomes ACTIVE when appended (the
dissertation's §4.1 rule — commitment is counted under the latest
appended config), one change may be in flight at a time, truncation
reverts to the previous config in the log, and snapshots embed the
member set so a restarted or far-behind node recovers membership with
its FSM.
"""

from __future__ import annotations

import logging
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..chaos import chaos
from ..utils.backoff import poll_until

FOLLOWER = "follower"
CANDIDATE = "candidate"
LEADER = "leader"

HEARTBEAT_INTERVAL = 0.05
ELECTION_TIMEOUT_MIN = 0.15
ELECTION_TIMEOUT_MAX = 0.30
APPLY_TIMEOUT = 10.0


@dataclass
class LogEntry:
    term: int
    index: int
    msg_type: str
    payload: Any


class _ApplyWaiter:
    __slots__ = ("event", "committed")

    def __init__(self):
        self.event = threading.Event()
        self.committed = False


class NotLeaderError(Exception):
    def __init__(self, leader_id: Optional[str]):
        super().__init__(f"not the leader (leader: {leader_id})")
        self.leader_id = leader_id


NOOP_TYPE = "_raft.noop"  # leadership barrier entry; never hits the FSM
CONFIG_TYPE = "_raft.config"  # membership change; payload {"peers": [...]}


class Transport:
    """RPC transport between raft peers."""

    def request_vote(self, peer: str, args: dict) -> Optional[dict]:
        raise NotImplementedError

    def append_entries(self, peer: str, args: dict) -> Optional[dict]:
        raise NotImplementedError

    def install_snapshot(self, peer: str, args: dict) -> Optional[dict]:
        raise NotImplementedError

    def forward_apply(self, peer: str, msg_type: str, payload: Any) -> int:
        raise NotImplementedError


class InmemTransport(Transport):
    """In-process transport: a shared registry of nodes. Supports
    partitioning for failure tests."""

    def __init__(self):
        self.nodes: Dict[str, "RaftNode"] = {}
        self.disconnected: set = set()

    def register(self, node: "RaftNode") -> None:
        self.nodes[node.node_id] = node

    def disconnect(self, node_id: str) -> None:
        self.disconnected.add(node_id)

    def reconnect(self, node_id: str) -> None:
        self.disconnected.discard(node_id)

    def _reachable(self, a: str, b: str) -> bool:
        return a not in self.disconnected and b not in self.disconnected

    @staticmethod
    def _exchange(peer: str, handler, args):
        """One RPC with the same transport.send/recv fault sites the
        TCP transport wires, so in-process cluster tests chaos-inject
        RPC loss without sockets. A send-drop loses the request (the
        handler never runs); a recv-drop runs the handler and loses the
        RESPONSE — the peer acted, the caller sees silence (the
        dangerous half of at-least-once delivery)."""
        if chaos.enabled and chaos.fire("transport.send", peer=peer) == "drop":
            return None
        resp = handler(args)
        if chaos.enabled and chaos.fire("transport.recv", peer=peer) == "drop":
            return None
        return resp

    def request_vote(self, peer: str, args: dict) -> Optional[dict]:
        node = self.nodes.get(peer)
        if node is None or not self._reachable(args["candidate_id"], peer):
            return None
        return self._exchange(peer, node.handle_request_vote, args)

    def append_entries(self, peer: str, args: dict) -> Optional[dict]:
        node = self.nodes.get(peer)
        if node is None or not self._reachable(args["leader_id"], peer):
            return None
        return self._exchange(peer, node.handle_append_entries, args)

    def install_snapshot(self, peer: str, args: dict) -> Optional[dict]:
        node = self.nodes.get(peer)
        if node is None or not self._reachable(args["leader_id"], peer):
            return None
        return self._exchange(peer, node.handle_install_snapshot, args)

    def forward_apply(self, peer: str, msg_type: str, payload: Any) -> int:
        node = self.nodes.get(peer)
        if node is None or peer in self.disconnected:
            raise ConnectionError(f"peer {peer} unreachable")
        # Mirror the TCP transport's forward hardening: a send-drop is
        # provably-unsent (the handler never ran), so riding it out
        # with backoff cannot double-apply.
        from ..utils.backoff import Backoff

        bo = Backoff(base=0.05, max_delay=0.4, attempts=3)
        while chaos.enabled and chaos.fire(
                "transport.send", peer=peer) == "drop":
            if not bo.sleep():
                raise ConnectionError(f"peer {peer} unreachable")
        return node.apply(msg_type, payload)


class RaftNode:
    def __init__(
        self,
        node_id: str,
        peers: List[str],
        transport: Transport,
        fsm_apply: Callable[[int, str, Any], Any],
        on_leadership: Callable[[bool], None],
        fsm_snapshot: Optional[Callable[[], dict]] = None,
        fsm_restore: Optional[Callable[[dict], None]] = None,
        storage=None,
        snapshot_threshold: int = 0,
    ):
        self.node_id = node_id
        self.peers = [p for p in peers if p != node_id]
        # Membership: seed config until a _raft.config entry or a
        # config-carrying snapshot overrides it. `removed` parks this
        # node (no campaigning) once a config excludes it.
        self._seed_peers = list(self.peers)
        self._snapshot_peers: Optional[List[str]] = None
        self.removed = False
        self.transport = transport
        self.fsm_apply = fsm_apply
        self.on_leadership = on_leadership
        self.fsm_snapshot = fsm_snapshot
        self.fsm_restore = fsm_restore
        self.storage = storage
        self.snapshot_threshold = snapshot_threshold
        self.logger = logging.getLogger(f"nomad_tpu.raft.{node_id}")

        self._lock = threading.RLock()
        self.state = FOLLOWER
        self.current_term = 0
        self.voted_for: Optional[str] = None
        self.log: List[LogEntry] = []  # indexes log_offset+1 .. via helpers
        # Compaction: everything at or below log_offset lives only in
        # the latest snapshot (log_offset = snapshot's last index).
        self.log_offset = 0
        self.snapshot_term = 0
        self._latest_snapshot: Optional[tuple] = None  # (index, term, data)
        self.commit_index = 0
        self.last_applied = 0
        self.leader_id: Optional[str] = None
        if storage is not None:
            self._restore_from_storage()

        # leader volatile state
        self.next_index: Dict[str, int] = {}
        self.match_index: Dict[str, int] = {}
        # Index of the noop barrier appended on election; config changes
        # are refused until it commits (see _change_config).
        self._term_start_index = 0

        self._last_heartbeat = time.monotonic()
        # Stale enough that votes are granted normally at boot.
        self._last_leader_contact = time.monotonic() - 3600.0
        self._election_deadline = self._next_election_deadline()
        # index -> (expected term, waiter); the commit must match the
        # term or the write was superseded by another leader.
        self._apply_waiters: Dict[int, Tuple[int, "_ApplyWaiter"]] = {}
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        # Leadership gain/loss callbacks run on one dispatcher thread in
        # FIFO order — a flap must never apply them reversed.
        import queue as _queue

        self._notify_queue: "_queue.Queue" = _queue.Queue()

    # ------------------------------------------------------------------

    def _notify_leadership(self, is_leader: bool) -> None:
        self._notify_queue.put(is_leader)

    def _run_notify(self) -> None:
        import queue as _queue

        while not self._stop.is_set():
            try:
                is_leader = self._notify_queue.get(timeout=0.2)
            except _queue.Empty:
                continue
            try:
                self.on_leadership(is_leader)
            except Exception:
                self.logger.exception("leadership callback failed")

    def start(self) -> None:
        for target, name in (
            (self._run_election_timer, "election"),
            (self._run_heartbeats, "heartbeat"),
            (self._run_apply, "apply"),
            (self._run_notify, "notify"),
        ):
            t = threading.Thread(
                target=target, name=f"raft-{self.node_id}-{name}", daemon=True
            )
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        was_leader = False
        with self._lock:
            was_leader = self.state == LEADER
            self.state = FOLLOWER
        self._stop.set()
        for t in self._threads:
            t.join(timeout=2.0)
        if was_leader:
            self.on_leadership(False)  # dispatcher stopped; call direct

    # ------------------------------------------------------ membership

    @staticmethod
    def _wrap_snapshot(data, peers: List[str]) -> dict:
        """Snapshots carry the member set so membership survives
        compaction/restart/InstallSnapshot alongside the FSM."""
        return {"__raft_fsm__": data, "__raft_peers__": sorted(peers)}

    @staticmethod
    def _unwrap_snapshot(blob) -> Tuple[Any, Optional[List[str]]]:
        if isinstance(blob, dict) and "__raft_fsm__" in blob:
            return blob["__raft_fsm__"], list(blob.get("__raft_peers__") or [])
        return blob, None  # legacy snapshot without a config

    def _members_locked(self) -> List[str]:
        return sorted(set(self.peers) | {self.node_id})

    def _activate_config_locked(self, members: List[str]) -> None:
        """A configuration takes effect as soon as it is appended (the
        single-server-change rule): votes and commit quorums count under
        the newest config in the log."""
        old_peers = set(self.peers)
        self.peers = [m for m in members if m != self.node_id]
        self.removed = self.node_id not in members
        if self.state == LEADER:
            nxt = self._last_log_index() + 1
            for p in self.peers:
                self.next_index.setdefault(p, nxt)
                self.match_index.setdefault(p, 0)
            for p in list(self.next_index):
                if p not in self.peers:
                    self.next_index.pop(p, None)
                    self.match_index.pop(p, None)
        # Departed peers: release their pooled transport connections
        # (otherwise every address ever in the cluster keeps sockets
        # open until process shutdown).
        forget = getattr(self.transport, "forget_peer", None)
        if forget is not None:
            for p in old_peers - set(self.peers):
                forget(p)
        self.logger.info("raft config active: %s", members)

    def _recompute_config_locked(self) -> None:
        """After truncation or restore: the active config is the last
        _raft.config entry in the log, else the snapshot's, else the
        seed peer set."""
        for entry in reversed(self.log):
            if entry.msg_type == CONFIG_TYPE:
                self._activate_config_locked(list(entry.payload["peers"]))
                return
        if self._snapshot_peers is not None:
            self._activate_config_locked(list(self._snapshot_peers))
            return
        self._activate_config_locked(
            sorted(set(self._seed_peers) | {self.node_id}))

    def _uncommitted_config_locked(self) -> bool:
        return any(
            e.msg_type == CONFIG_TYPE and e.index > self.commit_index
            for e in self.log
        )

    def _config_at_locked(self, index: int) -> List[str]:
        """Member set as of log position `index`: the last config entry
        at or below it, else the previous snapshot's, else the seed.
        Snapshots must embed THIS (not the active config): an active
        config past `index` may still be uncommitted, and persisting it
        would resurrect a truncated change after restart."""
        for entry in reversed(self.log):
            if entry.msg_type == CONFIG_TYPE and entry.index <= index:
                return list(entry.payload["peers"])
        if self._snapshot_peers is not None:
            return list(self._snapshot_peers)
        return sorted(set(self._seed_peers) | {self.node_id})

    # ----------------------------------------------------- persistence

    def _restore_from_storage(self) -> None:
        """Snapshot install + log replay on restart (the reference's
        raft does the same from raft.db + snapshot files)."""
        self.current_term, self.voted_for = self.storage.load_meta()
        snap = self.storage.load_latest_snapshot()
        if snap is not None:
            index, term, blob = snap
            data, peers = self._unwrap_snapshot(blob)
            if self.fsm_restore is not None:
                self.fsm_restore(data)
            self._snapshot_peers = peers
            self.log_offset = index
            self.snapshot_term = term
            self.commit_index = index
            self.last_applied = index
            self._latest_snapshot = snap
        entries = [e for e in self.storage.load_log(LogEntry)
                   if e.index > self.log_offset]
        # Guard against a gap (snapshot newer than the log tail).
        expect = self.log_offset + 1
        for e in entries:
            if e.index != expect:
                break
            self.log.append(e)
            expect += 1
        if snap is not None or any(
                e.msg_type == CONFIG_TYPE for e in self.log):
            self._recompute_config_locked()
        if self.log or snap is not None:
            self.logger.info(
                "restored raft state: snapshot@%d + %d log entries",
                self.log_offset, len(self.log))

    def _persist_meta(self) -> None:
        if self.storage is not None:
            self.storage.save_meta(self.current_term, self.voted_for)

    # ----------------------------------------------------- log helpers

    def _last_log_index(self) -> int:
        return self.log[-1].index if self.log else self.log_offset

    def _last_log_term(self) -> int:
        return self.log[-1].term if self.log else self.snapshot_term

    def _entry_at(self, index: int) -> Optional[LogEntry]:
        i = index - self.log_offset
        if i <= 0 or i > len(self.log):
            return None
        return self.log[i - 1]

    @staticmethod
    def _next_election_deadline() -> float:
        return time.monotonic() + random.uniform(
            ELECTION_TIMEOUT_MIN, ELECTION_TIMEOUT_MAX
        )

    # ------------------------------------------------------- RPC side

    def handle_request_vote(self, args: dict) -> dict:
        with self._lock:
            term = args["term"]
            up_to_date = (args["last_log_term"], args["last_log_index"]) >= (
                self._last_log_term(),
                self._last_log_index(),
            )
            if args.get("prevote"):
                # PreVote (dissertation §9.6, etcd PreVote): a candidate
                # first asks whether an election is even warranted —
                # NOTHING here mutates state, so a disruptive candidate
                # (a REMOVED server that never learned of its removal,
                # or a rejoining partitioned node) cannot inflate terms
                # and depose a healthy leader unless a majority agrees
                # the leader is gone. Grant iff the candidate's log
                # qualifies AND we have not heard from a live leader
                # within the minimum election timeout (the leader itself
                # counts ACK receipt as contact).
                leaderish = self.leader_id is not None or self.state == LEADER
                heard_recently = (
                    time.monotonic() - self._last_leader_contact
                    < ELECTION_TIMEOUT_MIN
                )
                granted = (
                    term >= self.current_term
                    and up_to_date
                    and not (leaderish and heard_recently
                             and args["candidate_id"] != self.leader_id)
                )
                return {"term": self.current_term, "vote_granted": granted}
            if term < self.current_term:
                return {"term": self.current_term, "vote_granted": False}
            if term > self.current_term:
                self._become_follower(term)
            if self.voted_for in (None, args["candidate_id"]) and up_to_date:
                self.voted_for = args["candidate_id"]
                self._persist_meta()  # durable before the vote leaves
                self._election_deadline = self._next_election_deadline()
                return {"term": self.current_term, "vote_granted": True}
            return {"term": self.current_term, "vote_granted": False}

    def handle_append_entries(self, args: dict) -> dict:
        with self._lock:
            term = args["term"]
            if term < self.current_term:
                return {"term": self.current_term, "success": False}
            if term > self.current_term or self.state != FOLLOWER:
                self._become_follower(term)
            self.leader_id = args["leader_id"]
            self._last_leader_contact = time.monotonic()
            self._election_deadline = self._next_election_deadline()

            prev_index = args["prev_log_index"]
            prev_term = args["prev_log_term"]
            if prev_index > 0 and prev_index != self.log_offset:
                entry = self._entry_at(prev_index)
                if entry is None or entry.term != prev_term:
                    return {"term": self.current_term, "success": False}
            if prev_index == self.log_offset and self.log_offset > 0:
                if prev_term != self.snapshot_term:
                    return {"term": self.current_term, "success": False}

            # Append, truncating conflicts.
            truncated = False
            appended = []
            for raw in args["entries"]:
                entry = LogEntry(**raw) if isinstance(raw, dict) else raw
                if entry.index <= self.log_offset:
                    continue  # already compacted into the snapshot
                existing = self._entry_at(entry.index)
                if existing is not None and existing.term != entry.term:
                    del self.log[entry.index - 1 - self.log_offset:]
                    truncated = True
                    existing = None
                if existing is None:
                    self.log.append(entry)
                    appended.append(entry)
            if self.storage is not None:
                if truncated:
                    self.storage.rewrite_log(self.log)
                else:
                    for entry in appended:
                        self.storage.append_entry(entry)
            if truncated or any(
                    e.msg_type == CONFIG_TYPE for e in appended):
                # Config entries activate on append; a truncation may
                # have removed one, reverting to the prior config.
                self._recompute_config_locked()

            if args["leader_commit"] > self.commit_index:
                self.commit_index = min(
                    args["leader_commit"], self._last_log_index()
                )
            return {"term": self.current_term, "success": True}

    def handle_install_snapshot(self, args: dict) -> dict:
        """A follower too far behind the leader's compacted log gets
        the whole FSM snapshot (raft InstallSnapshot)."""
        with self._lock:
            term = args["term"]
            if term < self.current_term:
                return {"term": self.current_term}
            if term > self.current_term or self.state != FOLLOWER:
                self._become_follower(term)
            self.leader_id = args["leader_id"]
            self._last_leader_contact = time.monotonic()
            self._election_deadline = self._next_election_deadline()
            last_index = args["last_index"]
            if last_index <= self.log_offset:
                return {"term": self.current_term}  # already have it
            data, peers = self._unwrap_snapshot(args["data"])
            if self.fsm_restore is not None:
                self.fsm_restore(data)
            self._snapshot_peers = peers
            self.log = []
            self.log_offset = last_index
            self.snapshot_term = args["last_term"]
            self.commit_index = max(self.commit_index, last_index)
            self.last_applied = last_index
            self._latest_snapshot = (last_index, args["last_term"],
                                     args["data"])
            if peers is not None:
                self._recompute_config_locked()
            if self.storage is not None:
                self.storage.save_snapshot(last_index, args["last_term"],
                                           args["data"])
                self.storage.rewrite_log(self.log)
            self.logger.info("installed snapshot @%d", last_index)
            return {"term": self.current_term}

    # ------------------------------------------------------ elections

    def _become_follower(self, term: int) -> None:
        was_leader = self.state == LEADER
        self.state = FOLLOWER
        if term > self.current_term:
            # One vote per term: voted_for only resets on a NEW term.
            self.current_term = term
            self.voted_for = None
            self._persist_meta()
        if was_leader:
            self._notify_leadership(False)

    def _run_election_timer(self) -> None:
        while not self._stop.is_set():
            time.sleep(0.02)
            with self._lock:
                if self.state == LEADER:
                    continue
                if self.removed:
                    # Excluded by the active config: never campaign (a
                    # removed node bumping terms would disrupt the
                    # cluster it was removed from).
                    continue
                if time.monotonic() < self._election_deadline:
                    continue
                # Timeout: probe with a PreVote round BEFORE touching
                # any state — only a majority agreeing the leader is
                # gone justifies a term bump (disruption-free elections).
                self._election_deadline = self._next_election_deadline()
                probe_term = self.current_term + 1
                last_idx, last_term = self._last_log_index(), self._last_log_term()
            try:
                if not self._prevote(probe_term, last_idx, last_term):
                    continue
            except Exception:  # noqa: BLE001 - the timer must survive
                self.logger.exception("prevote failed")
                continue
            with self._lock:
                if self.state == LEADER or self.removed:
                    continue
                self.state = CANDIDATE
                self.current_term += 1
                self.voted_for = self.node_id
                self._persist_meta()
                term = self.current_term
                self._election_deadline = self._next_election_deadline()
                last_idx, last_term = self._last_log_index(), self._last_log_term()
            try:
                self._campaign(term, last_idx, last_term)
            except Exception:  # noqa: BLE001 - the timer must survive
                self.logger.exception("campaign failed")

    def _prevote(self, term: int, last_idx: int, last_term: int) -> bool:
        """True when a majority would vote for us at `term` — no state
        anywhere changes during the probe."""
        votes = 1  # we would vote for ourselves
        args = {
            "term": term,
            "candidate_id": self.node_id,
            "last_log_index": last_idx,
            "last_log_term": last_term,
            "prevote": True,
        }
        for peer in self.peers:
            resp = self.transport.request_vote(peer, args)
            if resp and resp.get("vote_granted"):
                votes += 1
            if votes * 2 > len(self.peers) + 1:
                return True
        return votes * 2 > len(self.peers) + 1

    def _campaign(self, term: int, last_idx: int, last_term: int) -> None:
        votes = 1
        args = {
            "term": term,
            "candidate_id": self.node_id,
            "last_log_index": last_idx,
            "last_log_term": last_term,
        }
        for peer in self.peers:
            resp = self.transport.request_vote(peer, args)
            if resp is None or "term" not in resp:
                continue  # unreachable, or peer's raft not up yet
            with self._lock:
                if resp["term"] > self.current_term:
                    self._become_follower(resp["term"])
                    return
                if self.state != CANDIDATE or self.current_term != term:
                    return
            if resp["vote_granted"]:
                votes += 1
        if votes * 2 > len(self.peers) + 1:
            with self._lock:
                if self.state != CANDIDATE or self.current_term != term:
                    return
                self.state = LEADER
                self.leader_id = self.node_id
                # Barrier noop: raft never commits an older-term entry
                # by counting replicas, so a fresh leader appends one
                # entry of its own term to drive the commit index over
                # everything inherited (also what makes restart-recovery
                # of a single-node cluster re-apply its restored log).
                noop = LogEntry(term, self._last_log_index() + 1,
                                NOOP_TYPE, None)
                self.log.append(noop)
                self._term_start_index = noop.index
                if self.storage is not None:
                    self.storage.append_entry(noop)
                nxt = self._last_log_index() + 1
                self.next_index = {p: nxt for p in self.peers}
                self.match_index = {p: 0 for p in self.peers}
            self.logger.info("became leader for term %d", term)
            self._broadcast_heartbeat()
            self._notify_leadership(True)

    # ------------------------------------------------------ leadership

    def _run_heartbeats(self) -> None:
        while not self._stop.is_set():
            with self._lock:
                is_leader = self.state == LEADER
            if is_leader:
                try:
                    self._broadcast_heartbeat()
                except Exception:  # noqa: BLE001 - must survive
                    self.logger.exception("heartbeat broadcast failed")
            time.sleep(HEARTBEAT_INTERVAL)

    def _broadcast_heartbeat(self) -> None:
        if chaos.enabled and chaos.fire(
                "raft.heartbeat", node=self.node_id) == "drop":
            # Injected: the leader misses a whole broadcast round —
            # enough consecutive drops age followers past their
            # election timeout and flap leadership organically.
            return
        for peer in self.peers:
            self._replicate_to(peer)
        self._advance_commit()

    def _replicate_to(self, peer: str) -> None:
        with self._lock:
            if self.state != LEADER:
                return
            next_idx = self.next_index.get(peer, self._last_log_index() + 1)
            if next_idx <= self.log_offset and self._latest_snapshot:
                # The entries this peer needs are compacted away: ship
                # the snapshot instead (InstallSnapshot RPC).
                snap_index, snap_term, snap_data = self._latest_snapshot
                install_args = {
                    "term": self.current_term,
                    "leader_id": self.node_id,
                    "last_index": snap_index,
                    "last_term": snap_term,
                    "data": snap_data,
                }
            else:
                install_args = None
                next_idx = max(next_idx, self.log_offset + 1)
                prev_idx = next_idx - 1
                if prev_idx == self.log_offset:
                    prev_term = self.snapshot_term
                else:
                    prev_entry = self._entry_at(prev_idx)
                    prev_term = prev_entry.term if prev_entry else 0
                entries = list(self.log[next_idx - 1 - self.log_offset:])
                args = {
                    "term": self.current_term,
                    "leader_id": self.node_id,
                    "prev_log_index": prev_idx,
                    "prev_log_term": prev_term,
                    "entries": entries,
                    "leader_commit": self.commit_index,
                }
        if install_args is not None:
            resp = self.transport.install_snapshot(peer, install_args)
            if resp is None or "term" not in resp:
                return
            with self._lock:
                if resp["term"] > self.current_term:
                    self._become_follower(resp["term"])
                    return
                if self.state != LEADER:
                    return
                self.match_index[peer] = max(
                    self.match_index.get(peer, 0), install_args["last_index"])
                self.next_index[peer] = install_args["last_index"] + 1
            return
        resp = self.transport.append_entries(peer, args)
        if resp is None or "term" not in resp:
            return  # unreachable, or peer's raft not up yet
        with self._lock:
            if resp["term"] > self.current_term:
                self._become_follower(resp["term"])
                return
            if self.state != LEADER:
                return
            # A same-term response from a member is cluster contact: it
            # keeps the LEADER'S recent-leader window fresh for PreVote
            # denial, so a removed server's endless campaigns cannot
            # depose a leader that is still replicating (followers get
            # their window from receiving these appends; the leader
            # gets it from the ACKs).
            self._last_leader_contact = time.monotonic()
            if resp.get("success"):
                if entries:
                    self.match_index[peer] = entries[-1].index
                    self.next_index[peer] = entries[-1].index + 1
            else:
                self.next_index[peer] = max(1, self.next_index.get(peer, 1) - 1)

    def _advance_commit(self) -> None:
        if chaos.enabled and chaos.fire(
                "raft.commit", node=self.node_id) == "drop":
            return  # injected commit latency: skip this advance round
        with self._lock:
            if self.state != LEADER:
                return
            for n in range(self._last_log_index(), self.commit_index, -1):
                entry = self._entry_at(n)
                if entry is None or entry.term != self.current_term:
                    continue
                votes = 1 + sum(
                    1 for p in self.peers if self.match_index.get(p, 0) >= n
                )
                if votes * 2 > len(self.peers) + 1:
                    self.commit_index = n
                    break

    # ----------------------------------------------------------- apply

    def apply(self, msg_type: str, payload: Any) -> int:
        """Append an entry; blocks until it is committed and applied
        locally. Followers forward to the leader. Raises if the write
        was superseded (lost leadership before commit)."""
        if chaos.enabled:
            # 'delay' = injected apply latency (a slow disk / loaded
            # leader); 'error' raises like a mid-apply leader loss.
            chaos.fire("raft.apply", node=self.node_id, msg_type=msg_type)
        with self._lock:
            if self.state != LEADER:
                leader = self.leader_id
                if leader is None:
                    raise NotLeaderError(None)
                forward = True
                index = waiter = None
            else:
                forward = False
                index, waiter = self._leader_append_locked(msg_type, payload)
        if forward:
            return self.transport.forward_apply(leader, msg_type, payload)
        return self._wait_commit(index, waiter)

    def _leader_append_locked(self, msg_type: str, payload: Any):
        index = self._last_log_index() + 1
        term = self.current_term
        entry = LogEntry(term, index, msg_type, payload)
        self.log.append(entry)
        if self.storage is not None:
            self.storage.append_entry(entry)
        waiter = _ApplyWaiter()
        self._apply_waiters[index] = (term, waiter)
        return index, waiter

    def _wait_commit(self, index: int, waiter: "_ApplyWaiter") -> int:
        # Actively drive replication while waiting: a dropped round
        # otherwise stalls the commit until the next heartbeat tick.
        deadline = time.monotonic() + APPLY_TIMEOUT
        self._broadcast_heartbeat()
        while not waiter.event.wait(0.05):
            if time.monotonic() > deadline:
                with self._lock:
                    self._apply_waiters.pop(index, None)
                raise TimeoutError(f"apply of index {index} timed out")
            self._broadcast_heartbeat()
        if not waiter.committed:
            # A different leader committed a different entry here.
            raise NotLeaderError(self.leader_id)
        return index

    # --------------------------------------------- membership change API

    def add_peer(self, peer_id: str) -> None:
        """Leader-only: add a server to the cluster (leader.go:551
        addRaftPeer). No-op if already a member."""
        self._change_config(add=peer_id)

    def remove_peer(self, peer_id: str) -> None:
        """Leader-only: remove a server (leader.go:577 removeRaftPeer).
        No-op if not a member."""
        self._change_config(remove=peer_id)

    def _wait_term_barrier(self, timeout: float = 2.0) -> None:
        """Block until an entry of the CURRENT term (the election noop)
        is committed. With append-time-active single-server changes, a
        config change before that barrier is the classic membership
        safety bug: an old leader holding an uncommitted add-peer config
        and a new leader appending remove-peer before its barrier
        commits can form disjoint quorums and commit divergent entries.
        PreVote narrows but does not close the window under partition —
        this gate closes it. Raises if the barrier doesn't land in time
        (the membership reconcile sweep retries)."""
        deadline = time.monotonic() + timeout
        # One nudge up front to drive the barrier's replication; after
        # that the heartbeat thread owns retransmission. Broadcasting
        # from the waiter loop (as this once did) serializes synchronous
        # per-peer RPCs every 20ms — with an unreachable peer and a slow
        # transport timeout a single _change_config could block far past
        # the deadline while hammering the network.
        nudged = False
        while True:
            with self._lock:
                if self.state != LEADER:
                    raise NotLeaderError(self.leader_id)
                if self.commit_index >= self._term_start_index:
                    return
            if time.monotonic() > deadline:
                raise ValueError(
                    "leadership not established: election barrier not "
                    "committed yet")
            if not nudged:
                nudged = True
                self._broadcast_heartbeat()
            time.sleep(0.02)

    def _change_config(self, add: Optional[str] = None,
                       remove: Optional[str] = None) -> None:
        self._wait_term_barrier()
        with self._lock:
            if self.state != LEADER:
                raise NotLeaderError(self.leader_id)
            if remove == self.node_id:
                raise ValueError(
                    "cannot remove the leader; transfer leadership first")
            if self.commit_index < self._term_start_index:
                # Re-elected between the barrier wait and here: the NEW
                # term's barrier is pending again; let the caller retry.
                raise ValueError(
                    "leadership not established: election barrier not "
                    "committed yet")
            if self._uncommitted_config_locked():
                raise ValueError("configuration change already in progress")
            members = set(self.peers) | {self.node_id}
            if add is not None:
                if add in members:
                    return
                members.add(add)
            if remove is not None:
                if remove not in members:
                    return
                members.discard(remove)
            index, waiter = self._leader_append_locked(
                CONFIG_TYPE, {"peers": sorted(members)})
            # Active on append: replication and commit of this very
            # entry already count under the new configuration.
            self._activate_config_locked(sorted(members))
        self._wait_commit(index, waiter)

    def _run_apply(self) -> None:
        while not self._stop.is_set():
            applied_any = False
            with self._lock:
                while self.last_applied < self.commit_index:
                    self.last_applied += 1
                    entry = self._entry_at(self.last_applied)
                    waiting = self._apply_waiters.pop(self.last_applied, None)
                    if entry is not None and entry.msg_type not in (
                            NOOP_TYPE, CONFIG_TYPE):
                        try:
                            self.fsm_apply(entry.index, entry.msg_type, entry.payload)
                        except Exception:
                            self.logger.exception(
                                "fsm apply failed at %d", entry.index
                            )
                    if waiting is not None:
                        expected_term, waiter = waiting
                        # Only ack the waiter if OUR entry committed; a
                        # different term means the write was lost.
                        waiter.committed = (
                            entry is not None and entry.term == expected_term
                        )
                        waiter.event.set()
                    applied_any = True
            if applied_any:
                self._maybe_compact()
            else:
                time.sleep(0.005)

    def _maybe_compact(self) -> None:
        """Snapshot the FSM and truncate the applied log prefix once
        enough entries accumulated (fsm.go:506 persist, retained files;
        threshold 0 disables)."""
        if (not self.snapshot_threshold or self.fsm_snapshot is None):
            return
        with self._lock:
            due = (self.last_applied - self.log_offset
                   >= self.snapshot_threshold)
            snap_index = self.last_applied
        if not due:
            return
        # Snapshotting outside the raft lock keeps elections unblocked;
        # normally only this thread advances the FSM, but an
        # InstallSnapshot can land concurrently — re-validate under the
        # lock and abort if it did (the installed snapshot is newer).
        data = self.fsm_snapshot()
        with self._lock:
            if self.log_offset >= snap_index or self.last_applied != snap_index:
                return  # superseded by a concurrent snapshot install
            entry = self._entry_at(snap_index)
            snap_term = entry.term if entry else self.snapshot_term
            snap_peers = self._config_at_locked(snap_index)
            blob = self._wrap_snapshot(data, snap_peers)
            self.log = self.log[snap_index - self.log_offset:]
            self.log_offset = snap_index
            self.snapshot_term = snap_term
            self._snapshot_peers = snap_peers
            self._latest_snapshot = (snap_index, snap_term, blob)
            if self.storage is not None:
                self.storage.save_snapshot(snap_index, snap_term, blob)
                self.storage.rewrite_log(self.log)
        self.logger.info("compacted log @%d (%d entries kept)",
                         snap_index, len(self.log))

    # ------------------------------------------------------------------

    def is_leader(self) -> bool:
        with self._lock:
            return self.state == LEADER

    def last_index(self) -> int:
        with self._lock:
            return self.last_applied

    def last_contact(self) -> float:
        """Seconds since this node last heard from a leader (0.0 while
        leading) — the staleness bound a `?stale` read advertises via
        X-Nomad-LastContact."""
        with self._lock:
            if self.state == LEADER:
                return 0.0
            return max(0.0, time.monotonic() - self._last_leader_contact)

    def known_commit_index(self) -> int:
        """The leader commit index this node has observed — the wait
        target a `?consistent` follower read uses for read-your-writes
        without leader forwarding."""
        with self._lock:
            return self.commit_index

    def barrier(self) -> int:
        return self.last_index()

    def stats(self) -> dict:
        with self._lock:
            return {
                "state": self.state,
                "term": self.current_term,
                "leader": self.leader_id,
                "commit_index": self.commit_index,
                "last_applied": self.last_applied,
                "log_len": len(self.log),
                "members": self._members_locked(),
                "removed": self.removed,
            }


class UnavailableLog:
    """Log stand-in while a raft cluster is still forming: writes fail
    with no-leader (the reference blocks RPC writes the same way until
    raft elects), reads see index 0."""

    def apply(self, msg_type: str, payload: Any) -> int:
        raise NotLeaderError(None)

    def last_index(self) -> int:
        return 0

    def barrier(self) -> int:
        return 0


class RaftLog:
    """Adapter giving RaftNode the DevLog interface the Server uses.
    Forwarded writes wait for the local FSM to catch up so endpoint code
    can read its own writes (the reference forwards whole RPCs to the
    leader, which reads there; here only the log write forwards)."""

    def __init__(self, node: RaftNode):
        self.node = node

    def apply(self, msg_type: str, payload: Any) -> int:
        index = self.node.apply(msg_type, payload)
        if not poll_until(lambda: self.node.last_index() >= index,
                          APPLY_TIMEOUT, base=0.002, max_delay=0.05):
            raise TimeoutError(
                f"local fsm did not reach index {index} in time"
            )
        return index

    def last_index(self) -> int:
        return self.node.last_index()

    def barrier(self) -> int:
        return self.node.barrier()
