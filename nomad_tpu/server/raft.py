"""Raft consensus: leader election, log replication, commit.

Reference: the reference embeds hashicorp/raft (nomad/server.go:634
setupRaft, raft_rpc.go stream layer); this is a from-scratch
implementation of the same protocol surface the control plane needs:
randomized election timeouts, RequestVote/AppendEntries, majority
commit, leadership-change notification driving the leader-only
services, and write forwarding to the leader. Transports are
pluggable: in-memory for in-process clusters/tests, TCP/JSON for
multi-host.

Not implemented (acceptable for the capability target): log
compaction/snapshot install (the FSM has persist()/restore() ready) and
dynamic membership change.
"""

from __future__ import annotations

import logging
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

FOLLOWER = "follower"
CANDIDATE = "candidate"
LEADER = "leader"

HEARTBEAT_INTERVAL = 0.05
ELECTION_TIMEOUT_MIN = 0.15
ELECTION_TIMEOUT_MAX = 0.30
APPLY_TIMEOUT = 10.0


@dataclass
class LogEntry:
    term: int
    index: int
    msg_type: str
    payload: Any


class _ApplyWaiter:
    __slots__ = ("event", "committed")

    def __init__(self):
        self.event = threading.Event()
        self.committed = False


class NotLeaderError(Exception):
    def __init__(self, leader_id: Optional[str]):
        super().__init__(f"not the leader (leader: {leader_id})")
        self.leader_id = leader_id


class Transport:
    """RPC transport between raft peers."""

    def request_vote(self, peer: str, args: dict) -> Optional[dict]:
        raise NotImplementedError

    def append_entries(self, peer: str, args: dict) -> Optional[dict]:
        raise NotImplementedError

    def forward_apply(self, peer: str, msg_type: str, payload: Any) -> int:
        raise NotImplementedError


class InmemTransport(Transport):
    """In-process transport: a shared registry of nodes. Supports
    partitioning for failure tests."""

    def __init__(self):
        self.nodes: Dict[str, "RaftNode"] = {}
        self.disconnected: set = set()

    def register(self, node: "RaftNode") -> None:
        self.nodes[node.node_id] = node

    def disconnect(self, node_id: str) -> None:
        self.disconnected.add(node_id)

    def reconnect(self, node_id: str) -> None:
        self.disconnected.discard(node_id)

    def _reachable(self, a: str, b: str) -> bool:
        return a not in self.disconnected and b not in self.disconnected

    def request_vote(self, peer: str, args: dict) -> Optional[dict]:
        node = self.nodes.get(peer)
        if node is None or not self._reachable(args["candidate_id"], peer):
            return None
        return node.handle_request_vote(args)

    def append_entries(self, peer: str, args: dict) -> Optional[dict]:
        node = self.nodes.get(peer)
        if node is None or not self._reachable(args["leader_id"], peer):
            return None
        return node.handle_append_entries(args)

    def forward_apply(self, peer: str, msg_type: str, payload: Any) -> int:
        node = self.nodes.get(peer)
        if node is None or peer in self.disconnected:
            raise ConnectionError(f"peer {peer} unreachable")
        return node.apply(msg_type, payload)


class RaftNode:
    def __init__(
        self,
        node_id: str,
        peers: List[str],
        transport: Transport,
        fsm_apply: Callable[[int, str, Any], Any],
        on_leadership: Callable[[bool], None],
    ):
        self.node_id = node_id
        self.peers = [p for p in peers if p != node_id]
        self.transport = transport
        self.fsm_apply = fsm_apply
        self.on_leadership = on_leadership
        self.logger = logging.getLogger(f"nomad_tpu.raft.{node_id}")

        self._lock = threading.RLock()
        self.state = FOLLOWER
        self.current_term = 0
        self.voted_for: Optional[str] = None
        self.log: List[LogEntry] = []  # 1-indexed via helpers
        self.commit_index = 0
        self.last_applied = 0
        self.leader_id: Optional[str] = None

        # leader volatile state
        self.next_index: Dict[str, int] = {}
        self.match_index: Dict[str, int] = {}

        self._last_heartbeat = time.monotonic()
        self._election_deadline = self._next_election_deadline()
        # index -> (expected term, waiter); the commit must match the
        # term or the write was superseded by another leader.
        self._apply_waiters: Dict[int, Tuple[int, "_ApplyWaiter"]] = {}
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        # Leadership gain/loss callbacks run on one dispatcher thread in
        # FIFO order — a flap must never apply them reversed.
        import queue as _queue

        self._notify_queue: "_queue.Queue" = _queue.Queue()

    # ------------------------------------------------------------------

    def _notify_leadership(self, is_leader: bool) -> None:
        self._notify_queue.put(is_leader)

    def _run_notify(self) -> None:
        import queue as _queue

        while not self._stop.is_set():
            try:
                is_leader = self._notify_queue.get(timeout=0.2)
            except _queue.Empty:
                continue
            try:
                self.on_leadership(is_leader)
            except Exception:
                self.logger.exception("leadership callback failed")

    def start(self) -> None:
        for target, name in (
            (self._run_election_timer, "election"),
            (self._run_heartbeats, "heartbeat"),
            (self._run_apply, "apply"),
            (self._run_notify, "notify"),
        ):
            t = threading.Thread(
                target=target, name=f"raft-{self.node_id}-{name}", daemon=True
            )
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        was_leader = False
        with self._lock:
            was_leader = self.state == LEADER
            self.state = FOLLOWER
        self._stop.set()
        for t in self._threads:
            t.join(timeout=2.0)
        if was_leader:
            self.on_leadership(False)  # dispatcher stopped; call direct

    # ----------------------------------------------------- log helpers

    def _last_log_index(self) -> int:
        return self.log[-1].index if self.log else 0

    def _last_log_term(self) -> int:
        return self.log[-1].term if self.log else 0

    def _entry_at(self, index: int) -> Optional[LogEntry]:
        if index <= 0 or index > len(self.log):
            return None
        return self.log[index - 1]

    @staticmethod
    def _next_election_deadline() -> float:
        return time.monotonic() + random.uniform(
            ELECTION_TIMEOUT_MIN, ELECTION_TIMEOUT_MAX
        )

    # ------------------------------------------------------- RPC side

    def handle_request_vote(self, args: dict) -> dict:
        with self._lock:
            term = args["term"]
            if term < self.current_term:
                return {"term": self.current_term, "vote_granted": False}
            if term > self.current_term:
                self._become_follower(term)
            up_to_date = (args["last_log_term"], args["last_log_index"]) >= (
                self._last_log_term(),
                self._last_log_index(),
            )
            if self.voted_for in (None, args["candidate_id"]) and up_to_date:
                self.voted_for = args["candidate_id"]
                self._election_deadline = self._next_election_deadline()
                return {"term": self.current_term, "vote_granted": True}
            return {"term": self.current_term, "vote_granted": False}

    def handle_append_entries(self, args: dict) -> dict:
        with self._lock:
            term = args["term"]
            if term < self.current_term:
                return {"term": self.current_term, "success": False}
            if term > self.current_term or self.state != FOLLOWER:
                self._become_follower(term)
            self.leader_id = args["leader_id"]
            self._election_deadline = self._next_election_deadline()

            prev_index = args["prev_log_index"]
            prev_term = args["prev_log_term"]
            if prev_index > 0:
                entry = self._entry_at(prev_index)
                if entry is None or entry.term != prev_term:
                    return {"term": self.current_term, "success": False}

            # Append, truncating conflicts.
            for raw in args["entries"]:
                entry = LogEntry(**raw) if isinstance(raw, dict) else raw
                existing = self._entry_at(entry.index)
                if existing is not None and existing.term != entry.term:
                    del self.log[entry.index - 1 :]
                    existing = None
                if existing is None:
                    self.log.append(entry)

            if args["leader_commit"] > self.commit_index:
                self.commit_index = min(
                    args["leader_commit"], self._last_log_index()
                )
            return {"term": self.current_term, "success": True}

    # ------------------------------------------------------ elections

    def _become_follower(self, term: int) -> None:
        was_leader = self.state == LEADER
        self.state = FOLLOWER
        if term > self.current_term:
            # One vote per term: voted_for only resets on a NEW term.
            self.current_term = term
            self.voted_for = None
        if was_leader:
            self._notify_leadership(False)

    def _run_election_timer(self) -> None:
        while not self._stop.is_set():
            time.sleep(0.02)
            with self._lock:
                if self.state == LEADER:
                    continue
                if time.monotonic() < self._election_deadline:
                    continue
                # timeout: stand for election
                self.state = CANDIDATE
                self.current_term += 1
                self.voted_for = self.node_id
                term = self.current_term
                self._election_deadline = self._next_election_deadline()
                last_idx, last_term = self._last_log_index(), self._last_log_term()
            self._campaign(term, last_idx, last_term)

    def _campaign(self, term: int, last_idx: int, last_term: int) -> None:
        votes = 1
        args = {
            "term": term,
            "candidate_id": self.node_id,
            "last_log_index": last_idx,
            "last_log_term": last_term,
        }
        for peer in self.peers:
            resp = self.transport.request_vote(peer, args)
            if resp is None:
                continue
            with self._lock:
                if resp["term"] > self.current_term:
                    self._become_follower(resp["term"])
                    return
                if self.state != CANDIDATE or self.current_term != term:
                    return
            if resp["vote_granted"]:
                votes += 1
        if votes * 2 > len(self.peers) + 1:
            with self._lock:
                if self.state != CANDIDATE or self.current_term != term:
                    return
                self.state = LEADER
                self.leader_id = self.node_id
                nxt = self._last_log_index() + 1
                self.next_index = {p: nxt for p in self.peers}
                self.match_index = {p: 0 for p in self.peers}
            self.logger.info("became leader for term %d", term)
            self._broadcast_heartbeat()
            self._notify_leadership(True)

    # ------------------------------------------------------ leadership

    def _run_heartbeats(self) -> None:
        while not self._stop.is_set():
            with self._lock:
                is_leader = self.state == LEADER
            if is_leader:
                self._broadcast_heartbeat()
            time.sleep(HEARTBEAT_INTERVAL)

    def _broadcast_heartbeat(self) -> None:
        for peer in self.peers:
            self._replicate_to(peer)
        self._advance_commit()

    def _replicate_to(self, peer: str) -> None:
        with self._lock:
            if self.state != LEADER:
                return
            next_idx = self.next_index.get(peer, self._last_log_index() + 1)
            prev_idx = next_idx - 1
            prev_entry = self._entry_at(prev_idx)
            prev_term = prev_entry.term if prev_entry else 0
            entries = [e for e in self.log[next_idx - 1 :]]
            args = {
                "term": self.current_term,
                "leader_id": self.node_id,
                "prev_log_index": prev_idx,
                "prev_log_term": prev_term,
                "entries": entries,
                "leader_commit": self.commit_index,
            }
        resp = self.transport.append_entries(peer, args)
        if resp is None:
            return
        with self._lock:
            if resp["term"] > self.current_term:
                self._become_follower(resp["term"])
                return
            if self.state != LEADER:
                return
            if resp["success"]:
                if entries:
                    self.match_index[peer] = entries[-1].index
                    self.next_index[peer] = entries[-1].index + 1
            else:
                self.next_index[peer] = max(1, self.next_index.get(peer, 1) - 1)

    def _advance_commit(self) -> None:
        with self._lock:
            if self.state != LEADER:
                return
            for n in range(self._last_log_index(), self.commit_index, -1):
                entry = self._entry_at(n)
                if entry is None or entry.term != self.current_term:
                    continue
                votes = 1 + sum(
                    1 for p in self.peers if self.match_index.get(p, 0) >= n
                )
                if votes * 2 > len(self.peers) + 1:
                    self.commit_index = n
                    break

    # ----------------------------------------------------------- apply

    def apply(self, msg_type: str, payload: Any) -> int:
        """Append an entry; blocks until it is committed and applied
        locally. Followers forward to the leader. Raises if the write
        was superseded (lost leadership before commit)."""
        with self._lock:
            if self.state != LEADER:
                leader = self.leader_id
                if leader is None:
                    raise NotLeaderError(None)
                forward = True
            else:
                forward = False
                index = self._last_log_index() + 1
                term = self.current_term
                entry = LogEntry(term, index, msg_type, payload)
                self.log.append(entry)
                waiter = _ApplyWaiter()
                self._apply_waiters[index] = (term, waiter)
        if forward:
            return self.transport.forward_apply(leader, msg_type, payload)

        # Actively drive replication while waiting: a dropped round
        # otherwise stalls the commit until the next heartbeat tick.
        deadline = time.monotonic() + APPLY_TIMEOUT
        self._broadcast_heartbeat()
        while not waiter.event.wait(0.05):
            if time.monotonic() > deadline:
                with self._lock:
                    self._apply_waiters.pop(index, None)
                raise TimeoutError(f"apply of index {index} timed out")
            self._broadcast_heartbeat()
        if not waiter.committed:
            # A different leader committed a different entry here.
            raise NotLeaderError(self.leader_id)
        return index

    def _run_apply(self) -> None:
        while not self._stop.is_set():
            applied_any = False
            with self._lock:
                while self.last_applied < self.commit_index:
                    self.last_applied += 1
                    entry = self._entry_at(self.last_applied)
                    waiting = self._apply_waiters.pop(self.last_applied, None)
                    if entry is not None:
                        try:
                            self.fsm_apply(entry.index, entry.msg_type, entry.payload)
                        except Exception:
                            self.logger.exception(
                                "fsm apply failed at %d", entry.index
                            )
                    if waiting is not None:
                        expected_term, waiter = waiting
                        # Only ack the waiter if OUR entry committed; a
                        # different term means the write was lost.
                        waiter.committed = (
                            entry is not None and entry.term == expected_term
                        )
                        waiter.event.set()
                    applied_any = True
            if not applied_any:
                time.sleep(0.005)

    # ------------------------------------------------------------------

    def is_leader(self) -> bool:
        with self._lock:
            return self.state == LEADER

    def last_index(self) -> int:
        with self._lock:
            return self.last_applied

    def barrier(self) -> int:
        return self.last_index()

    def stats(self) -> dict:
        with self._lock:
            return {
                "state": self.state,
                "term": self.current_term,
                "leader": self.leader_id,
                "commit_index": self.commit_index,
                "last_applied": self.last_applied,
                "log_len": len(self.log),
            }


class RaftLog:
    """Adapter giving RaftNode the DevLog interface the Server uses.
    Forwarded writes wait for the local FSM to catch up so endpoint code
    can read its own writes (the reference forwards whole RPCs to the
    leader, which reads there; here only the log write forwards)."""

    def __init__(self, node: RaftNode):
        self.node = node

    def apply(self, msg_type: str, payload: Any) -> int:
        index = self.node.apply(msg_type, payload)
        deadline = time.monotonic() + APPLY_TIMEOUT
        while self.node.last_index() < index:
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"local fsm did not reach index {index} in time"
                )
            time.sleep(0.002)
        return index

    def last_index(self) -> int:
        return self.node.last_index()

    def barrier(self) -> int:
        return self.node.barrier()
