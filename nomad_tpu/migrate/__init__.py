"""Churn control plane: bounded migration parallelism + priority
preemption policy (ROADMAP item 3, SURVEY.md build-plan stages 6-7).

Two churn workflows live behind this module:

- **Migration budget** (:class:`MigrationGovernor`) — the analog of the
  reference's drain ``max_parallel``: a process-global bound on how
  many displaced allocations may be *in flight* (claimed by a
  scheduling attempt but not yet committed/released) at once. A
  100-node drain storm displaces hundreds of allocs in one broker
  wave; without the budget every eval evicts-and-places its whole
  migrate set simultaneously and the replacement placements thundering-
  herd the plan queue. With it, each eval claims up to the remaining
  budget, defers the rest to a follow-up ``migration`` eval, and
  releases its claim when its plan submit finishes — so concurrent
  in-flight migrations never exceed ``max_parallel`` (the chaos soak's
  bound) while the storm still drains in waves instead of stalling.

- **Preemption policy** — the host-side half of the dense preemption
  pass (ops/preempt.py): eligibility (enabled + red pressure + eval
  priority above the threshold), the victim-selection oracle the
  differential rig judges the kernel against, and the commit counters
  bench --preempt-ab reads.

Both are process-global and lock-guarded, like the breaker and the
resident-state tracker (one device path / one leader per process);
``configure()`` is called from Server init with the ServerConfig knobs
and never resets counters.

Chaos sites (nomad_tpu/chaos):

- ``drain.mid_migration`` — fired at the top of a scheduler's migrate
  leg ('error' = the eval dies mid-migration and must redeliver with
  no eviction committed; 'delay' = a slow migration wave).
- ``preempt.victim_lost`` — fired per victim at preemption commit
  ('drop' = the victim is NOT staged in the plan while its freed
  capacity was already counted by the kernel — the plan applier's
  exact verification must reject the node and force a replan).
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional

# Default in-flight migration budget (ServerConfig.migrate_max_parallel
# overrides; 0 = unbounded). 32 keeps a 100-node drain storm to a few
# waves without letting it flood the plan queue.
DEFAULT_MAX_PARALLEL = 32

# Evals must outrank this to preempt (strictly greater). The default
# job priority is 50, so out of the box only above-normal-priority
# work may evict.
DEFAULT_PREEMPT_PRIORITY = 50

# Wait stamped on budget-deferred follow-up migration evals: long
# enough that the claiming wave's submits have freed slots by the time
# the broker re-delivers, short enough that a drain storm's tail wave
# is not operator-visible latency.
MIGRATE_RETRY_WAIT = 0.05


def check_migration_chaos(eval_id: str = "") -> None:
    """Host-side fault gate for the migration leg, called by the
    generic scheduler before it claims budget and stages evictions.
    Armed with a ``drain.mid_migration`` 'error' spec it raises
    ChaosInjectedError exactly where a mid-migration crash would
    surface — before any eviction is staged, so the redelivered eval
    replans from clean state (the exactly-once-terminal contract the
    drain soak asserts). A no-op two-attribute check in production."""
    from ..chaos import chaos

    if chaos.enabled:
        chaos.fire("drain.mid_migration", eval_id=eval_id)


class MigrationGovernor:
    """Bounded migration parallelism, shared by every scheduling
    worker in the process."""

    def __init__(self, max_parallel: int = DEFAULT_MAX_PARALLEL):
        self._lock = threading.Lock()
        self.max_parallel = max_parallel  # guarded-by: _lock (0 = off)
        self.in_flight = 0  # guarded-by: _lock
        self.high_water = 0  # guarded-by: _lock
        self.granted_total = 0  # guarded-by: _lock
        self.deferred_total = 0  # guarded-by: _lock
        self.released_total = 0  # guarded-by: _lock

    def configure(self, max_parallel: Optional[int] = None) -> None:
        with self._lock:
            if max_parallel is not None:
                self.max_parallel = int(max_parallel)

    def acquire(self, n: int) -> int:
        """Claim up to ``n`` migration slots; returns the grant (which
        may be 0 — the caller defers the remainder to a follow-up
        migration eval). Unbounded (max_parallel <= 0) grants all of
        ``n`` but still tracks in-flight/high-water for observability."""
        if n <= 0:
            return 0
        with self._lock:
            if self.max_parallel <= 0:
                granted = n
            else:
                granted = max(0, min(n, self.max_parallel - self.in_flight))
            self.in_flight += granted
            self.high_water = max(self.high_water, self.in_flight)
            self.granted_total += granted
            self.deferred_total += n - granted
            return granted

    def reset_stats(self) -> None:
        """Re-baseline the observability counters (high-water mark,
        grant/defer/release totals) WITHOUT touching in-flight claims —
        tests and bench arms measure a window, and a lifetime max would
        smear earlier windows into it."""
        with self._lock:
            self.high_water = self.in_flight
            self.granted_total = 0
            self.deferred_total = 0
            self.released_total = 0

    def release(self, n: int) -> None:
        """Return ``n`` slots (the claiming attempt's plan submit
        finished — committed or failed; either way those migrations
        are no longer in flight at the scheduler)."""
        if n <= 0:
            return
        with self._lock:
            self.in_flight = max(0, self.in_flight - n)
            self.released_total += n

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "max_parallel": self.max_parallel,
                "in_flight": self.in_flight,
                "high_water": self.high_water,
                "granted_total": self.granted_total,
                "deferred_total": self.deferred_total,
                "released_total": self.released_total,
            }


class _PreemptPolicy:
    """Process-global preemption switchboard + counters."""

    def __init__(self):
        self._lock = threading.Lock()
        self.enabled = False  # guarded-by: _lock
        self.priority_threshold = DEFAULT_PREEMPT_PRIORITY  # guarded-by: _lock
        # Pressure probe: () -> "green"|"yellow"|"red". Server init
        # points this at its admission controller; tests force it.
        # None = no signal = never preempt (preemption is an overload
        # valve, not a default placement strategy).
        self.pressure_probe: Optional[Callable[[], str]] = None  # guarded-by: _lock
        self.evictions_staged = 0  # guarded-by: _lock
        self.evictions_committed = 0  # guarded-by: _lock
        self.placements = 0  # guarded-by: _lock

    def configure(self, enabled: Optional[bool] = None,
                  priority_threshold: Optional[int] = None,
                  pressure_probe: Optional[Callable[[], str]] = None) -> None:
        with self._lock:
            if enabled is not None:
                self.enabled = bool(enabled)
            if priority_threshold is not None:
                self.priority_threshold = int(priority_threshold)
            if pressure_probe is not None:
                self.pressure_probe = pressure_probe

    def eligible(self, eval_priority: int) -> bool:
        with self._lock:
            if not self.enabled:
                return False
            if eval_priority <= self.priority_threshold:
                return False
            probe = self.pressure_probe
        if probe is None:
            return False
        try:
            return probe() == "red"
        except Exception:  # noqa: BLE001 - a broken probe must not fail evals
            return False

    def note(self, staged: int = 0, committed: int = 0,
             placements: int = 0) -> None:
        with self._lock:
            self.evictions_staged += staged
            self.evictions_committed += committed
            self.placements += placements

    def stats(self) -> Dict[str, object]:
        with self._lock:
            return {
                "enabled": self.enabled,
                "priority_threshold": self.priority_threshold,
                "evictions_staged": self.evictions_staged,
                "evictions_committed": self.evictions_committed,
                "placements": self.placements,
            }


_governor = MigrationGovernor()
_policy = _PreemptPolicy()


def get_governor() -> MigrationGovernor:
    return _governor


def configure(migrate_max_parallel: Optional[int] = None,
              preemption_enabled: Optional[bool] = None,
              preempt_priority_threshold: Optional[int] = None,
              pressure_probe: Optional[Callable[[], str]] = None) -> None:
    """Server-init configuration funnel (mirrors breaker/resident/
    kernels: last explicit configuration wins, counters survive)."""
    _governor.configure(max_parallel=migrate_max_parallel)
    _policy.configure(enabled=preemption_enabled,
                      priority_threshold=preempt_priority_threshold,
                      pressure_probe=pressure_probe)


def preemption_eligible(eval_priority: int) -> bool:
    """Whether this eval may run the dense preemption pass: preemption
    is on, the cluster reads red (the PR 5 admission signal), and the
    eval outranks the threshold. Checked AFTER normal placement failed
    — preemption is the last resort, never the first choice."""
    return _policy.eligible(eval_priority)


def note_preemption(staged: int, placements: int = 0) -> None:
    """Scheduler-side accounting: victims staged into a plan and the
    placements they enabled."""
    _policy.note(staged=staged, placements=placements)


def note_preemption_committed(n: int) -> None:
    """Plan-applier-side accounting: victims whose eviction actually
    committed through the raft funnel (bench --check compares this to
    the staged count to refuse numbers with lost evictions)."""
    if n > 0:
        _policy.note(committed=n)


def select_victims_host(allocs: List, needed, max_priority: int,
                        limit: Optional[int] = None) -> Optional[List]:
    """The CPU victim-selection oracle: lowest-priority-first prefix of
    a node's live allocations that frees at least ``needed`` (cpu, mem,
    disk, iops) — exactly what the dense pass's prefix-of-sorted-
    candidates selection computes on device. Returns the victim list,
    or None when even evicting every eligible alloc cannot free enough.
    Used by the host fallback path and judged against the kernel by
    the differential rig."""
    eligible = sorted(
        (a for a in allocs
         if not a.terminal_status() and victim_priority(a) < max_priority),
        key=victim_sort_key)
    if limit is not None:
        eligible = eligible[:limit]
    freed = [0.0, 0.0, 0.0, 0.0]
    victims: List = []
    for a in eligible:
        if all(f >= n for f, n in zip(freed, needed)):
            break
        r = _alloc_res(a)
        for i in range(4):
            freed[i] += r[i]
        victims.append(a)
    if all(f >= n for f, n in zip(freed, needed)):
        return victims
    return None


def victim_priority(alloc) -> int:
    """An allocation's preemption rank: its job's priority (the stored
    alloc carries the job denormalized; a stripped copy defends with
    the default)."""
    return alloc.job.priority if alloc.job is not None else 50


def victim_sort_key(alloc):
    """Deterministic lowest-priority-first victim order (ties broken
    oldest-first then by id, so the dense tensor and the host oracle
    agree on the exact prefix)."""
    return (victim_priority(alloc), alloc.create_index, alloc.id)


def _alloc_res(alloc):
    tr = alloc.task_resources or {}
    cpu = mem = iops = 0.0
    disk = (alloc.shared_resources.disk_mb
            if alloc.shared_resources is not None else 0.0)
    for r in tr.values():
        cpu += r.cpu
        mem += r.memory_mb
        disk += r.disk_mb
        iops += r.iops
    return (cpu, mem, disk, iops)


def preempt_stats() -> Dict[str, object]:
    return _policy.stats()


def churn_stats() -> Dict[str, object]:
    """The ``server.stats()["churn"]`` payload: migration budget +
    preemption counters in one place."""
    out: Dict[str, object] = {"migration": _governor.stats()}
    out["preemption"] = _policy.stats()
    return out


__all__ = [
    "DEFAULT_MAX_PARALLEL",
    "DEFAULT_PREEMPT_PRIORITY",
    "MIGRATE_RETRY_WAIT",
    "MigrationGovernor",
    "check_migration_chaos",
    "churn_stats",
    "configure",
    "get_governor",
    "note_preemption",
    "note_preemption_committed",
    "preempt_stats",
    "preemption_eligible",
    "select_victims_host",
    "victim_priority",
    "victim_sort_key",
]
