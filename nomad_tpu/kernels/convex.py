"""Convex-relaxation bin-packing placement kernel (CvxCluster-style).

The greedy kernel (ops/binpack.py) places the K asks of an eval
SEQUENTIALLY: each step argmaxes the masked BestFit score against the
carried state. That is the reference's semantics, but it is myopic —
step j cannot see asks j+1..K, and the tie-break noise that
decorrelates concurrent evals also scatters placements across
near-equal nodes, stranding capacity in fragments (the Tesserae
fragmentation axis the quality scoreboard measures).

This kernel solves the JOINT problem first, then rounds:

1. **Relax** the node-per-ask assignment to a simplex-constrained
   program: x[k, :] is a distribution over the N nodes for ask k.
   The objective trades three terms —
   the per-(ask, node) BestFit affinity computed once at the initial
   state; a quadratic penalty on EXPECTED over-capacity (cpu/mem/disk/
   iops + bandwidth + ports under the relaxed loads), which is what
   makes the K asks repel each other away from jointly-overcommitted
   nodes; and a concentration reward on expected per-node load that
   pulls asks onto already-utilized (and shared) nodes — the
   anti-fragmentation pressure a sequential argmax cannot express.

2. **Solve** with a fixed-iteration mirror-descent loop: gradient
   ascent on logits with x = softmax(logits) is exactly entropic
   projection onto the simplex, the projection structure CvxCluster
   exploits (PAPERS.md: first-order relaxations run 100-1000x faster
   than exact solvers and vectorize natively). `lax.scan` over
   SOLVE_ITERS keeps the loop inside one XLA program; shapes are the
   caller's buckets, so steady-state recompiles stay 0.

3. **Round** with the greedy repair scan, score-biased by the relaxed
   solution: each step's feasibility mask (`_score_and_mask` — the
   SAME mask the greedy kernel and the CPU oracle enforce) guarantees
   capacity/bandwidth/ports/distinct-hosts/constraint validity at the
   carried state, and ROUND_BIAS * x[k] steers the argmax toward the
   relaxation's choice. An ask whose relaxed node no longer fits
   falls through to the next-best FEASIBLE node — the repair pass.
   Validity is therefore structurally identical to greedy: the
   relaxation can only change WHICH feasible node wins, never whether
   an infeasible one does (kernels/differential.py asserts this
   against the CPU oracle).

Pure and transform-safe: vmap-able over the batch axis, scan-able
under pre_resolve, exactly like the greedy program — the batcher's
overlay/compact/fused-delta paths ride unchanged.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..ops.binpack import (
    NEG_INF,
    NUM_RESOURCES,
    R_CPU,
    R_MEM,
    Asks,
    NodeState,
    PlacementConfig,
    _score_and_mask,
)

# Fixed solver iteration count: compile-time constant so the mirror-
# descent loop is ONE lax.scan inside the cached program. 12 steps
# converges the storm shapes (K <= 64) well past rounding precision —
# the gradient is CLOSED-FORM (below), so each step is a couple of
# [K,N]/[N,R] contractions, not an autodiff replay.
SOLVE_ITERS = 12
# Mirror-descent step on the logits. The affinity term is in fitness
# units (0..18); 0.35 crosses that range in a handful of steps without
# oscillating against the quadratic penalty.
SOLVE_STEP = 0.35
# Weight of the quadratic expected-overcapacity penalty, per
# normalized resource dimension. Large enough that one fully
# overcommitted dimension (load ratio 1 over capacity) dominates the
# whole affinity range.
OVER_PENALTY = 60.0
# Concentration (anti-fragmentation) reward on expected per-node load:
# pulls asks toward already-utilized nodes and toward sharing nodes
# with each other, up against the overcapacity penalty.
PACK_REWARD = 6.0
# Rounding bias: how many fitness points the relaxation's preference
# is worth in the repair scan's argmax. Bounded, so a NEG_INF
# (infeasible) mask can never be overridden; comparable to the
# BestFit dynamic range, so the relaxation decides ties and near-ties
# while a grossly worse node still loses.
ROUND_BIAS = 8.0
# Strand-awareness in the repair scan: fitness-point cost of leaving a
# node with free capacity that no longer fits this ask (normalized
# waste fraction x this weight). BestFit is blind to the ask quantum —
# it prefers the TIGHTEST feasible node even when the remainder
# strands (headroom 1.6x ask beats 2.0x ask, wasting 0.6 of an ask) —
# and the tie-break noise randomizes choices within ~2 fitness points
# besides. This term is what turns the rounding into a
# fragmentation-aware repair pass; it biases WITHIN the feasible set
# only, so validity is untouched.
STRAND_BIAS = 12.0


def mirror_descent(logits, lin, mask, res_active, bw_active, ports_active,
                   base_frac, base_bw_frac, denom_nr, bw_denom, ports_denom,
                   active, iters: int):
    """The entropic mirror-descent loop on the relaxed joint-assignment
    objective, factored out so the hot-path kernel (`_relaxed_assignment`)
    and the off-path defrag solver (nomad_tpu/defrag/solver.py) run the
    SAME program — the defrag loop warm-starts it from the previous
    round's logits, which is where the CvxCluster-style re-solve
    speedup comes from. `iters` must be a compile-time constant (the
    loop is UNROLLED: at these shapes a lax.scan's per-iteration
    dispatch overhead on CPU backends outweighs the whole body, and
    the flat graph fuses). Returns the final logits (the iterate the
    warm start carries)."""
    for _ in range(iters):
        x = jax.nn.softmax(logits + mask, axis=1) * active
        exp_load = base_frac + jnp.einsum("kn,kr->nr", x,
                                          res_active) / denom_nr
        over = jnp.maximum(exp_load - 1.0, 0.0)
        over_bw = jnp.maximum(
            base_bw_frac + (x.T @ bw_active) / bw_denom - 1.0, 0.0)
        over_ports = jnp.maximum(
            (x.T @ ports_active) / ports_denom - 1.0, 0.0)
        tot = jnp.sum(exp_load, axis=1) / NUM_RESOURCES
        node_term = (PACK_REWARD / NUM_RESOURCES) * tot[:, None] \
            - 2.0 * OVER_PENALTY * over  # [N, R]: d obj / d exp_load
        g = (lin
             + jnp.einsum("nr,kr->kn", node_term / denom_nr, res_active)
             - 2.0 * OVER_PENALTY
             * (jnp.outer(bw_active, over_bw / bw_denom)
                + jnp.outer(ports_active, over_ports / ports_denom)))
        logits = logits + SOLVE_STEP * g
    return logits


def _relaxed_assignment(state: NodeState, asks: Asks,
                        config: PlacementConfig):
    """Solve the simplex-relaxed joint assignment; returns x [K, N]
    (rows of inactive asks are meaningless and ignored downstream)."""
    g = state.feasible.shape[1]

    # -------- static per-(ask, node) structure, computed once --------
    tg_onehots = (jnp.arange(g)[None, :]
                  == asks.tg_index[:, None])  # [K, G]
    feas = (jnp.take(state.feasible, asks.tg_index, axis=1).T
            & state.node_ok[None, :])  # [K, N]
    # Initial-state resource fit, one [K, N] plane per dimension (the
    # [K, N, R] broadcast would be ~0.5GB at the top buckets).
    headroom = state.capacity - state.util  # [N, R]
    for r in range(NUM_RESOURCES):
        feas &= asks.resources[:, r][:, None] <= headroom[None, :, r]
    feas &= (asks.bw[:, None]
             <= (state.bw_avail - state.bw_used)[None, :])
    feas &= asks.ports[:, None] <= state.ports_free[None, :]
    tg_cnt = jnp.einsum("ng,kg->kn", state.tg_count,
                        tg_onehots.astype(state.tg_count.dtype))
    tg_dhs = jnp.take(asks.tg_distinct_hosts, asks.tg_index)  # [K]
    feas &= jnp.where(asks.job_distinct_hosts,
                      state.job_count[None, :] == 0, True)
    feas &= jnp.where(tg_dhs[:, None], tg_cnt == 0, True)

    # BestFit affinity at the initial state (ScoreFit on the post-
    # placement free fractions, anti-affinity included) — the linear
    # term of the objective.
    denom_nr = jnp.maximum(state.sched_capacity, 1.0)  # [N, R]
    free_cpu = 1.0 - (state.util[None, :, R_CPU]
                      + asks.resources[:, None, R_CPU]) / denom_nr[None, :, R_CPU]
    free_mem = 1.0 - (state.util[None, :, R_MEM]
                      + asks.resources[:, None, R_MEM]) / denom_nr[None, :, R_MEM]
    fitness = 20.0 - (jnp.power(10.0, free_cpu)
                      + jnp.power(10.0, free_mem))
    fitness = jnp.clip(fitness, 0.0, 18.0)
    fitness = jnp.where(
        (state.sched_capacity[None, :, R_CPU] <= 0)
        | (state.sched_capacity[None, :, R_MEM] <= 0),
        0.0, fitness)
    affinity = fitness - (config.anti_affinity_penalty
                          * state.job_count.astype(jnp.float32)[None, :])

    active = asks.active.astype(jnp.float32)[:, None]  # [K, 1]
    mask = jnp.where(feas, 0.0, NEG_INF)  # [K, N]

    # Expectation terms stay [K,R] x [K,N] -> [N,R] contractions — the
    # [K,N,R] broadcast they replace is ~0.5GB at the top buckets.
    # Normalizing by schedulable capacity puts every dimension (and
    # every node size) on one scale so OVER_PENALTY means the same
    # thing at 1 core as at 64.
    res_active = asks.resources * active  # [K, R]
    bw_active = asks.bw * active[:, 0]  # [K]
    ports_active = asks.ports * active[:, 0]  # [K]
    base_frac = state.util / denom_nr
    bw_denom = jnp.maximum(state.bw_avail, 1.0)
    base_bw_frac = state.bw_used / bw_denom
    ports_denom = jnp.maximum(state.ports_free, 1.0)
    lin = jnp.where(feas, affinity, 0.0)

    # Entropic mirror descent (exponentiated gradient) with the
    # CLOSED-FORM gradient:
    #
    #   obj(x) = <x, lin>
    #            - OVER_PENALTY * (|over|^2 + |over_bw|^2 + |over_p|^2)
    #            + PACK_REWARD/2 * |tot|^2
    #
    # with exp_load = base_frac + (x^T res)/denom (per node/dim),
    # over = relu(exp_load - 1), tot = mean_r exp_load, so
    #
    #   d obj/d x[k,n] = lin[k,n]
    #     + sum_r (PACK_REWARD/R * tot[n] - 2*OVER_PENALTY*over[n,r])
    #             * res[k,r]/denom[n,r]
    #     - 2*OVER_PENALTY * (over_bw[n]*bw[k]/bw_denom[n] + ports...)
    #
    # The MD step on the simplex is x <- x*exp(step*g) renormalized =
    # logits += step*g under softmax — NOT the Euclidean chain rule
    # x*(g - <x,g>), which stalls exactly when x is still diffuse.
    # The shared loop lives in mirror_descent() (the defrag solver
    # warm-starts the same program across rounds).
    logits = mirror_descent(
        lin, lin, mask, res_active, bw_active, ports_active,
        base_frac, base_bw_frac, denom_nr, bw_denom, ports_denom,
        active, SOLVE_ITERS)  # init at the objective's own linear term
    return jax.nn.softmax(logits + mask, axis=1)


def convex_placement_program(state: NodeState, asks: Asks, key,
                             config: PlacementConfig):
    """Drop-in for ops/binpack.placement_program (PlacementConfig.
    kernel == "convex"): relaxed joint solve, then the feasibility-
    mask-respecting rounding scan. Returns (choices [K] int32,
    scores [K] f32, final_state)."""
    x = _relaxed_assignment(state, asks, config)

    k_count = asks.resources.shape[0]
    n = state.util.shape[0]
    g = state.feasible.shape[1]
    noise = jax.random.uniform(
        key, (k_count, n), minval=0.0, maxval=config.noise_scale)
    tg_onehots = (jnp.arange(g)[None, :]
                  == asks.tg_index[:, None])  # [K, G]
    feas_rows = (jnp.take(state.feasible, asks.tg_index, axis=1).T
                 & state.node_ok[None, :])  # [K, N]
    tg_dhs = jnp.take(asks.tg_distinct_hosts, asks.tg_index)  # [K]

    # Rounding preference, max-normalized (raw softmax mass spreads
    # over N nodes — the RELATIVE ordering is the signal). Two parts:
    # the ask's own row, and the relaxation's AGGREGATE node mass
    # y[n] = sum_k x[k,n] — the node SET the joint solve decided to
    # fill. The aggregate is what breaks the identical-asks
    # degeneracy: symmetric asks get symmetric rows (the LP cannot
    # order them), but their SUM marks how much total load the solve
    # wants on each node, and the sequential repair scan then packs
    # that set in order, falling to the next-preferred node exactly
    # when the carried state stops fitting.
    y = jnp.sum(x, axis=0)
    pref = (x / (jnp.max(x, axis=1, keepdims=True) + 1e-9)
            + y[None, :] / (jnp.max(y) + 1e-9)) * 0.5

    def body(carry, xs):
        (ask_res, ask_bw, ask_ports, feas_row, tg_onehot, tg_dh, active,
         noise_row, pref_row) = xs
        # The SAME mask/score the greedy kernel and the oracle enforce,
        # evaluated at the CARRIED state — feasibility here is exact.
        score = _score_and_mask(
            carry, ask_res, ask_bw, ask_ports, feas_row, tg_onehot,
            asks.job_distinct_hosts, tg_dh, config, noise_row)
        # Strand lookahead (see STRAND_BIAS): what this placement
        # leaves behind on each node, in ask-quanta. Nodes whose
        # post-placement headroom still fits another such ask (or is
        # ~zero) cost nothing; a remainder in (0, ask) is waste,
        # weighted by its normalized size over the dimensions the ask
        # actually uses.
        head = carry.capacity - carry.util - ask_res[None, :]  # [N, R]
        fits_another = jnp.all(head >= ask_res[None, :], axis=1)
        used_dim = (ask_res > 0).astype(jnp.float32)  # [R]
        waste = (jnp.maximum(head, 0.0)
                 / jnp.maximum(carry.sched_capacity, 1.0)) @ used_dim \
            / jnp.maximum(jnp.sum(used_dim), 1.0)
        strand_pen = jnp.where(fits_another, 0.0, waste)
        bias = ROUND_BIAS * pref_row - STRAND_BIAS * strand_pen
        biased = score + bias
        choice = jnp.argmax(biased)
        valid = (biased[choice] > NEG_INF / 2) & active
        # Reported score excludes the tie-break noise AND the
        # relaxation bias: AllocMetric carries the node's actual
        # BestFit fitness, comparable across kernels.
        clean_score = score[choice] - noise_row[choice]

        safe = jnp.where(valid, choice, n)  # row n: OOB-drop no-op
        new_state = carry._replace(
            util=carry.util.at[safe].add(ask_res, mode="drop"),
            bw_used=carry.bw_used.at[safe].add(ask_bw, mode="drop"),
            ports_free=carry.ports_free.at[safe].add(
                -ask_ports, mode="drop"),
            job_count=carry.job_count.at[safe].add(1, mode="drop"),
            tg_count=carry.tg_count.at[safe].add(
                tg_onehot.astype(jnp.int32), mode="drop"),
        )
        out_choice = jnp.where(valid, choice, -1).astype(jnp.int32)
        out_score = jnp.where(valid, clean_score, 0.0)
        return new_state, (out_choice, out_score)

    final_state, (choices, scores) = jax.lax.scan(
        body,
        state,
        (asks.resources, asks.bw, asks.ports, feas_rows, tg_onehots,
         tg_dhs, asks.active, noise, pref),
    )
    return choices, scores, final_state
