"""Kernel-agnostic placement-quality scoreboard.

Throughput (bench.py's evals/s columns) says how FAST a kernel
places; nothing measured how WELL. This module scores committed
placement decisions on the two axes Tesserae (PAPERS.md) evaluates
placement policies on, plus the queueing axis the admission layer
cares about:

- **fragmentation** — the fraction of the cluster's free cpu+mem
  capacity stranded on nodes that can no longer fit a reference ask
  (free capacity you own but cannot sell). 0 = every free node still
  fits the ask; 1 = all remaining headroom is unusable fragments.
- **binpack_score** — mean fill fraction (max of cpu/mem) over the
  OCCUPIED schedulable nodes: how tightly the used part of the
  cluster is packed. Higher = tighter (BestFit's goal, measured).
- **queueing_delay_ms** — p99 time placement work spent QUEUED
  rather than computed/committed, measured at whichever queue the
  harness has: on a live server that is the broker (the flight
  recorder's ``broker.wait`` p99, what ``snapshot()`` reports); the
  broker-less bench e2e harness measures its queue, the batcher
  (``device.dispatch`` p99 minus ``device.solve`` p99).

All three are computed from COMMITTED state — the dense schedulers
feed the board from the post-placement claimed arrays right after
appending to the plan (the applier re-verifies, so emitted == applied
modulo the conflict retries the pipeline stats already count), and
``quality_from_store`` recomputes from a live/oracle state store for
bench columns and tests. The board never touches the state store and
never blocks: bounded ring of samples under one leaf lock.

Surfaces: ``server.stats()["placement_quality"]``, ``/v1/metrics``
gauges (``placement_quality.*``), and bench.py's
fragmentation/binpack_score/queueing_delay_ms columns + --kernel-ab.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

import numpy as np

# Samples kept per kernel (ring, drop-oldest): enough for a stable
# median over a storm, bounded so a long-lived server never grows.
SAMPLE_CAP = 512
# Steady-state sampling rate: scoring costs O(N) host work (a [N,4]
# copy + a few full-array passes), which at 10k nodes x 64 concurrent
# evals is real GIL time on the scheduler hot path — and a 512-sample
# median needs nowhere near every eval. The first WARM_SAMPLES evals
# per kernel always score (fast feedback on fresh servers / bench
# arms); after that, 1 in SAMPLE_EVERY.
WARM_SAMPLES = 64
SAMPLE_EVERY = 8


def quality_from_arrays(util, capacity, node_ok, ask_res) -> Dict[str, float]:
    """Score one committed cluster state. `util`/`capacity` are the
    dense [N, R] arrays (reserved included in util, exactly the kernel
    accounting), `node_ok` the [N] readiness mask, `ask_res` the [R]
    reference ask fragmentation is measured against (a job's task-group
    ask). Returns {"fragmentation", "binpack_score"}."""
    util = np.asarray(util, np.float64)
    capacity = np.asarray(capacity, np.float64)
    node_ok = np.asarray(node_ok, bool)
    ask_res = np.asarray(ask_res, np.float64)

    real = node_ok & (capacity[:, 0] > 0)
    if not real.any():
        return {"fragmentation": 0.0, "binpack_score": 0.0}
    cap = capacity[real]
    use = np.minimum(util[real], cap)
    free = cap - use

    # Fragmentation: free cpu+mem stranded on nodes that cannot fit
    # the reference ask on EVERY dimension it asks for.
    fits = np.ones(len(cap), bool)
    for r in range(len(ask_res)):
        if ask_res[r] > 0:
            fits &= free[:, r] >= ask_res[r]
    weight = free[:, 0] / max(cap[:, 0].max(), 1.0) + \
        free[:, 1] / max(cap[:, 1].max(), 1.0)
    total_free = float(weight.sum())
    stranded = float(weight[~fits].sum())
    fragmentation = stranded / total_free if total_free > 0 else 0.0

    # Bin-pack utilization: mean max(cpu, mem) fill over occupied
    # nodes (nodes carrying any cpu or mem load beyond zero).
    frac = use[:, :2] / np.maximum(cap[:, :2], 1.0)
    occupied = frac.max(axis=1) > 1e-9
    binpack = float(frac[occupied].max(axis=1).mean()) if occupied.any() \
        else 0.0
    return {"fragmentation": fragmentation, "binpack_score": binpack}


def quality_from_store(state, job) -> Dict[str, float]:
    """Recompute the scoreboard metrics from a state store snapshot
    (bench columns for host-path configs; differential-rig checks).
    `job`'s first task group is the reference ask."""
    from ..structs import allocs_fit

    nodes = [n for n in state.nodes()]
    n = len(nodes)
    util = np.zeros((n, 4), np.float64)
    capacity = np.zeros((n, 4), np.float64)
    node_ok = np.zeros(n, bool)
    for i, node in enumerate(nodes):
        r = node.resources
        capacity[i] = (r.cpu, r.memory_mb, r.disk_mb, r.iops)
        node_ok[i] = node.ready()
        live = [a for a in state.allocs_by_node(node.id)
                if not a.terminal_status()]
        _fit, _dim, used = allocs_fit(node, live)
        util[i] = (used.cpu, used.memory_mb, used.disk_mb, used.iops)
    return quality_from_arrays(
        util, capacity, node_ok, reference_ask(job))


def slice_fragmentation(util, capacity, node_ok, topo_ids, ask_res,
                        k: int) -> float:
    """Gang-scheduling quality axis (nomad_tpu/gang): the fraction of
    the cluster's free cpu+mem capacity stranded in topology groups
    that can no longer fit a WHOLE gang of ``k`` members asking
    ``ask_res`` — node-level fragmentation's analog at rack/ICI
    granularity. 0 = every group's free capacity is gang-usable; 1 =
    all remaining headroom sits in groups too fragmented (or too
    small) for any gang. Nodes with topo id < 0 count as stranded for
    gangs (they can never prove slice contiguity)."""
    util = np.asarray(util, np.float64)
    capacity = np.asarray(capacity, np.float64)
    node_ok = np.asarray(node_ok, bool)
    topo_ids = np.asarray(topo_ids, np.int64)
    ask = np.asarray(ask_res, np.float64)

    real = node_ok & (capacity[:, 0] > 0)
    if not real.any():
        return 0.0
    cap = capacity[real]
    use = np.minimum(util[real], cap)
    free = cap - use
    ids = topo_ids[: len(node_ok)][real]

    # Per-node member units from free capacity (ops/gang.py
    # _member_units, resource dims only).
    units = np.full(len(cap), np.inf)
    for r in range(min(len(ask), cap.shape[1])):
        if ask[r] > 0:
            units = np.minimum(units, np.floor(free[:, r] / ask[r]))
    units = np.where(np.isfinite(units), np.maximum(units, 0.0), 0.0)

    weight = free[:, 0] / max(cap[:, 0].max(), 1.0) + \
        free[:, 1] / max(cap[:, 1].max(), 1.0)
    total = float(weight.sum())
    if total <= 0:
        return 0.0
    stranded = float(weight[ids < 0].sum())
    for gid in np.unique(ids[ids >= 0]):
        sel = ids == gid
        if units[sel].sum() < k:
            stranded += float(weight[sel].sum())
    return stranded / total


def slice_frag_from_store(state, job, tg, level: str = "rack") -> float:
    """slice_fragmentation recomputed from a state-store snapshot (the
    bench --gang-ab column and rig checks). ``tg`` is the gang task
    group whose member ask and count parameterize the axis."""
    from ..models.topology import TOPOLOGY_META_KEYS
    from ..structs import allocs_fit

    key = TOPOLOGY_META_KEYS[level]
    nodes = list(state.nodes())
    n = len(nodes)
    util = np.zeros((n, 4), np.float64)
    capacity = np.zeros((n, 4), np.float64)
    node_ok = np.zeros(n, bool)
    topo = np.full(n, -1, np.int64)
    interned = {}
    for i, node in enumerate(nodes):
        r = node.resources
        capacity[i] = (r.cpu, r.memory_mb, r.disk_mb, r.iops)
        node_ok[i] = node.ready()
        value = node.meta.get(key)
        if value:
            topo[i] = interned.setdefault(value, len(interned))
        live = [a for a in state.allocs_by_node(node.id)
                if not a.terminal_status()]
        _fit, _dim, used = allocs_fit(node, live)
        util[i] = (used.cpu, used.memory_mb, used.disk_mb, used.iops)
    ask = np.zeros(4, np.float64)
    for task in tg.tasks:
        r = task.resources
        ask += (r.cpu, r.memory_mb, r.disk_mb, r.iops)
    if tg.ephemeral_disk:
        ask[2] += tg.ephemeral_disk.size_mb
    return slice_fragmentation(util, capacity, node_ok, topo, ask,
                               tg.count)


def reference_ask(job) -> np.ndarray:
    """[R] cpu/mem/disk/iops ask of the job's first task group — the
    fragmentation reference."""
    ask = np.zeros(4, np.float64)
    if job is None or not job.task_groups:
        return ask
    tg = job.task_groups[0]
    for task in tg.tasks:
        r = task.resources
        ask += (r.cpu, r.memory_mb, r.disk_mb, r.iops)
    if tg.ephemeral_disk:
        ask[2] += tg.ephemeral_disk.size_mb
    return ask


class QualityBoard:
    """Bounded per-kernel sample board. note_plan() is called on the
    scheduler hot path right after a dense plan's placements are
    appended: one leaf lock around ring bookkeeping, no allocation
    proportional to anything unbounded, never blocks."""

    def __init__(self):
        self._lock = threading.Lock()
        # kernel -> preallocated rings (fragmentation, binpack) +
        # write cursor; slot = count mod SAMPLE_CAP. guarded-by: _lock
        self._rings: Dict[str, list] = {}
        # kernel -> should_sample tick count. guarded-by: _lock
        self._ticks: Dict[str, int] = {}
        # Rolling-window marks (reset_window): kernel -> sample count
        # at the last reset, so window_snapshot() reads only samples
        # that landed SINCE — the defrag trajectory on /v1/metrics
        # without client-side delta math. guarded-by: _lock
        self._window_marks: Dict[str, int] = {}
        # broker.wait histogram snapshot at the last reset (count,
        # buckets) for the windowed queueing p99. guarded-by: _lock
        self._queue_mark = None

    def should_sample(self, kernel: str) -> bool:
        """Whether this eval should pay the O(N) scoring cost (see
        WARM_SAMPLES/SAMPLE_EVERY): callers check BEFORE computing the
        claimed state, so skipped evals cost two dict ops."""
        with self._lock:
            tick = self._ticks.get(kernel, 0)
            self._ticks[kernel] = tick + 1
        return tick < WARM_SAMPLES or tick % SAMPLE_EVERY == 0

    def note_plan(self, kernel: str, fragmentation: float,
                  binpack: float) -> None:
        with self._lock:
            ent = self._rings.get(kernel)
            if ent is None:
                ent = [np.zeros(SAMPLE_CAP), np.zeros(SAMPLE_CAP), 0]
                self._rings[kernel] = ent
            slot = ent[2] % SAMPLE_CAP
            ent[0][slot] = fragmentation
            ent[1][slot] = binpack
            ent[2] += 1

    def reset(self) -> None:
        with self._lock:
            self._rings.clear()
            self._ticks.clear()
            self._window_marks.clear()
            self._queue_mark = None

    def reset_window(self) -> None:
        """Start a fresh rolling window (reset_stats()-style, like the
        migration governor's): marks every kernel's current sample
        cursor and snapshots the broker-wait histogram. The telemetry
        loop calls this each emission interval, so the window gauges on
        /v1/metrics read per-interval medians — the axis the defrag
        trajectory is judged on — while the lifetime medians and the
        Prometheus counters stay monotonic."""
        marks = self._queue_marks_now()
        with self._lock:
            for kernel, ent in self._rings.items():
                self._window_marks[kernel] = ent[2]
            self._queue_mark = marks

    @staticmethod
    def _queue_marks_now():
        from .. import trace

        return trace.get_recorder().stage_buckets("broker.wait")

    def window_snapshot(self, reset: bool = False) -> Dict[str, dict]:
        """Per-kernel medians over samples since the last
        reset_window() (capped at the ring size), plus the windowed
        broker-wait queueing p99. A kernel with no window samples is
        omitted — a gauge repeating a stale median would fake a flat
        trajectory."""
        from ..utils.metrics import hist_percentile

        with self._lock:
            items = [(k, ent[0].copy(), ent[1].copy(), ent[2],
                      self._window_marks.get(k, 0))
                     for k, ent in self._rings.items()]
            queue_mark = self._queue_mark
        out: Dict[str, dict] = {}
        kernels: Dict[str, dict] = {}
        for kernel, frag, binp, count, mark in items:
            n_window = min(count - mark, SAMPLE_CAP, count)
            if n_window <= 0:
                continue
            # The window's slots are the n_window newest writes:
            # cursor positions [count - n_window, count) mod cap.
            slots = (np.arange(count - n_window, count) % SAMPLE_CAP)
            kernels[kernel] = {
                "fragmentation": round(float(np.median(frag[slots])), 4),
                "binpack_score": round(float(np.median(binp[slots])), 4),
                "samples": int(n_window),
            }
        out["kernels"] = kernels
        cur = self._queue_marks_now()
        queueing = 0.0
        if cur is not None:
            count, buckets = cur
            if queue_mark is not None:
                m_count, m_buckets = queue_mark
                count -= m_count
                buckets = [b - mb for b, mb in zip(buckets, m_buckets)]
            if count > 0:
                queueing = hist_percentile(buckets, count, 0.99)
        out["queueing_delay_ms"] = round(float(queueing), 3)
        if reset:
            self.reset_window()
        return out

    def snapshot(self) -> Dict[str, dict]:
        """Per-kernel medians + sample counts, plus the queueing-delay
        p99 from the flight recorder (one number — queueing happens
        before a kernel is chosen, so it is cluster-wide)."""
        from .. import trace

        out: Dict[str, dict] = {}
        with self._lock:
            items = [(k, ent[0].copy(), ent[1].copy(), ent[2])
                     for k, ent in self._rings.items()]
        for kernel, frag, binp, count in items:
            n = min(count, SAMPLE_CAP)
            if not n:
                continue
            out[kernel] = {
                "fragmentation": round(float(np.median(frag[:n])), 4),
                "binpack_score": round(float(np.median(binp[:n])), 4),
                "samples": count,
            }
        stages = trace.get_recorder().stage_stats()
        wait = stages.get("broker.wait", {})
        return {
            "kernels": out,
            "queueing_delay_ms": round(float(wait.get("p99_ms", 0.0)), 3),
        }


_global: Optional[QualityBoard] = None
_global_lock = threading.Lock()


def get_board() -> QualityBoard:
    global _global
    with _global_lock:
        if _global is None:
            _global = QualityBoard()
        return _global
