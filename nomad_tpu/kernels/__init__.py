"""Pluggable dense placement kernels (ROADMAP item 4).

A *placement kernel* is the per-batch solve at the heart of the dense
scheduler: a pure function with `ops/binpack.py placement_program`'s
exact signature —

    kernel(state: NodeState, asks: Asks, key, config: PlacementConfig)
        -> (choices [K] int32, scores [K] f32, final_state)

`placement_program` dispatches to the registered kernel named by
``PlacementConfig.kernel`` (a static/compile-time field, so every
kernel gets its own cached XLA program and rides the batcher's
overlay / compact / pre-resolve / fused-delta paths unchanged — the
kernel swaps only HOW the solve is computed, never how batches form,
how bases become device-resident, or how plans commit).

Selection surfaces:

- ``placement_kernel`` config knob (ServerConfig + agent HCL
  ``server.placement_kernel`` + CLI), validated at server init so a
  typo fails loudly before the first eval;
- scheduler factory registry: every kernel K also registers
  ``service-K-tpu`` / ``batch-K-tpu`` factories
  (scheduler/__init__.py), pinning that kernel per scheduler type the
  same way ``scheduler_factories`` routes evals.

Built-ins: ``greedy`` (the sequential masked-argmax scan in
ops/binpack.py — the BestFit-v3 reference reformulation) and
``convex`` (kernels/convex.py — a CvxCluster-style convex-relaxation
bin-packer: simplex-relaxed assignment solved by a fixed-iteration
jitted mirror-descent loop, then rounded by a feasibility-mask-
respecting repair scan).

Validity contract: a kernel may trade placement QUALITY, never
VALIDITY — the oracle differential rig (kernels/differential.py) runs
every registered kernel against the sequential CPU oracle on
randomized clusters and asserts feasibility, capacity, and
plan-apply acceptance. The quality scoreboard (kernels/quality.py)
measures the trade: fragmentation, bin-pack utilization, queueing
delay.

Preemption interplay (ops/preempt.py): the dense priority-preemption
pass is NOT part of the kernel contract — kernels place into free
capacity only. When a red-pressure, outranking eval's kernel solve
leaves asks unplaced, the scheduler runs the separate preemption
program over a fresh matrix (its own compiled entry point, greedy
scoring) regardless of which kernel failed first; evictions commit
through the plan's verified node_preemptions leg either way. A kernel
therefore never needs victim-awareness to stay correct under
preemption — it just sees the post-eviction capacity on the replan.

This module stays JAX-free at import time (the scheduler package and
server init import it; only the dense dispatch path may pull in jax):
kernel programs register as LAZY loaders resolved on first dispatch.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List

# The kernel ops/binpack.py implements natively: `placement_program`
# runs its own scan when config.kernel == DEFAULT_KERNEL, so the
# default entry needs no loader.
DEFAULT_KERNEL = "greedy"

# name -> zero-arg loader returning the kernel program (lazy: loading
# pulls in jax). guarded-by: _LOCK
_LOADERS: Dict[str, Callable[[], Callable]] = {}
# name -> resolved program (memoized loads). guarded-by: _LOCK
_PROGRAMS: Dict[str, Callable] = {}
# Sorted name tuple, rebuilt on registration: kernel_names() sits on
# the per-eval routing path (worker.host_factory), so reads are a
# lock-free immutable-ref load. guarded-by: _LOCK (writes)
_NAMES: tuple = ()
_LOCK = threading.Lock()
# Process-global active kernel, set by kernels.configure() from
# ServerConfig.placement_kernel (process-global like the batcher's
# device cache and the breaker: one device path per process).
_ACTIVE = DEFAULT_KERNEL  # guarded-by: _LOCK


def _load_greedy():
    # The native sequential masked-argmax program. Calling it through
    # the registry is equivalent to calling it directly: its dispatch
    # branch is a no-op when config.kernel == DEFAULT_KERNEL.
    from ..ops.binpack import placement_program

    return placement_program


def _load_convex():
    from .convex import convex_placement_program

    return convex_placement_program


def register_kernel(name: str, loader: Callable[[], Callable]) -> None:
    """Register a placement kernel under `name`. `loader` is a
    zero-arg callable returning the kernel program (resolved lazily on
    first dispatch so registration never imports jax). Third-party
    kernels register here and become selectable through every surface
    (placement_kernel knob, `service-<name>-tpu` factories, bench
    --kernel-ab)."""
    if not name or "-" in name:
        # Kernel names embed into factory names ("service-<k>-tpu") and
        # host_factory() strips them back out; a dash would make that
        # mapping ambiguous.
        raise ValueError(
            f"invalid kernel name {name!r}: non-empty, no dashes")
    if name == DEFAULT_KERNEL and DEFAULT_KERNEL in _LOADERS:
        # placement_program runs the native scan for the default name
        # without consulting the registry — accepting a replacement
        # loader here would silently never run it.
        raise ValueError(
            f"the native {DEFAULT_KERNEL!r} kernel cannot be replaced; "
            f"register under a new name")
    global _NAMES
    with _LOCK:
        _LOADERS[name] = loader
        _PROGRAMS.pop(name, None)
        _NAMES = tuple(sorted(_LOADERS))


register_kernel(DEFAULT_KERNEL, _load_greedy)
register_kernel("convex", _load_convex)


def kernel_names() -> List[str]:
    # Lock-free: _NAMES is an immutable tuple swapped atomically on
    # registration (this sits on the per-eval routing path).
    return list(_NAMES)


def kernel_program(name: str) -> Callable:
    """Resolve a kernel name to its program (loading it on first use).
    `placement_program` calls this for every non-default kernel."""
    with _LOCK:
        prog = _PROGRAMS.get(name)
        loader = _LOADERS.get(name)
    if prog is not None:
        return prog
    if loader is None:
        raise ValueError(
            f"unknown placement kernel {name!r} "
            f"(registered: {', '.join(kernel_names())})")
    prog = loader()
    with _LOCK:
        _PROGRAMS[name] = prog
    return prog


def validate(kernel: str) -> None:
    """Raise ValueError unless `kernel` is registered — server init
    calls this so a typo'd ``placement_kernel`` fails at startup, not
    at the first eval."""
    if kernel not in _NAMES:
        raise ValueError(
            f"unknown placement kernel {kernel!r} "
            f"(registered: {', '.join(_NAMES)})")


def configure(kernel: str = None) -> None:
    """Set the process-global active kernel (the one `*-tpu` factories
    without an explicit kernel use). Raises ValueError on an unknown
    name. Like the breaker and resident-state globals this is
    process-wide — the LAST explicit configuration wins; Server init
    therefore only calls this for a non-default ``placement_kernel``
    (a second default-configured server in the process must not
    silently flip an explicitly-configured one back to greedy)."""
    global _ACTIVE
    if kernel is None:
        return
    validate(kernel)
    with _LOCK:
        _ACTIVE = kernel


def active_kernel() -> str:
    with _LOCK:
        return _ACTIVE


__all__ = [
    "DEFAULT_KERNEL",
    "active_kernel",
    "configure",
    "kernel_names",
    "kernel_program",
    "register_kernel",
    "validate",
]
