"""Oracle differential rig: any registered kernel vs the sequential
CPU oracle.

The validity contract (kernels/__init__.py): a placement kernel may
trade placement QUALITY but never VALIDITY. This rig is the
enforcement — for a spread of seeded randomized clusters (mixed
resource shapes, pre-existing load, datacenter/rack constraints,
distinct-hosts, drained nodes) it runs one evaluation through the
kernel-under-test's scheduler factory (``service-<kernel>-tpu`` /
``batch-<kernel>-tpu`` — the same registry seam production selection
uses) against the scheduler test Harness, then has the ORACLE judge
every placement the kernel emitted:

- **plan-apply accepted** — ``server.plan_apply.evaluate_node_plan``
  (the live applier's per-node verification, plan_apply.go:318) must
  accept every node the plan touches against the pre-eval snapshot;
- **capacity never exceeded** — ``allocs_fit`` over each node's
  proposed set (existing live allocs minus evictions plus the plan's
  placements);
- **feasibility** — every chosen node individually passes the HOST
  iterator stack (``GenericStack.select`` pinned to that node on a
  fresh context): constraints, drivers, readiness — the oracle's own
  feasibility chain, not the dense mask's;
- **distinct-hosts honored** — no two allocs of the job (or of a
  distinct-hosts task group) share a node, counting pre-existing
  live allocs;
- the eval itself completes (no crash-and-nack).

The oracle's own run on an identical cluster is recorded alongside
(placed counts) so quality drift is visible in the report, but count
parity is deliberately NOT asserted — that is the quality axis the
scoreboard measures, not the validity axis this rig enforces.

bench.py --check consumes ``run_differential`` and refuses to report
kernel numbers whose rig is red; tests/test_kernels.py sweeps it
property-style.
"""

from __future__ import annotations

import random
from typing import Dict, List, Optional

DEFAULT_SEEDS = range(7000, 7012)


def build_scenario(seed: int):
    """(seed_state_fn, job) for one rig case. Counts stay >= 4 so the
    dense bulk path engages (the dense schedulers route <= 3
    placements to the host iterators — a rig case that never reached
    the kernel would vacuously pass)."""
    from .. import mock
    from ..structs import Constraint, consts

    rng = random.Random(seed)
    n_nodes = rng.choice([6, 9, 17, 33])
    dc_count = rng.choice([1, 2])
    use_networks = rng.random() < 0.4
    use_racks = rng.random() < 0.5
    distinct = rng.random() < 0.4
    preload = rng.random() < 0.5
    drain_frac = rng.choice([0.0, 0.0, 0.2, 0.4])
    job_type = rng.choice(["service", "batch"])
    count = rng.choice([4, 6, 11, 24])
    cpu = rng.choice([100, 333, 900])
    mem = rng.choice([64, 300, 700])

    nodes = []
    for i in range(n_nodes):
        node = mock.node()
        node.datacenter = f"dc{i % dc_count + 1}"
        if use_racks:
            node.meta["rack"] = f"r{i % 4}"
        if i % 3 == 0:  # heterogeneous capacity: some nodes half-size
            node.resources.cpu //= 2
            node.resources.memory_mb //= 2
        node.compute_class()
        nodes.append(node)
    drained = [n.id for n in nodes[: int(n_nodes * drain_frac)]]

    filler_allocs = []
    if preload:
        filler = mock.job()
        filler.id = "filler"
        for i, node in enumerate(nodes):
            if i % 2:
                continue
            a = mock.alloc()
            a.node_id, a.job_id, a.job = node.id, filler.id, filler
            a.desired_status = consts.ALLOC_DESIRED_RUN
            a.client_status = consts.ALLOC_CLIENT_RUNNING
            for tr in a.task_resources.values():
                tr.cpu = rng.choice([200, 700])
                tr.memory_mb = rng.choice([128, 512])
                tr.networks = []
            a.resources = None
            filler_allocs.append(a)

    def seed_state(h, job):
        # All store writes route through the oracle's sanctioned
        # fixture funnel (scheduler/testing.py seed_harness_cluster):
        # kernels/ never touches the state store directly — the
        # ntalint raft-funnel self-check asserts exactly that.
        from ..scheduler.testing import seed_harness_cluster

        seed_harness_cluster(h, nodes=nodes, allocs=filler_allocs,
                             jobs=[job.copy()], drained=drained)

    job = mock.job()
    job.type = job_type
    job.datacenters = [f"dc{d + 1}" for d in range(dc_count)]
    tg = job.task_groups[0]
    tg.count = count
    task = tg.tasks[0]
    task.resources.cpu = cpu
    task.resources.memory_mb = mem
    if not use_networks:
        task.resources.networks = []
    if use_racks and rng.random() < 0.5:
        job.constraints.append(Constraint(
            ltarget="${meta.rack}", operand="regexp", rtarget="^r[01]$"))
    if distinct:
        job.constraints.append(
            Constraint(operand=consts.CONSTRAINT_DISTINCT_HOSTS))
    return seed_state, job


def _oracle_feasible(snap, job, tg, node) -> bool:
    """The HOST feasibility chain's verdict on one node for one task
    group: a fresh single-node iterator stack must yield it."""
    from ..scheduler.context import EvalContext
    from ..scheduler.stack import GenericStack
    from ..structs import Plan

    ctx = EvalContext(snap, Plan(job=job), rng=random.Random(0))
    stack = GenericStack(job.type == "batch", ctx)
    stack.set_job(job)
    stack.set_nodes([node])
    option, _ = stack.select(tg)
    return option is not None


def _check_case(kernel: str, seed: int) -> List[str]:
    """Run one rig case; returns the list of violation strings."""
    from ..scheduler.testing import Harness
    from ..server.plan_apply import evaluate_node_plan
    from ..structs import allocs_fit, consts, new_eval, remove_allocs

    seed_state, job = build_scenario(seed)
    factory = f"{job.type}-{kernel}-tpu"

    h = Harness(seed=seed)
    seed_state(h, job)
    snap = h.state.snapshot()
    h.process(factory, new_eval(
        h.state.job_by_id(job.id), consts.EVAL_TRIGGER_JOB_REGISTER))

    bad: List[str] = []
    if not h.evals or h.evals[-1].status != consts.EVAL_STATUS_COMPLETE:
        status = h.evals[-1].status if h.evals else "<none>"
        bad.append(f"seed {seed}: eval did not complete ({status})")

    job_dh = any(c.operand == consts.CONSTRAINT_DISTINCT_HOSTS
                 for c in job.constraints)
    tg_by_name = {tg.name: tg for tg in job.task_groups}
    for plan in h.plans:
        for node_id, placed in plan.node_allocation.items():
            node = snap.node_by_id(node_id)
            if node is None:
                bad.append(f"seed {seed}: placed on unknown node "
                           f"{node_id}")
                continue
            # Plan-apply acceptance: the live applier's verification.
            if not evaluate_node_plan(snap, plan, node_id):
                bad.append(f"seed {seed}: plan-apply rejected node "
                           f"{node_id}")
            # Capacity: proposed set must fit (the applier's AllocsFit,
            # spelled out so the failing dimension is named).
            existing = snap.allocs_by_node_terminal(node_id, False)
            updates = plan.node_update.get(node_id, [])
            proposed = remove_allocs(existing, updates) + placed
            for a in proposed:
                if a.job is None:
                    a.job = plan.job
            fit, dim, _ = allocs_fit(node, proposed)
            if not fit:
                bad.append(f"seed {seed}: capacity exceeded on "
                           f"{node_id}: {dim}")
            # Oracle feasibility + distinct-hosts per placement.
            this_job_live = [
                a for a in existing
                if a.job_id == job.id and not a.terminal_status()]
            for alloc in placed:
                tg = tg_by_name.get(alloc.task_group)
                if tg is None:
                    bad.append(f"seed {seed}: alloc names unknown task "
                               f"group {alloc.task_group!r}")
                    continue
                if not _oracle_feasible(snap, job, tg, node):
                    bad.append(
                        f"seed {seed}: oracle rejects node {node_id} "
                        f"for tg {tg.name} (kernel placed there)")
                tg_dh = any(
                    c.operand == consts.CONSTRAINT_DISTINCT_HOSTS
                    for c in tg.constraints)
                if job_dh and (len(placed) + len(this_job_live)) > 1:
                    bad.append(f"seed {seed}: distinct_hosts (job) "
                               f"violated on {node_id}")
                    break
                if tg_dh:
                    same_tg = ([a for a in placed
                                if a.task_group == tg.name]
                               + [a for a in this_job_live
                                  if a.task_group == tg.name])
                    if len(same_tg) > 1:
                        bad.append(f"seed {seed}: distinct_hosts (tg "
                                   f"{tg.name}) violated on {node_id}")
                        break
    return bad


def _oracle_placed(seed: int) -> int:
    """The sequential oracle's placed count on the identical cluster
    (report context, not an assertion)."""
    from ..scheduler.testing import Harness
    from ..structs import consts, new_eval

    seed_state, job = build_scenario(seed)
    h = Harness(seed=seed)
    seed_state(h, job)
    h.process(job.type, new_eval(
        h.state.job_by_id(job.id), consts.EVAL_TRIGGER_JOB_REGISTER))
    return len(h.state.allocs_by_job(job.id))


def run_differential(kernel: str, seeds=DEFAULT_SEEDS,
                     with_oracle_counts: bool = False) -> Dict:
    """Run the rig for one kernel across `seeds`. Returns a report:
    {"kernel", "cases", "violations": [...], "green": bool,
     "placed": {seed: (kernel_placed, oracle_placed)}? }."""
    from ..scheduler.testing import Harness  # noqa: F401 (fail fast on import)

    violations: List[str] = []
    placed: Dict[int, tuple] = {}
    for seed in seeds:
        violations.extend(_check_case(kernel, seed))
        if with_oracle_counts:
            from ..structs import consts, new_eval

            seed_state, job = build_scenario(seed)
            h = Harness(seed=seed)
            seed_state(h, job)
            h.process(f"{job.type}-{kernel}-tpu", new_eval(
                h.state.job_by_id(job.id),
                consts.EVAL_TRIGGER_JOB_REGISTER))
            placed[seed] = (len(h.state.allocs_by_job(job.id)),
                            _oracle_placed(seed))
    report = {
        "kernel": kernel,
        "cases": len(list(seeds)),
        "violations": violations,
        "green": not violations,
    }
    if with_oracle_counts:
        report["placed"] = placed
    return report


def assert_differential(kernel: str, seeds=DEFAULT_SEEDS) -> None:
    report = run_differential(kernel, seeds)
    assert report["green"], (
        f"kernel {kernel!r} failed the oracle differential:\n"
        + "\n".join(report["violations"]))


# ------------------------------------------------- migration-plan judge
#
# PR 14 extends the rig from judging PLACEMENTS to judging eviction+
# placement MIGRATION plans — the legs a defrag wave (nomad_tpu/defrag)
# or a drain storm stages. The CPU oracle re-verifies what the live
# plan applier verifies, spelled out so a failing wave names its sin.


def judge_migration_plan(snap, plan, seed=None) -> List[str]:
    """Violations in one migration plan's legs against the pre-eval
    snapshot: every eviction victim (node_update stops + the
    preemption leg) must EXIST, be NON-TERMINAL, and live on the node
    its leg names; evicting it must actually free its accounted
    capacity (the post-eviction used vector shrinks by exactly the
    victim's usage); and every placement must fit its node WITH the
    plan's own evictions discounted (allocs_fit over the proposed
    set) and pass plan-apply verification."""
    from ..models.matrix import _alloc_usage
    from ..server.plan_apply import evaluate_node_plan
    from ..structs import allocs_fit, remove_allocs

    tag = f"seed {seed}: " if seed is not None else ""
    bad: List[str] = []
    evict_nodes = set(plan.node_update) | set(plan.node_preemptions)
    for node_id in sorted(evict_nodes):
        node = snap.node_by_id(node_id)
        if node is None:
            bad.append(f"{tag}eviction leg names unknown node {node_id}")
            continue
        victims = (plan.node_update.get(node_id, [])
                   + plan.node_preemptions.get(node_id, []))
        existing = snap.allocs_by_node_terminal(node_id, False)
        by_id = {a.id: a for a in existing}
        freeable = []
        for victim in victims:
            stored = snap.alloc_by_id(victim.id)
            if stored is None:
                bad.append(f"{tag}victim {victim.id} does not exist")
                continue
            if stored.terminal_status():
                bad.append(f"{tag}victim {victim.id} already terminal "
                           f"({stored.desired_status}/"
                           f"{stored.client_status})")
                continue
            if stored.node_id != node_id:
                bad.append(f"{tag}victim {victim.id} is on node "
                           f"{stored.node_id}, leg claims {node_id}")
                continue
            if victim.id in by_id:
                freeable.append(by_id[victim.id])
        # Capacity actually freed: used(before) - used(after removal)
        # must equal the victims' accounted usage per dimension — a
        # victim whose eviction frees nothing (double-listed, already
        # gone) would let a placement ride phantom capacity.
        _f0, _d0, used_before = allocs_fit(node, existing)
        remaining = remove_allocs(existing, freeable)
        _f1, _d1, used_after = allocs_fit(node, remaining)
        want = [0.0] * 4
        for a in freeable:
            cpu, mem, disk, iops, _bw, _p = _alloc_usage(a)
            want[0] += cpu
            want[1] += mem
            want[2] += disk
            want[3] += iops
        got = (used_before.cpu - used_after.cpu,
               used_before.memory_mb - used_after.memory_mb,
               used_before.disk_mb - used_after.disk_mb,
               used_before.iops - used_after.iops)
        if any(abs(g - w) > 1e-6 for g, w in zip(got, want)):
            bad.append(f"{tag}node {node_id}: evictions freed {got}, "
                       f"accounting claims {tuple(want)}")
    for node_id, placed in plan.node_allocation.items():
        node = snap.node_by_id(node_id)
        if node is None:
            bad.append(f"{tag}placed on unknown node {node_id}")
            continue
        if not evaluate_node_plan(snap, plan, node_id):
            bad.append(f"{tag}plan-apply rejected node {node_id}")
        existing = snap.allocs_by_node_terminal(node_id, False)
        updates = (plan.node_update.get(node_id, [])
                   + plan.node_preemptions.get(node_id, []))
        proposed = remove_allocs(existing, updates) + placed
        for a in proposed:
            if a.job is None:
                a.job = plan.job
        fit, dim, _ = allocs_fit(node, proposed)
        if not fit:
            bad.append(f"{tag}capacity exceeded on {node_id}: {dim}")
    return bad


# ------------------------------------------------------ gang-plan judge
#
# Gang scheduling (nomad_tpu/gang) extends the rig a third time: from
# placements and migration plans to ALL-OR-NOTHING gang plans. The CPU
# oracle re-verifies the atomicity contract itself, not just per-node
# validity — a partially-staged gang is a violation even if every
# member individually fits.


def judge_gang_plan(snap, plan, job, seed=None) -> List[str]:
    """Violations in one plan's gang legs against the pre-eval
    snapshot: per gang task group, the plan stages ALL count members
    or NONE (and the gang_groups leg names exactly the staged ids);
    slice gangs land inside ONE topology group; spread gangs respect
    the per-group cap; every member's node passes plan-apply
    verification and fits with its CO-SCHEDULED gang members (and the
    plan's evictions) discounted; every member's node passes the host
    oracle's feasibility chain."""
    from ..gang import (
        gang_distinct_hosts,
        gang_key,
        gang_mode,
        gang_task_groups,
        spread_cap,
    )
    from ..gang.host import estimate_member_units
    from ..models.topology import TOPOLOGY_META_KEYS
    from ..ops.gang import GANG_MODE_SLICE, GANG_MODE_SPREAD
    from ..server.plan_apply import evaluate_node_plan
    from ..structs import allocs_fit, remove_allocs

    tag = f"seed {seed}: " if seed is not None else ""
    bad: List[str] = []
    placed_by_node = plan.node_allocation
    for tg in gang_task_groups(job):
        k = tg.count
        key = gang_key(job.id, tg.name)
        members = [(node_id, a)
                   for node_id, placed in placed_by_node.items()
                   for a in placed
                   if a.job_id == job.id and a.task_group == tg.name]
        # All-K-or-none.
        if members and len(members) != k:
            bad.append(f"{tag}gang {key}: staged {len(members)} of {k} "
                       "members (partial gang)")
        # The atomicity leg must name exactly the staged members —
        # an unlisted member would silently escape whole-gang reject.
        leg = set(plan.gang_groups.get(key, ()))
        ids = {a.id for _n, a in members}
        if members and leg != ids:
            bad.append(f"{tag}gang {key}: gang_groups leg names "
                       f"{len(leg)} ids, plan stages {len(ids)}")
        if not members:
            continue
        mode, level = gang_mode(tg.gang)
        meta_key = TOPOLOGY_META_KEYS.get(level, "rack")
        if mode == GANG_MODE_SLICE:
            groups = set()
            for node_id, _a in members:
                node = snap.node_by_id(node_id)
                value = node.meta.get(meta_key) if node else None
                if not value:
                    bad.append(f"{tag}gang {key}: member on {node_id} "
                               f"which has no {meta_key!r} meta — "
                               "contiguity unprovable")
                else:
                    groups.add(value)
            if len(groups) > 1:
                bad.append(f"{tag}gang {key}: slice spans "
                           f"{sorted(groups)} — not contiguous")
        if mode == GANG_MODE_SPREAD:
            dh = gang_distinct_hosts(job, tg)
            groups_all: dict = {}
            for node in snap.nodes():
                # the same ready + datacenter filter BOTH scheduler
                # legs group by — counting foreign-DC groups as
                # eligible would shrink the cap below what the legs
                # lawfully used and convict a correct plan
                if not node.ready() \
                        or node.datacenter not in job.datacenters:
                    continue
                g = node.meta.get(meta_key) or f"__node__{node.id}"
                groups_all.setdefault(g, []).append(node)
            eligible = sum(
                1 for nodes in groups_all.values()
                if any(estimate_member_units(snap, None, n, tg, dh) >= 1
                       for n in nodes))
            cap = spread_cap(k, eligible)
            counts: dict = {}
            for node_id, _a in members:
                node = snap.node_by_id(node_id)
                g = ((node.meta.get(meta_key) if node else None)
                     or f"__node__{node_id}")
                counts[g] = counts.get(g, 0) + 1
            for g, got in counts.items():
                if got > cap:
                    bad.append(f"{tag}gang {key}: spread cap {cap} "
                               f"exceeded in group {g!r} ({got})")
        # Per-node: plan-apply acceptance + capacity with co-scheduled
        # members (they are all in node_allocation) and evictions
        # discounted + host-oracle feasibility.
        for node_id in sorted({n for n, _a in members}):
            node = snap.node_by_id(node_id)
            if node is None:
                bad.append(f"{tag}gang {key}: member on unknown node "
                           f"{node_id}")
                continue
            if not evaluate_node_plan(snap, plan, node_id):
                bad.append(f"{tag}gang {key}: plan-apply rejected "
                           f"node {node_id}")
            existing = snap.allocs_by_node_terminal(node_id, False)
            updates = (plan.node_update.get(node_id, [])
                       + plan.node_preemptions.get(node_id, []))
            proposed = (remove_allocs(existing, updates)
                        + placed_by_node.get(node_id, []))
            for a in proposed:
                if a.job is None:
                    a.job = plan.job
            fit, dim, _ = allocs_fit(node, proposed)
            if not fit:
                bad.append(f"{tag}gang {key}: capacity exceeded on "
                           f"{node_id}: {dim}")
            if not _oracle_feasible(snap, job, tg, node):
                bad.append(f"{tag}gang {key}: oracle rejects node "
                           f"{node_id}")
    return bad


def build_gang_scenario(seed: int):
    """(seed_state_fn, job) for one gang rig case: a topology cluster
    (racks of 4, ICI pairs inside racks) with optional preload/drains,
    and a gang job whose mode sweeps slice/spread/affinity/free."""
    from .. import mock
    from ..structs import Gang, consts

    rng = random.Random(seed)
    n_nodes = rng.choice([12, 16, 24])
    preload = rng.random() < 0.5
    drain_frac = rng.choice([0.0, 0.0, 0.15])
    mode = rng.choice(["slice", "slice", "spread", "affinity", "free"])
    k = rng.choice([3, 4, 6])
    cpu = rng.choice([400, 700])
    mem = rng.choice([256, 512])

    nodes = []
    for i in range(n_nodes):
        node = mock.node()
        node.resources.cpu = 3000
        node.resources.memory_mb = 3000
        node.meta["rack"] = f"r{i // 4}"
        node.meta["ici"] = f"r{i // 4}-ici{(i % 4) // 2}"
        node.compute_class()
        nodes.append(node)
    if mode == "slice" and rng.random() < 0.3:
        # Some topology-less nodes: slice gangs must never land there.
        for node in nodes[-2:]:
            node.meta.pop("rack", None)
            node.meta.pop("ici", None)
            node.compute_class()
    drained = [n.id for n in nodes[: int(n_nodes * drain_frac)]]

    filler_allocs = []
    if preload:
        filler = mock.job()
        filler.id = "gang-filler"
        for i, node in enumerate(nodes):
            if i % 3:
                continue
            a = mock.alloc()
            a.node_id, a.job_id, a.job = node.id, filler.id, filler
            a.desired_status = consts.ALLOC_DESIRED_RUN
            a.client_status = consts.ALLOC_CLIENT_RUNNING
            for tr in a.task_resources.values():
                tr.cpu = rng.choice([500, 1500])
                tr.memory_mb = rng.choice([400, 1200])
                tr.networks = []
            a.resources = None
            filler_allocs.append(a)

    def seed_state(h, job):
        from ..scheduler.testing import seed_harness_cluster

        seed_harness_cluster(h, nodes=nodes, allocs=filler_allocs,
                             jobs=[job.copy()], drained=drained)

    job = mock.job()
    job.id = f"gang-{seed}"
    job.datacenters = [nodes[0].datacenter]
    tg = job.task_groups[0]
    tg.count = k
    tg.gang = Gang(
        slice="rack" if mode == "slice" else "",
        spread="rack" if mode == "spread" else "",
        affinity="rack" if mode == "affinity" else "",
    )
    task = tg.tasks[0]
    task.resources.cpu = cpu
    task.resources.memory_mb = mem
    if rng.random() < 0.5:
        task.resources.networks = []
    if rng.random() < 0.3:
        from ..structs import Constraint

        tg.constraints.append(
            Constraint(operand=consts.CONSTRAINT_DISTINCT_HOSTS))
    return seed_state, job


GANG_SEEDS = range(9200, 9208)


def run_gang_differential(seeds=GANG_SEEDS,
                          factory_suffix: str = "-tpu") -> Dict:
    """Drive gang evals through the dense factory on seeded topology
    clusters and have the oracle judge EVERY plan with
    judge_gang_plan, plus the store-level invariant: a gang job's live
    member count is 0 or exactly K — a partially-committed gang in
    the store is the one thing this subsystem exists to prevent."""
    from ..scheduler.testing import Harness
    from ..structs import consts, new_eval

    violations: List[str] = []
    placed_gangs = 0
    for seed in seeds:
        seed_state, job = build_gang_scenario(seed)
        h = Harness(seed=seed)
        seed_state(h, job)
        snap = h.state.snapshot()
        h.process(f"{job.type}{factory_suffix}", new_eval(
            h.state.job_by_id(job.id), consts.EVAL_TRIGGER_JOB_REGISTER))
        for plan in h.plans:
            violations.extend(
                judge_gang_plan(snap, plan, job, seed=seed))
        live = [a for a in h.state.allocs_by_job(job.id)
                if not a.terminal_status()]
        k = job.task_groups[0].count
        if len(live) not in (0, k):
            violations.append(
                f"seed {seed}: store holds {len(live)} of {k} gang "
                "members (partial commit)")
        if len(live) == k:
            placed_gangs += 1
    return {"cases": len(list(seeds)), "placed_gangs": placed_gangs,
            "violations": violations, "green": not violations}


def _defrag_scenario(seed: int):
    """A fragmented service cluster for the defrag differential: mixed
    big/small asks packed tight, then churn-stopped smalls leave
    sub-ask remainders scattered across nodes — the consolidation
    shape the defrag solver exists for."""
    import random as _random

    from ..scheduler.testing import (
        Harness,
        churn_stop_small_allocs,
        seed_consolidation_cluster,
    )

    rng = _random.Random(seed)
    h = Harness(seed=seed)
    # The SHARED fragmentation fixture (scheduler/testing.py): the
    # bench --defrag-ab arm builds the same workload, so the rig and
    # the trajectory always judge one shape.
    seed_consolidation_cluster(h, rng.choice([24, 32]))
    churn_stop_small_allocs(h, rng, 0.35)
    return h


DEFRAG_SEEDS = range(8100, 8106)


def run_defrag_differential(seeds=DEFRAG_SEEDS,
                            factory: str = "service") -> Dict:
    """Drive full defrag waves (solve -> wave evals -> scheduler) on
    seeded fragmented clusters and have the oracle judge EVERY plan a
    wave produced with judge_migration_plan, plus the wave contracts:
    each marked alloc's eviction is exactly-once (one terminal stamp,
    never two), and job alloc counts are preserved (a defrag wave must
    never shrink a service)."""
    from ..defrag import WarmState, build_wave_evals, compute_defrag_plan
    from ..structs import consts

    violations: List[str] = []
    waves = 0
    for seed in seeds:
        h = _defrag_scenario(seed)
        want_live = {
            j.id: len([a for a in h.state.allocs_by_job(j.id)
                       if not a.terminal_status()])
            for j in h.state.jobs()}
        warm = WarmState()
        for _round in range(3):
            snap = h.state.snapshot()
            plan = compute_defrag_plan(
                snap, ["dc1"], max_moves=8, min_gain=0.001, warm=warm)
            if not plan.moves:
                break
            evals = build_wave_evals(snap, plan.moves)
            waves += 1
            for ev in evals:
                # Judge each plan against the snapshot ITS eval ran on:
                # an earlier wave eval's committed eviction legitimately
                # frees the room a later placement uses, and judging the
                # later plan against the wave-START snapshot would read
                # that as phantom overcommit.
                ev_snap = h.state.snapshot()
                seen_plans = len(h.plans)
                h.process(factory, ev)
                for wave_plan in h.plans[seen_plans:]:
                    violations.extend(judge_migration_plan(
                        ev_snap, wave_plan, seed=seed))
            for mv in plan.moves:
                stored = h.state.alloc_by_id(mv.alloc_id)
                if stored is None:
                    violations.append(
                        f"seed {seed}: moved alloc {mv.alloc_id} "
                        "vanished")
                elif stored.desired_status not in (
                        consts.ALLOC_DESIRED_STOP,
                        consts.ALLOC_DESIRED_EVICT):
                    violations.append(
                        f"seed {seed}: moved alloc {mv.alloc_id} "
                        "has no eviction terminal")
        for job_id, want in want_live.items():
            got = len([a for a in h.state.allocs_by_job(job_id)
                       if not a.terminal_status()])
            if got < want:
                violations.append(
                    f"seed {seed}: job {job_id} shrank {want}->{got} "
                    "across defrag waves")
    return {"cases": len(list(seeds)), "waves": waves,
            "violations": violations, "green": not violations}
