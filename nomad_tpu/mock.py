"""Mock fixtures for tests and benchmarks.

Reference: nomad/mock/mock.go:9 (Node), :62 (Job), :157 (SystemJob),
:228 (Eval), :252 (Alloc) — same shapes: a 4GB/3.2GHz node with one
network, a service job with 10 web tasks, etc.
"""

from __future__ import annotations

from .structs import (
    AllocMetric,
    Allocation,
    Constraint,
    EphemeralDisk,
    Evaluation,
    Job,
    LogConfig,
    NetworkResource,
    Node,
    Port,
    Resources,
    RestartPolicy,
    Task,
    TaskGroup,
    UpdateStrategy,
    consts,
)
from .utils.ids import generate_uuid


def node() -> Node:
    n = Node(
        id=generate_uuid(),
        secret_id=generate_uuid(),
        datacenter="dc1",
        name="foobar",
        attributes={
            "kernel.name": "linux",
            "arch": "x86",
            "nomad.version": "0.5.0",
            "driver.exec": "1",
            "driver.mock_driver": "1",
        },
        resources=Resources(
            cpu=4000,
            memory_mb=8192,
            disk_mb=100 * 1024,
            iops=150,
            networks=[
                NetworkResource(
                    device="eth0",
                    cidr="192.168.0.100/32",
                    ip="192.168.0.100",
                    mbits=1000,
                )
            ],
        ),
        reserved=Resources(
            cpu=100,
            memory_mb=256,
            disk_mb=4 * 1024,
            networks=[
                NetworkResource(
                    device="eth0",
                    ip="192.168.0.100",
                    mbits=1,
                    reserved_ports=[Port("ssh", 22)],
                )
            ],
        ),
        links={"consul": "foobar.dc1"},
        meta={"pci-dss": "true"},
        node_class="linux-medium-pci",
        status=consts.NODE_STATUS_READY,
    )
    n.compute_class()
    return n


def job() -> Job:
    j = Job(
        region="global",
        id=generate_uuid(),
        name="my-job",
        type=consts.JOB_TYPE_SERVICE,
        priority=50,
        all_at_once=False,
        datacenters=["dc1"],
        constraints=[Constraint(ltarget="${attr.kernel.name}", rtarget="linux", operand="=")],
        task_groups=[
            TaskGroup(
                name="web",
                count=10,
                ephemeral_disk=EphemeralDisk(size_mb=150),
                restart_policy=RestartPolicy(
                    attempts=3, interval=10 * 60.0, delay=60.0, mode="delay"
                ),
                tasks=[
                    Task(
                        name="web",
                        driver="exec",
                        config={"command": "/bin/date"},
                        env={"FOO": "bar"},
                        log_config=LogConfig(),
                        resources=Resources(
                            cpu=500,
                            memory_mb=256,
                            networks=[
                                NetworkResource(
                                    mbits=50,
                                    dynamic_ports=[Port("http", 0), Port("admin", 0)],
                                )
                            ],
                        ),
                        meta={"foo": "bar"},
                    )
                ],
                meta={"elb_check_type": "http"},
            )
        ],
        meta={"owner": "armon"},
        status=consts.JOB_STATUS_PENDING,
        create_index=42,
        modify_index=99,
        job_modify_index=99,
    )
    j.canonicalize()
    return j


def system_job() -> Job:
    j = Job(
        region="global",
        id=generate_uuid(),
        name="my-job",
        type=consts.JOB_TYPE_SYSTEM,
        priority=100,
        all_at_once=False,
        datacenters=["dc1"],
        constraints=[Constraint(ltarget="${attr.kernel.name}", rtarget="linux", operand="=")],
        task_groups=[
            TaskGroup(
                name="web",
                count=1,
                restart_policy=RestartPolicy(
                    attempts=3, interval=10 * 60.0, delay=60.0, mode="delay"
                ),
                ephemeral_disk=EphemeralDisk(),
                tasks=[
                    Task(
                        name="web",
                        driver="exec",
                        config={"command": "/bin/date"},
                        env={},
                        log_config=LogConfig(),
                        resources=Resources(
                            cpu=500,
                            memory_mb=256,
                            networks=[NetworkResource(mbits=50, dynamic_ports=[Port("http", 0)])],
                        ),
                    )
                ],
            )
        ],
        meta={"owner": "armon"},
        status=consts.JOB_STATUS_PENDING,
        create_index=42,
        modify_index=99,
        job_modify_index=99,
    )
    j.canonicalize()
    return j


def batch_job() -> Job:
    j = job()
    j.type = consts.JOB_TYPE_BATCH
    for tg in j.task_groups:
        tg.restart_policy = RestartPolicy(attempts=0, interval=0.0, delay=0.0, mode="fail")
    return j


def eval() -> Evaluation:
    return Evaluation(
        id=generate_uuid(),
        priority=50,
        type=consts.JOB_TYPE_SERVICE,
        job_id=generate_uuid(),
        status=consts.EVAL_STATUS_PENDING,
    )


def alloc() -> Allocation:
    a = Allocation(
        id=generate_uuid(),
        eval_id=generate_uuid(),
        node_id="12345678-abcd-efab-cdef-123456789abc",
        task_group="web",
        resources=Resources(
            cpu=500,
            memory_mb=256,
            disk_mb=150,
            networks=[
                NetworkResource(
                    device="eth0",
                    ip="192.168.0.100",
                    mbits=50,
                    reserved_ports=[Port("main", 5000)],
                    dynamic_ports=[Port("http", 9876), Port("admin", 9877)],
                )
            ],
        ),
        task_resources={
            "web": Resources(
                cpu=500,
                memory_mb=256,
                networks=[
                    NetworkResource(
                        device="eth0",
                        ip="192.168.0.100",
                        mbits=50,
                        reserved_ports=[Port("main", 5000)],
                        dynamic_ports=[Port("http", 9876), Port("admin", 9877)],
                    )
                ],
            )
        },
        shared_resources=Resources(disk_mb=150),
        metrics=AllocMetric(),
        desired_status=consts.ALLOC_DESIRED_RUN,
        client_status=consts.ALLOC_CLIENT_PENDING,
    )
    j = job()
    a.job = j
    a.job_id = j.id
    a.name = f"{j.id}.web[0]"
    return a
