from .mesh import make_mesh, shard_placement_inputs, sharded_placement

__all__ = ["make_mesh", "shard_placement_inputs", "sharded_placement"]
