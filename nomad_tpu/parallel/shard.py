"""Explicit shard_map programs over the node axis of the resident base.

parallel/mesh.py is the GSPMD half of the scale-out story: annotate
input shardings, let XLA infer the collectives. This module is the
explicit half — shard_map programs whose bodies are written against
LOCAL node-axis slices, for the operations where the collective
structure is part of the contract and must not depend on what the
partitioner infers:

- ``sharded_base_delta``: the resident-base row scatter
  (ops/binpack.py apply_base_delta) with a replicated payload; each
  shard keeps only the rows that land in its slice, so the delta stays
  node-local — zero collectives, and the scattered rows are
  bit-identical to the single-device program's (every shard writes the
  same replicated values, `__graft_entry__.py` proves it at 8 devices).
- ``sharded_group_capacity``: the gang program's topology-group
  scatter-add (ops/gang.py _group_capacity). A gang slice can span
  shards, so each shard scatter-adds its local members into the padded
  group vector and a psum over the node axis assembles the global
  per-group capacity.

No host->device transfer lives here — ntalint's full-matrix-reship
scope covers this module with a ZERO baseline (unlike mesh.py, which is
deliberately out of scope as the placement infrastructure the
sanctioned upload path calls). Callers hand in arrays already placed by
scheduler/batcher.py's rebuild entry point or parallel/mesh.py.

Programs are cached per mesh (and static shape knobs) and registered in
ops/binpack.py's jit accounting via ``shard_cache_size()`` — the
steady-state-recompiles-0 contract covers the sharded programs too.
The factory names are declared in binpack's ``NTA_JIT_ACCOUNTED``
manifest, so ntalint's `unregistered-jit` rule holds this module's
nested ``jax.jit`` sites to that accounting statically
(tests/test_compile_surface.py diffs manifest, AST scan, and the
runtime registry both ways).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Tuple

import jax
import jax.numpy as jnp
from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P

from .mesh import NODE_AXIS

# key -> jitted program; guarded by _PROGRAM_LOCK. One entry per
# (program kind, mesh[, static knob]) — bounded by the process's mesh
# count (one), not by traffic.
_PROGRAMS: Dict[Tuple, object] = {}
_PROGRAM_LOCK = threading.Lock()


def _cached(key: Tuple, build):
    with _PROGRAM_LOCK:
        fn = _PROGRAMS.get(key)
    if fn is not None:
        return fn
    built = build()
    with _PROGRAM_LOCK:
        return _PROGRAMS.setdefault(key, built)


def node_shard_count(mesh) -> int:
    """Shards along the node axis of a parallel/mesh.py mesh."""
    return int(mesh.shape[NODE_AXIS])


def sharded_base_delta(mesh):
    """The shard_map analog of ops/binpack.py apply_base_delta for a
    node-axis-sharded resident base: mutable arrays arrive sharded
    (parallel/mesh.py base_specs), the few-row payload replicated
    (delta_row_specs). Each shard rebases the global row indices into
    its local slice and drops the rest — the scatter never gathers the
    node axis. Padding rows (duplicates of real rows, batcher
    _pad_rows) write identical values, so duplicate indices stay
    deterministic."""

    def build():
        def local(util, bw_used, ports_free, node_ok,
                  rows, util_rows, bw_rows, ports_rows, ok_rows):
            n_local = util.shape[0]
            lo = jax.lax.axis_index(NODE_AXIS) * n_local
            local_rows = rows - lo
            here = (local_rows >= 0) & (local_rows < n_local)
            # Out-of-slice rows route to n_local and drop in the
            # scatter (the same drop idiom as placement_step's invalid
            # placements, ops/binpack.py).
            safe = jnp.where(here, local_rows, n_local)
            return (util.at[safe].set(util_rows, mode="drop"),
                    bw_used.at[safe].set(bw_rows, mode="drop"),
                    ports_free.at[safe].set(ports_rows, mode="drop"),
                    node_ok.at[safe].set(ok_rows, mode="drop"))

        mapped = shard_map(
            local, mesh=mesh,
            in_specs=(P(NODE_AXIS, None), P(NODE_AXIS), P(NODE_AXIS),
                      P(NODE_AXIS), P(), P(None, None), P(), P(), P()),
            out_specs=(P(NODE_AXIS, None), P(NODE_AXIS), P(NODE_AXIS),
                       P(NODE_AXIS)))
        return jax.jit(mapped)

    return _cached(("base_delta", mesh), build)


def sharded_group_capacity(mesh, g_pad: int):
    """The gang program's topology-group scatter-add, shard_mapped:
    per-shard local scatter-add of member units into the padded group
    vector, assembled with a psum over the node axis (a gang slice can
    span shards). ``g_pad`` is a static shape knob (models/topology.py
    topo_group_pad), so one program exists per (mesh, pad bucket)."""

    def build():
        from ..ops.gang import _group_capacity

        def local(units, topo_ids):
            partial = _group_capacity(units, topo_ids, g_pad)
            return jax.lax.psum(partial, NODE_AXIS)

        mapped = shard_map(
            local, mesh=mesh,
            in_specs=(P(NODE_AXIS), P(NODE_AXIS)),
            out_specs=P())
        return jax.jit(mapped)

    return _cached(("group_capacity", mesh, g_pad), build)


def per_shard_occupancy(arrays) -> List[dict]:
    """[{device, rows, bytes}] per shard of a device-resident base
    tuple (or a single array) — the bench's per-shard occupancy and
    device-memory columns. Pure metadata: reads shard layouts, moves
    no data. Single-device arrays report one row."""
    if not isinstance(arrays, (tuple, list)):
        arrays = (arrays,)
    per: Dict[str, dict] = {}
    for j, arr in enumerate(arrays):
        shards = getattr(arr, "addressable_shards", None)
        if shards is None:
            continue
        for s in shards:
            d = str(s.device)
            ent = per.setdefault(d, {"device": d, "rows": 0, "bytes": 0})
            ent["bytes"] += int(s.data.nbytes)
            if j == 0:
                ent["rows"] += int(s.data.shape[0])
    return [per[d] for d in sorted(per)]


def _one_cache_size(fn) -> int:
    try:
        return fn._cache_size()
    except Exception:  # noqa: BLE001 - accounting must never raise
        return 0


def shard_cache_size() -> int:
    """Compiled-program count across the cached shard_map programs —
    an input to ops/binpack.py jit_cache_size, so the bench's
    jit_recompiles gate covers the sharded paths too."""
    with _PROGRAM_LOCK:
        fns = list(_PROGRAMS.values())
    return sum(_one_cache_size(fn) for fn in fns)
