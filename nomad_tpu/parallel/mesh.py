"""Device-mesh sharding of the placement program.

The cluster-scheduling analog of model parallelism: the *node axis* is
the model dimension (a 10k+-node matrix shards across chips over ICI)
and the *eval batch* is the data dimension (independent evaluations =
optimistic concurrency). Following the standard recipe: pick a mesh,
annotate input shardings, and let XLA insert the collectives — the
masked argmax over the sharded node axis lowers to an all-reduce, and
the one-hot state update stays node-local.

The reference has no tensor math to shard; its parallelism is N worker
goroutines (SURVEY.md section 2.4). Here one device-mesh program
subsumes both: `dp` x `nodes` = workers x cluster-shards.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.binpack import Asks, NodeState

DP_AXIS = "dp"  # independent evals (data parallel)
NODE_AXIS = "nodes"  # cluster node matrix (model parallel)


def make_mesh(n_devices: Optional[int] = None, dp: Optional[int] = None) -> Mesh:
    """Build a dp x nodes mesh over the available devices. When dp is
    not given, prefer sharding the node axis (the big dimension).

    When the default backend has fewer devices than requested (e.g. one
    real TPU chip while a dryrun asks for an 8-way mesh), fall back to
    the host CPU devices — `--xla_force_host_platform_device_count`
    makes those plentiful regardless of the accelerator count."""
    try:
        devices = np.array(jax.devices())
    except RuntimeError:
        # Default backend failed to initialize (e.g. no usable
        # accelerator in the driver environment) — the cpu backend is
        # always available and plentiful under
        # --xla_force_host_platform_device_count.
        devices = np.array(jax.devices("cpu"))
    if n_devices is not None and devices.size < n_devices:
        cpus = np.array(jax.devices("cpu"))
        if cpus.size >= n_devices:
            devices = cpus
    if n_devices is not None:
        if devices.size < n_devices:
            raise ValueError(
                f"need {n_devices} devices, have {devices.size} "
                f"(and {len(jax.devices('cpu'))} cpu)")
        devices = devices[:n_devices]
    total = devices.size
    if dp is None:
        dp = 1
    if total % dp:
        # A real error, not an assert: asserts vanish under `python
        # -O` and a silently ragged reshape would shard the node axis
        # unevenly.
        raise ValueError(f"{total} devices not divisible by dp={dp}")
    return Mesh(devices.reshape(dp, total // dp), (DP_AXIS, NODE_AXIS))


def _node_state_specs(batched: bool) -> NodeState:
    """PartitionSpecs for each NodeState leaf: shard the leading node
    dim (after the optional batch dim) across NODE_AXIS."""
    b = (DP_AXIS,) if batched else ()
    vec = P(*b, NODE_AXIS)  # [.., N]
    mat = P(*b, NODE_AXIS, None)  # [.., N, R]
    return NodeState(
        capacity=mat,
        sched_capacity=mat,
        util=mat,
        bw_avail=vec,
        bw_used=vec,
        ports_free=vec,
        job_count=vec,
        tg_count=mat,
        feasible=mat,
        node_ok=vec,
    )


def base_specs() -> Tuple:
    """PartitionSpecs for the batcher's cluster-base tuple, IN ITS
    ORDER: (capacity, sched_capacity, util, bw_avail, bw_used,
    ports_free, node_ok, class_ids). Lives here so the pairing between
    field and spec cannot drift from the dispatch-side shardings
    above."""
    s = _node_state_specs(batched=False)
    return (s.capacity, s.sched_capacity, s.util, s.bw_avail,
            s.bw_used, s.ports_free, s.node_ok, P(NODE_AXIS))


def delta_row_specs() -> Tuple:
    """PartitionSpecs for the resident-base delta payload, IN
    apply_base_delta's argument order after the four target arrays:
    (rows, util_rows, bw_rows, ports_rows, ok_rows). Replicated on
    purpose: a delta touches a handful of rows whose home shard the
    scatter resolves on device — pre-splitting each row to its shard
    would cost more host work than the few-hundred-byte payload it
    ships. Lives here (with base_specs) so a sharded resident base and
    its update path can't drift apart."""
    return (P(), P(None, None), P(), P(), P())


def _asks_specs(batched: bool) -> Asks:
    b = (DP_AXIS,) if batched else ()
    return Asks(
        resources=P(*b, None, None),
        bw=P(*b, None),
        ports=P(*b, None),
        tg_index=P(*b, None),
        active=P(*b, None),
        job_distinct_hosts=P(*b),
        tg_distinct_hosts=P(*b, None),
    )


def shard_placement_inputs(
    mesh: Mesh, state: NodeState, asks: Asks, keys, batched: bool = False
) -> Tuple[NodeState, Asks, object]:
    """Place the inputs on the mesh with the canonical shardings. The
    node count must divide the nodes-axis size (callers bucket to
    multiples of 128, models/matrix.py).

    ONE device_put per pytree (the shardings ride as a matching
    pytree), not one per leaf: jax batches the transfer into a single
    commit, where the per-leaf tree.map paid one host->device RPC per
    array — 10 RPCs per NodeState through a remote-device tunnel."""
    state_sh = jax.device_put(
        state,
        jax.tree.map(lambda spec: NamedSharding(mesh, spec),
                     _node_state_specs(batched)),
    )
    asks_sh = jax.device_put(
        asks,
        jax.tree.map(lambda spec: NamedSharding(mesh, spec),
                     _asks_specs(batched)),
    )
    key_spec = P(DP_AXIS) if batched else P()
    keys_sh = jax.device_put(keys, NamedSharding(mesh, key_spec))
    return state_sh, asks_sh, keys_sh


def gang_state_specs() -> "object":
    """PartitionSpecs for ops/gang.py's GangState, IN FIELD ORDER:
    node-axis leaves shard, everything is per-node. Lives here (with
    base_specs) so the gang program's sharded inputs can't drift from
    the dispatch-side layout. The topology-group scatter-add inside the
    program crosses shards (a gang slice can span them) — GSPMD lowers
    it to a segment-sum + all-reduce, the same collective the explicit
    parallel/shard.py sharded_group_capacity states by hand."""
    from ..ops.gang import GangState

    vec = P(NODE_AXIS)
    mat = P(NODE_AXIS, None)
    return GangState(
        capacity=mat,
        sched_capacity=mat,
        util=mat,
        bw_avail=vec,
        bw_used=vec,
        ports_free=vec,
        feas_row=vec,
        job_count=vec,
        dh_presence=vec,
        topo_ids=vec,
    )


def shard_gang_inputs(mesh: Mesh, state) -> "object":
    """Place a GangState on the mesh, node axis sharded. One
    device_put for the whole pytree (single transfer commit, like
    shard_placement_inputs)."""
    return jax.device_put(
        state,
        jax.tree.map(lambda spec: NamedSharding(mesh, spec),
                     gang_state_specs()),
    )


def defrag_solve_specs() -> Tuple:
    """PartitionSpecs for the defrag global solve's arguments, IN
    defrag/solver.py _solve_jit order: (logits0, fresh, base_util,
    capacity, sched_capacity, node_ok, bw_avail, bw_used, ports_free,
    ask_res, ask_bw, ask_ports, active). The x[K, N] tensor (logits0
    and the program's intermediates) shards over its NODE column —
    the biggest tensor in the system is what caps the fleet on one
    device. Ask-axis arrays replicate (K is bounded by
    MAX_SOLVE_ALLOCS)."""
    vec = P(NODE_AXIS)
    mat = P(NODE_AXIS, None)
    return (P(None, NODE_AXIS), P(), mat, mat, mat, vec, vec, vec, vec,
            P(None, None), P(), P(), P())


def shard_defrag_inputs(mesh: Mesh, args: Tuple) -> Tuple:
    """Place the defrag solve's argument tuple on the mesh
    (defrag_solve_specs order). GSPMD propagates through mirror
    descent: the per-alloc softmax over the sharded node axis lowers
    to a cross-device reduction, the gradient terms stay node-local."""
    return jax.device_put(
        args,
        tuple(NamedSharding(mesh, s) for s in defrag_solve_specs()),
    )


def sharded_placement(mesh: Mesh, state: NodeState, asks: Asks, keys, config,
                      batched: bool = False):
    """Run the placement program with mesh-sharded inputs. GSPMD
    propagates the shardings through the scan; the argmax over the
    sharded node axis becomes a cross-device reduction on ICI."""
    from ..ops.binpack import batched_placement_program, placement_program_jit

    state, asks, keys = shard_placement_inputs(mesh, state, asks, keys, batched)
    if batched:
        return batched_placement_program(state, asks, keys, config)
    return placement_program_jit(state, asks, keys, config)
