"""Agent configuration: HCL/JSON files, directory merge, CLI overlay.

Reference: command/agent/config.go (Config struct, Merge semantics,
DefaultConfig/DevConfig) and config_parse.go (HCL decoding). A config
value resolves as: defaults < config files (in load order; a directory
loads its *.hcl/*.json sorted by name) < CLI flags. Merge is per-field:
later non-zero scalars win, maps union (later wins per key), lists
concatenate (retry_join) or replace (client.servers follows the
reference's "later file wins" for servers).
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from ..jobspec.hcl import parse_hcl


@dataclass
class ServerBlock:
    enabled: bool = False
    bootstrap_expect: int = 0
    num_schedulers: Optional[int] = None
    enabled_schedulers: List[str] = field(default_factory=list)
    node_gc_threshold: str = ""
    heartbeat_grace: str = ""
    retry_join: List[str] = field(default_factory=list)
    start_join: List[str] = field(default_factory=list)
    # Per-type factory overrides, e.g. { "service" = "service-tpu" } —
    # finer-grained than the all-or-nothing -tpu flag.
    scheduler_factories: Dict[str, str] = field(default_factory=dict)
    # Drain-to-batch tuning (server/config.py): max evals drained per
    # broker visit for dense factories, and the group size below which
    # latency-aware routing sends evals to the host pipeline.
    eval_batch_size: Optional[int] = None
    dense_min_batch: Optional[int] = None
    # Central dispatch pipeline knobs (server/config.py dispatch_*):
    # enable/disable, batches in flight, and the device-side in-batch
    # conflict pre-resolution toggle.
    dispatch_pipeline: Optional[bool] = None
    dispatch_max_inflight: Optional[int] = None
    dense_pre_resolve: Optional[bool] = None
    # Scheduler executive (server/executive.py): the batched
    # event-loop dense scheduler. When on, `executive_threads` (not
    # num_schedulers) is the dense path's parallelism knob —
    # num_schedulers then only sizes the host/system worker pool (see
    # README "Scheduler executive" migration note).
    scheduler_executive: Optional[bool] = None
    executive_threads: Optional[int] = None
    # Device-resident node state (models/resident.py): enable knob +
    # the delta-vs-rebuild row threshold (0 = auto).
    device_resident: Optional[bool] = None
    resident_rebuild_rows: Optional[int] = None
    # Placement kernel (nomad_tpu/kernels): the dense solve the *-tpu
    # factories run ("greedy" / "convex" / a plugin's); validated at
    # server init.
    placement_kernel: Optional[str] = None
    # Churn control (nomad_tpu/migrate; server/config.py): the
    # in-flight migration budget (drain max_parallel analog) and the
    # dense priority-preemption switch + threshold.
    migrate_max_parallel: Optional[int] = None
    preemption_enabled: Optional[bool] = None
    preempt_priority_threshold: Optional[int] = None
    # Continuous defragmentation (nomad_tpu/defrag; server/config.py):
    # the leader-side background optimizer loop — enable switch, round
    # interval, minimum net fragmentation gain, per-wave move cap.
    defrag_enabled: Optional[bool] = None
    defrag_interval: Optional[float] = None
    defrag_min_gain: Optional[float] = None
    defrag_max_moves_per_wave: Optional[int] = None
    # Overload protection (nomad_tpu/admission; server/config.py):
    # bounded broker ready queues, eval deadlines, the token-bucket
    # intake gate, and the device-path circuit breaker.
    eval_ready_cap: Optional[int] = None
    eval_deadline_ttl: Optional[float] = None
    admission_enabled: Optional[bool] = None
    breaker_enabled: Optional[bool] = None
    breaker_failure_threshold: Optional[int] = None
    breaker_cooldown: Optional[float] = None
    # Contention observatory (nomad_tpu/profile; server/config.py):
    # recording + GIL sampler switch, sampler cadence, and the
    # pressure-monitor lock-wait p99 thresholds (ms; 0 disables).
    profile_enabled: Optional[bool] = None
    gil_sampler_interval: Optional[float] = None
    admission_lock_wait_yellow_ms: Optional[float] = None
    admission_lock_wait_red_ms: Optional[float] = None


@dataclass
class ClientBlock:
    enabled: bool = False
    state_dir: str = ""
    alloc_dir: str = ""
    servers: List[str] = field(default_factory=list)
    node_class: str = ""
    options: Dict[str, str] = field(default_factory=dict)
    meta: Dict[str, str] = field(default_factory=dict)
    network_speed: int = 0
    reserved: Dict[str, Any] = field(default_factory=dict)
    # Operator chroot embed map for the exec driver (reference
    # client-config chroot_env); empty = built-in defaults. Job specs
    # cannot set this — the driver rejects chroot_env in task config.
    chroot_env: Dict[str, str] = field(default_factory=dict)


@dataclass
class TelemetryBlock:
    statsite_address: str = ""
    statsd_address: str = ""
    disable_hostname: bool = False
    collection_interval: str = "1s"
    circonus_submission_url: str = ""


@dataclass
class Ports:
    http: int = 4646
    rpc: int = 4647
    serf: int = 4648


@dataclass
class ConsulBlock:
    address: str = ""
    server_service_name: str = "nomad"
    client_service_name: str = "nomad-client"
    auto_advertise: bool = True


@dataclass
class VaultBlock:
    enabled: bool = False
    address: str = ""
    token: str = ""


@dataclass
class TLSBlock:
    """Reference: config.go TLSConfig / nomad/structs/config/tls.go —
    one CA + node cert/key pair covers both wire protocols (the raft
    transport terminates mTLS, the HTTP API terminates server TLS)."""

    enabled: bool = False
    ca_file: str = ""
    cert_file: str = ""
    key_file: str = ""
    # Reference's EnableRPC/EnableHTTP split: either channel can stay
    # plaintext during a rolling TLS rollout.
    rpc: bool = True
    http: bool = True


@dataclass
class AgentConfig:
    region: str = "global"
    datacenter: str = "dc1"
    name: str = ""
    data_dir: str = ""
    log_level: str = "INFO"
    bind_addr: str = "127.0.0.1"
    advertise_addr: str = ""
    enable_debug: bool = False
    dev_mode: bool = False
    ports: Ports = field(default_factory=Ports)
    server: ServerBlock = field(default_factory=ServerBlock)
    client: ClientBlock = field(default_factory=ClientBlock)
    telemetry: TelemetryBlock = field(default_factory=TelemetryBlock)
    consul: ConsulBlock = field(default_factory=ConsulBlock)
    vault: VaultBlock = field(default_factory=VaultBlock)
    tls: TLSBlock = field(default_factory=TLSBlock)
    # Dotted paths explicitly assigned (by a config file, dev preset, or
    # flag). Merge copies exactly these from the override — so a file
    # CAN set a field back to its default ("explicitly set to the
    # default" is not the same as "unset").
    set_keys: set = field(default_factory=set)

    def assign(self, dotted: str, value: Any) -> None:
        obj = self
        parts = dotted.split(".")
        for part in parts[:-1]:
            obj = getattr(obj, part)
        setattr(obj, parts[-1], value)
        self.set_keys.add(dotted)


def default_config() -> AgentConfig:
    """DefaultConfig (config.go): neither server nor client enabled."""
    return AgentConfig()


def dev_config() -> AgentConfig:
    """DevConfig (config.go): combined server+client, permissive client
    options, in-memory everything."""
    cfg = AgentConfig()
    cfg.assign("dev_mode", True)
    cfg.assign("server.enabled", True)
    cfg.assign("server.num_schedulers", 2)
    cfg.assign("client.enabled", True)
    cfg.client.options["driver.raw_exec.enable"] = "1"
    cfg.set_keys.add("client.options")
    return cfg


# ---------------------------------------------------------------- parse


def _expect_block(raw: Any, what: str) -> Dict[str, Any]:
    """HCL repeated blocks arrive as lists; config blocks must be
    single (config_parse.go errors on duplicates too)."""
    if isinstance(raw, list):
        raise ValueError(f"duplicate {what!r} block")
    if not isinstance(raw, dict):
        raise ValueError(f"{what!r} must be a block")
    return raw


def _str_map(raw: Any, what: str) -> Dict[str, str]:
    if not isinstance(raw, dict):
        raise ValueError(f"{what!r} must be a block of key = value")
    return {str(k): str(v) for k, v in raw.items()}


def _str_list(raw: Any) -> List[str]:
    if isinstance(raw, str):
        return [raw]
    return [str(v) for v in raw or []]


# (dotted key -> caster); the cast doubles as light validation.
_SCHEMA: Dict[str, Any] = {
    "region": str, "datacenter": str, "name": str, "data_dir": str,
    "log_level": str, "bind_addr": str, "advertise_addr": str,
    "enable_debug": bool,
    "ports.http": int, "ports.rpc": int, "ports.serf": int,
    "server.enabled": bool, "server.bootstrap_expect": int,
    "server.num_schedulers": int, "server.enabled_schedulers": _str_list,
    "server.node_gc_threshold": str, "server.heartbeat_grace": str,
    "server.retry_join": _str_list, "server.start_join": _str_list,
    "server.eval_batch_size": int, "server.dense_min_batch": int,
    "server.dispatch_pipeline": bool, "server.dispatch_max_inflight": int,
    "server.dense_pre_resolve": bool,
    "server.scheduler_executive": bool, "server.executive_threads": int,
    "server.device_resident": bool, "server.resident_rebuild_rows": int,
    "server.placement_kernel": str,
    "server.migrate_max_parallel": int,
    "server.preemption_enabled": bool,
    "server.preempt_priority_threshold": int,
    "server.defrag_enabled": bool,
    "server.defrag_interval": float,
    "server.defrag_min_gain": float,
    "server.defrag_max_moves_per_wave": int,
    "server.eval_ready_cap": int, "server.eval_deadline_ttl": float,
    "server.admission_enabled": bool, "server.breaker_enabled": bool,
    "server.breaker_failure_threshold": int,
    "server.breaker_cooldown": float,
    "server.profile_enabled": bool,
    "server.gil_sampler_interval": float,
    "server.admission_lock_wait_yellow_ms": float,
    "server.admission_lock_wait_red_ms": float,
    "client.enabled": bool, "client.state_dir": str,
    "client.alloc_dir": str, "client.node_class": str,
    "client.servers": _str_list, "client.network_speed": int,
    "telemetry.statsite_address": str, "telemetry.statsd_address": str,
    "telemetry.collection_interval": str, "telemetry.disable_hostname": bool,
    "telemetry.circonus_submission_url": str,
    "consul.address": str, "consul.server_service_name": str,
    "consul.client_service_name": str, "consul.auto_advertise": bool,
    "vault.enabled": bool, "vault.address": str, "vault.token": str,
    "tls.enabled": bool, "tls.ca_file": str, "tls.cert_file": str,
    "tls.key_file": str, "tls.rpc": bool, "tls.http": bool,
}
_MAP_KEYS = {"client.options", "client.meta", "client.reserved",
             "client.chroot_env", "server.scheduler_factories"}
_BLOCKS = {"ports", "server", "client", "telemetry", "consul", "vault",
           "tls"}


def config_from_dict(data: Dict[str, Any]) -> AgentConfig:
    cfg = AgentConfig()
    for key, raw in data.items():
        if key in _BLOCKS:
            block = _expect_block(raw, key)
            for sub, val in block.items():
                dotted = f"{key}.{sub}"
                if dotted in _MAP_KEYS:
                    if dotted == "client.reserved":
                        cfg.assign(dotted, _expect_block(val, dotted))
                    else:
                        cfg.assign(dotted, _str_map(val, dotted))
                elif dotted in _SCHEMA:
                    cfg.assign(dotted, _SCHEMA[dotted](val))
                else:
                    raise ValueError(f"unknown config keys: {dotted}")
        elif key in _SCHEMA:
            cfg.assign(key, _SCHEMA[key](raw))
        else:
            raise ValueError(f"unknown config keys: {key}")
    return cfg


def parse_config_file(path: str) -> AgentConfig:
    """One file: .json parses as JSON, anything else as HCL
    (config_parse.go sniffs the same way)."""
    with open(path) as f:
        src = f.read()
    if path.endswith(".json"):
        data = json.loads(src)
    else:
        data = parse_hcl(src)
    try:
        return config_from_dict(data)
    except ValueError as e:
        raise ValueError(f"{path}: {e}") from None


def load_config(path: str) -> AgentConfig:
    """A file loads directly; a directory loads every *.hcl/*.json in
    lexical order and merges them (config.go LoadConfigDir)."""
    if os.path.isdir(path):
        cfg = AgentConfig()
        found = False
        for name in sorted(os.listdir(path)):
            if not (name.endswith(".hcl") or name.endswith(".json")):
                continue
            cfg = merge_config(cfg, parse_config_file(os.path.join(path, name)))
            found = True
        if not found:
            raise ValueError(f"no .hcl or .json config files in {path}")
        return cfg
    return parse_config_file(path)


def load_configs(paths: List[str]) -> AgentConfig:
    """Merge defaults with every -config path in order."""
    cfg = default_config()
    for path in paths:
        cfg = merge_config(cfg, load_config(path))
    return cfg


def parse_duration(text: str) -> float:
    """Go-style duration to seconds: "30s", "10m", "1h30m", "250ms",
    bare numbers are seconds."""
    text = text.strip()
    if not text:
        raise ValueError("empty duration")
    try:
        return float(text)
    except ValueError:
        pass
    units = {"ms": 0.001, "s": 1.0, "m": 60.0, "h": 3600.0}
    total = 0.0
    num = ""
    i = 0
    while i < len(text):
        c = text[i]
        if c.isdigit() or c == ".":
            num += c
            i += 1
            continue
        unit = text[i:i + 2] if text[i:i + 2] == "ms" else c
        if unit not in units or not num:
            raise ValueError(f"bad duration {text!r}")
        total += float(num) * units[unit]
        num = ""
        i += len(unit)
    if num:
        raise ValueError(f"bad duration {text!r}")
    return total


# ---------------------------------------------------------------- merge


def merge_config(a: AgentConfig, b: AgentConfig) -> AgentConfig:
    """a < b; returns a new config. Exactly b's explicitly-set keys are
    copied over (maps union, with b winning per entry), so "set back to
    the default" works and unset fields never clobber."""
    import copy

    out = copy.deepcopy(a)
    for dotted in sorted(b.set_keys):
        obj = b
        dst = out
        parts = dotted.split(".")
        for part in parts[:-1]:
            obj = getattr(obj, part)
            dst = getattr(dst, part)
        val = copy.deepcopy(getattr(obj, parts[-1]))
        if isinstance(val, dict):
            getattr(dst, parts[-1]).update(val)
        elif dotted in ("server.retry_join", "server.start_join"):
            # Join seed lists accumulate across files (config.go Merge
            # appends); other lists follow later-file-wins.
            merged = getattr(dst, parts[-1]) + val
            setattr(dst, parts[-1], list(dict.fromkeys(merged)))
        else:
            setattr(dst, parts[-1], val)
        out.set_keys.add(dotted)
    return out
