"""Command-line interface.

Reference: command/ + commands.go:28-146 — run/plan/status/stop/
validate/init/inspect/node-status/node-drain/alloc-status/eval-status/
agent-info and the agent entrypoint. Talks to the agent over the HTTP
SDK; `agent -dev` runs an in-process server+client.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import List, Optional

from ..api.client import APIError, Client
from ..utils.codec import to_dict

EXAMPLE_JOB = '''\
# Example job file (reference: command/init.go)
job "example" {
  datacenters = ["dc1"]
  type = "service"

  update {
    stagger = "10s"
    max_parallel = 1
  }

  group "cache" {
    count = 1

    restart {
      attempts = 10
      interval = "5m"
      delay = "25s"
      mode = "delay"
    }

    ephemeral_disk {
      size = 300
    }

    task "redis" {
      driver = "exec"

      config {
        command = "/bin/sleep"
        args = ["3600"]
      }

      resources {
        cpu = 500
        memory = 256

        network {
          mbits = 10
          port "db" {}
        }
      }
    }
  }
}
'''


def _client(args) -> Client:
    address = args.address or os.environ.get("NOMAD_ADDR", "http://127.0.0.1:4646")
    region = getattr(args, "region", "") or os.environ.get("NOMAD_REGION", "")
    return Client(address, timeout=30.0, region=region)


def _fmt_table(rows: List[List[str]], header: List[str]) -> str:
    all_rows = [header] + rows
    widths = [max(len(str(r[i])) for r in all_rows) for i in range(len(header))]
    lines = []
    for r in all_rows:
        lines.append("  ".join(str(c).ljust(w) for c, w in zip(r, widths)).rstrip())
    return "\n".join(lines)


def _short(ident: str) -> str:
    return ident[:8] if ident else ""


def _monitor_eval(client: Client, eval_id: str, timeout: float = 60.0) -> int:
    """Poll the eval until terminal; print placement results
    (command/monitor.go)."""
    deadline = time.monotonic() + timeout
    printed_blocked = False
    while time.monotonic() < deadline:
        ev, _ = client.evaluations.info(eval_id)
        if ev.status in ("complete", "failed", "canceled"):
            print(f'Evaluation "{_short(eval_id)}" finished with status "{ev.status}"')
            if ev.failed_tg_allocs:
                for tg, metric in ev.failed_tg_allocs.items():
                    print(f"\nTask Group {tg!r} (failed to place all allocations):")
                    for constraint, count in metric.constraint_filtered.items():
                        print(f"  * Constraint {constraint!r} filtered {count} nodes")
                    for dim, count in metric.dimension_exhausted.items():
                        print(f"  * Resources exhausted on {count} nodes: {dim}")
                    if metric.nodes_evaluated == 0:
                        print("  * No nodes were eligible for evaluation")
                if ev.blocked_eval and not printed_blocked:
                    print(
                        f'\nEvaluation "{_short(ev.blocked_eval)}" waiting for '
                        "additional capacity to place remainder"
                    )
            return 0 if ev.status == "complete" else 1
        time.sleep(0.2)
    print(f"Timed out waiting for evaluation {_short(eval_id)}")
    return 1


# ------------------------------------------------------------- commands


def cmd_version(args) -> int:
    from .. import API_MAJOR_VERSION, __version__

    print(f"nomad-tpu v{__version__} (api {API_MAJOR_VERSION})")
    return 0


def cmd_init(args) -> int:
    path = "example.nomad"
    if os.path.exists(path):
        print(f"Job file {path!r} already exists", file=sys.stderr)
        return 1
    with open(path, "w") as f:
        f.write(EXAMPLE_JOB)
    print(f"Example job file written to {path}")
    return 0


def cmd_validate(args) -> int:
    from ..jobspec import parse_file

    try:
        job = parse_file(args.file)
        errors = job.validate()
    except (ValueError, OSError) as e:
        print(f"Error validating job: {e}", file=sys.stderr)
        return 1
    if errors:
        for err in errors:
            print(f"Validation error: {err}", file=sys.stderr)
        return 1
    print("Job validation successful")
    return 0


def cmd_run(args) -> int:
    from ..jobspec import parse_file

    job = parse_file(args.file)
    client = _client(args)
    if args.check_index is not None:
        eval_id = client.jobs.enforce_register(job, args.check_index)
    else:
        eval_id = client.jobs.register(job)
    if not eval_id:
        print(f'Job "{job.id}" registered (periodic, no evaluation)')
        return 0
    print(f'==> Evaluation "{_short(eval_id)}" created for job "{job.id}"')
    if args.detach:
        print(eval_id)
        return 0
    return _monitor_eval(client, eval_id)


_DIFF_MARK = {"Added": "+", "Deleted": "-", "Edited": "+/-", "None": " "}


def _print_field_diffs(fields, indent: str) -> None:
    for f in fields:
        mark = _DIFF_MARK.get(f.get("type"), " ")
        if f.get("type") == "Edited":
            print(f'{indent}{mark} {f["name"]}: {f["old"]!r} => {f["new"]!r}')
        elif f.get("type") == "Added":
            print(f'{indent}{mark} {f["name"]}: {f["new"]!r}')
        elif f.get("type") == "Deleted":
            print(f'{indent}{mark} {f["name"]}: {f["old"]!r}')
        elif f.get("type") == "None":
            print(f'{indent}  {f["name"]}: {f["old"]!r}')


def _print_object_diffs(objects, indent: str) -> None:
    for o in objects or []:
        mark = _DIFF_MARK.get(o.get("type"), " ")
        print(f'{indent}{mark} {o["name"]} {{')
        _print_field_diffs(o.get("fields") or [], indent + "    ")
        _print_object_diffs(o.get("objects") or [], indent + "    ")
        print(f"{indent}}}")


def cmd_plan(args) -> int:
    from ..jobspec import parse_file

    job = parse_file(args.file)
    client = _client(args)
    result = client.jobs.plan(job, diff=True, contextual=args.verbose)
    diff = result.get("diff") or {}
    mark = _DIFF_MARK.get(diff.get("type", "None"), " ")
    print(f"{mark} Job: {job.id!r}")
    _print_field_diffs(diff.get("fields") or [], "  ")
    _print_object_diffs(diff.get("objects") or [], "  ")
    for tgd in diff.get("task_groups") or []:
        mark = _DIFF_MARK.get(tgd.get("type", "None"), " ")
        counts = ", ".join(
            f"{n} {label}" for label, n in (tgd.get("updates") or {}).items() if n
        )
        print(f'{mark} Task Group: {tgd["name"]!r}' + (f" ({counts})" if counts else ""))
        if args.verbose or tgd.get("type") != "None":
            _print_field_diffs(tgd.get("fields") or [], "    ")
            _print_object_diffs(tgd.get("objects") or [], "    ")
            for td in tgd.get("tasks") or []:
                tmark = _DIFF_MARK.get(td.get("type", "None"), " ")
                notes = ", ".join(td.get("annotations") or [])
                print(f'    {tmark} Task: {td["name"]!r}' + (f" ({notes})" if notes else ""))
                _print_field_diffs(td.get("fields") or [], "        ")
                _print_object_diffs(td.get("objects") or [], "        ")

    failed = result.get("failed_tg_allocs") or {}
    if failed:
        print("\nPlacement failures:")
        for tg, metric in failed.items():
            print(f"  Task Group {tg!r}:")
            for constraint, count in (metric.get("constraint_filtered") or {}).items():
                print(f"    * Constraint {constraint!r} filtered {count} nodes")
    else:
        print("\nAll tasks successfully allocated.")
    print(f'\nJob Modify Index: {result.get("job_modify_index", 0)}')
    print('To submit the job with version verification run:\n')
    print(f'nomad-tpu run -check-index {result.get("job_modify_index", 0)} {args.file}')
    return 0


def cmd_status(args) -> int:
    client = _client(args)
    if not args.job:
        jobs, _ = client.jobs.list()
        if not jobs:
            print("No running jobs")
            return 0
        rows = [
            [_stub["id"], _stub["type"], str(_stub["priority"]), _stub["status"]]
            for _stub in jobs
        ]
        print(_fmt_table(rows, ["ID", "Type", "Priority", "Status"]))
        return 0
    try:
        job, _ = client.jobs.info(args.job)
    except APIError as e:
        print(f"Error querying job: {e}", file=sys.stderr)
        return 1
    print(f"ID            = {job.id}")
    print(f"Name          = {job.name}")
    print(f"Type          = {job.type}")
    print(f"Priority      = {job.priority}")
    print(f"Datacenters   = {','.join(job.datacenters)}")
    print(f"Status        = {job.status}")
    print(f"Periodic      = {job.is_periodic()}")
    summary, _ = client.jobs.summary(job.id)
    print("\nSummary")
    rows = [
        [tg, str(s["queued"]), str(s["starting"]), str(s["running"]),
         str(s["failed"]), str(s["complete"]), str(s["lost"])]
        for tg, s in (summary.get("summary") or {}).items()
    ]
    print(_fmt_table(
        rows, ["Task Group", "Queued", "Starting", "Running", "Failed",
               "Complete", "Lost"]
    ))
    allocs, _ = client.jobs.allocations(job.id)
    if allocs:
        print("\nAllocations")
        rows = [
            [_short(a["id"]), _short(a["eval_id"]), _short(a["node_id"]),
             a["task_group"], a["desired_status"], a["client_status"]]
            for a in allocs
        ]
        print(_fmt_table(
            rows, ["ID", "Eval ID", "Node ID", "Task Group", "Desired", "Status"]
        ))
    return 0


def cmd_stop(args) -> int:
    client = _client(args)
    eval_id = client.jobs.deregister(args.job)
    if not eval_id:
        print(f'Job "{args.job}" deregistered')
        return 0
    print(f'==> Evaluation "{_short(eval_id)}" created for deregistration')
    if args.detach:
        return 0
    return _monitor_eval(client, eval_id)


def cmd_inspect(args) -> int:
    client = _client(args)
    job, _ = client.jobs.info(args.job)
    print(json.dumps(to_dict(job), indent=2, sort_keys=True))
    return 0


def cmd_node_status(args) -> int:
    client = _client(args)
    if not args.node:
        nodes, _ = client.nodes.list()
        rows = [
            [_short(n["id"]), n["datacenter"], n["name"], n["node_class"],
             str(n["drain"]), n["status"]]
            for n in nodes
        ]
        print(_fmt_table(rows, ["ID", "DC", "Name", "Class", "Drain", "Status"]))
        return 0
    node, _ = client.nodes.info(args.node)
    print(f"ID         = {node.id}")
    print(f"Name       = {node.name}")
    print(f"Class      = {node.node_class}")
    print(f"DC         = {node.datacenter}")
    print(f"Drain      = {node.drain}")
    print(f"Status     = {node.status}")
    if node.resources:
        print(
            f"Resources  = cpu:{node.resources.cpu}MHz "
            f"mem:{node.resources.memory_mb}MB disk:{node.resources.disk_mb}MB"
        )
    drivers = sorted(
        k.removeprefix("driver.")
        for k in node.attributes
        if k.startswith("driver.") and not k.endswith(".enable")
    )
    print(f"Drivers    = {','.join(drivers)}")
    return 0


def cmd_node_drain(args) -> int:
    client = _client(args)
    if not (args.enable or args.disable):
        print("Either -enable or -disable is required", file=sys.stderr)
        return 1
    client.nodes.drain(args.node, drain=bool(args.enable))
    state = "enabled" if args.enable else "disabled"
    print(f"Node {_short(args.node)} drain {state}")
    return 0


def cmd_alloc_status(args) -> int:
    client = _client(args)
    alloc, _ = client.allocations.info(args.alloc)
    print(f"ID            = {alloc.id}")
    print(f"Eval ID       = {_short(alloc.eval_id)}")
    print(f"Name          = {alloc.name}")
    print(f"Node ID       = {_short(alloc.node_id)}")
    print(f"Job ID        = {alloc.job_id}")
    print(f"Desired       = {alloc.desired_status}  {alloc.desired_description}")
    print(f"Status        = {alloc.client_status}  {alloc.client_description}")
    for task, state in alloc.task_states.items():
        print(f"\nTask {task!r} is {state.state!r} (failed={state.failed})")
        for event in state.events[-5:]:
            details = []
            if event.exit_code:
                details.append(f"exit={event.exit_code}")
            if event.driver_error:
                details.append(event.driver_error)
            if event.message:
                details.append(event.message)
            print(f"  {event.type}" + (f" ({', '.join(details)})" if details else ""))
    metrics = alloc.metrics
    if metrics is not None and args.verbose:
        print("\nPlacement Metrics")
        print(f"  Nodes evaluated: {metrics.nodes_evaluated}")
        print(f"  Nodes filtered:  {metrics.nodes_filtered}")
        print(f"  Nodes exhausted: {metrics.nodes_exhausted}")
        for name, score in metrics.scores.items():
            print(f"  Score {name}: {score:.3f}")
    return 0


def cmd_eval_status(args) -> int:
    client = _client(args)
    ev, _ = client.evaluations.info(args.eval)
    print(f"ID                 = {ev.id}")
    print(f"Status             = {ev.status}  {ev.status_description}")
    print(f"Type               = {ev.type}")
    print(f"Triggered By       = {ev.triggered_by}")
    print(f"Job ID             = {ev.job_id}")
    print(f"Priority           = {ev.priority}")
    if ev.blocked_eval:
        print(f"Blocked Eval       = {_short(ev.blocked_eval)}")
    if ev.queued_allocations:
        print(f"Queued Allocations = {ev.queued_allocations}")
    if ev.failed_tg_allocs:
        print("\nFailed Placements")
        for tg, metric in ev.failed_tg_allocs.items():
            print(f"Task Group {tg!r}:")
            for constraint, count in metric.constraint_filtered.items():
                print(f"  * Constraint {constraint!r} filtered {count} nodes")
            for dim, count in metric.dimension_exhausted.items():
                print(f"  * {dim} exhausted on {count} nodes")
    return 0


def cmd_fs(args) -> int:
    """Browse an allocation's filesystem (command/fs.go)."""
    client = _client(args)
    path = args.path or "/"
    if args.stat:
        st = client.alloc_fs.stat(args.alloc, path)
        kind = "dir" if st["is_dir"] else "file"
        print(f'{st["name"]}\t{kind}\t{st["size"]} bytes')
        return 0
    st = client.alloc_fs.stat(args.alloc, path)
    if st["is_dir"]:
        for ent in client.alloc_fs.list(args.alloc, path):
            kind = "d" if ent["is_dir"] else "-"
            print(f'{kind} {ent["size"]:>10}  {ent["name"]}')
    else:
        sys.stdout.buffer.write(client.alloc_fs.cat(args.alloc, path))
    return 0


def cmd_logs(args) -> int:
    """Stream a task's stdout/stderr (command/logs.go): offset-poll the
    logs endpoint; -f keeps following."""
    client = _client(args)
    ltype = "stderr" if args.stderr else "stdout"
    task = args.task
    if not task:
        alloc, _ = client.allocations.info(args.alloc)
        names = list(alloc.task_states or {})
        if len(names) != 1:
            print(
                f"allocation has {len(names)} tasks, specify one of: {names}",
                file=sys.stderr,
            )
            return 1
        task = names[0]
    if args.tail and args.n > 0:
        out = client.alloc_fs.logs(args.alloc, task, ltype, offset=args.n, origin="end")
    else:
        out = client.alloc_fs.logs(args.alloc, task, ltype)
    sys.stdout.buffer.write(out["data"])
    sys.stdout.flush()
    offset = out["offset"]
    while args.follow:
        time.sleep(1.0)
        out = client.alloc_fs.logs(args.alloc, task, ltype, offset=offset)
        if out["data"]:
            sys.stdout.buffer.write(out["data"])
            sys.stdout.flush()
            offset = out["offset"]
    return 0


def cmd_server_members(args) -> int:
    client = _client(args)
    members = client.agent.members()
    if not members:
        print("No known members")
        return 0
    print(f"{'Name':<28} {'Addr':<22} {'Status':<8} {'Region':<10} DC")
    for m in sorted(members, key=lambda m: m["name"]):
        print(f"{m['name']:<28} {m['addr']:<22} {m['status']:<8} "
              f"{m['region']:<10} {m['datacenter']}")
    return 0


def cmd_server_join(args) -> int:
    client = _client(args)
    joined = client.agent.join(args.addrs)
    print(f"Joined {joined} servers successfully")
    return 0 if joined else 1


def cmd_server_force_leave(args) -> int:
    client = _client(args)
    client.agent.force_leave(args.node)
    print(f"Force-leave of {args.node} requested")
    return 0


def cmd_agent_info(args) -> int:
    client = _client(args)
    info = client.agent.self()
    print(json.dumps(info["stats"], indent=2, sort_keys=True))
    return 0


def _resolve_agent_config(args):
    """defaults (< dev) < -config files in order < CLI flags
    (command.go:909 flag overlay)."""
    from .agent_config import (
        default_config,
        dev_config,
        load_config,
        merge_config,
    )

    cfg = dev_config() if args.dev else default_config()
    for path in args.config or []:
        cfg = merge_config(cfg, load_config(path))
    if args.bind:
        cfg.bind_addr = args.bind
    if args.port:
        cfg.ports.http = args.port
    if getattr(args, "serf_port", 0):
        cfg.ports.serf = args.serf_port
    if args.region:
        cfg.region = args.region
    if args.node_name:
        cfg.name = args.node_name
    if args.num_schedulers is not None:
        cfg.server.num_schedulers = args.num_schedulers
    if args.statsd:
        cfg.telemetry.statsd_address = args.statsd
    if args.consul:
        cfg.consul.address = args.consul
    if args.advertise:
        cfg.advertise_addr = args.advertise
    if args.join:
        cfg.server.start_join = cfg.server.start_join + args.join.split(",")
    if args.log_level:
        cfg.log_level = args.log_level
    return cfg


def _advertise_addr(cfg):
    """A wildcard bind is not routable — advertise a real interface
    address instead."""
    import socket as _socket

    advertise = cfg.advertise_addr or cfg.bind_addr
    if advertise in ("0.0.0.0", "::"):
        s = _socket.socket(_socket.AF_INET, _socket.SOCK_DGRAM)
        try:
            s.connect(("10.255.255.255", 1))
            advertise = s.getsockname()[0]
        except OSError:
            advertise = "127.0.0.1"
        finally:
            s.close()
    return advertise


def cmd_agent(args) -> int:
    """Run an agent: server, client, or both, from merged config
    (agent.go:61 — the Agent composes nomad.Server and client.Client
    per config; -dev enables both with permissive defaults)."""
    import logging
    import socket as _socket

    from ..api import HTTPServer
    from ..client import ClientAgent, ClientConfig
    from ..server import Server, ServerConfig
    from ..utils import metrics
    from .agent_config import parse_duration

    try:
        cfg = _resolve_agent_config(args)
        collection_interval = parse_duration(cfg.telemetry.collection_interval)
        heartbeat_grace = (parse_duration(cfg.server.heartbeat_grace)
                           if cfg.server.heartbeat_grace else None)
        node_gc_threshold = (parse_duration(cfg.server.node_gc_threshold)
                             if cfg.server.node_gc_threshold else None)
    except (ValueError, OSError) as e:
        print(f"error loading config: {e}", file=sys.stderr)
        return 1
    logging.basicConfig(
        level=getattr(logging, cfg.log_level.upper(), logging.INFO),
        format="%(asctime)s [%(levelname)s] %(name)s: %(message)s",
    )
    if not cfg.server.enabled and not cfg.client.enabled:
        print("agent must have server, client, or both enabled "
              "(use -dev or a -config file)", file=sys.stderr)
        return 1

    metrics.configure(
        statsd_addr=cfg.telemetry.statsd_address,
        statsite_addr=cfg.telemetry.statsite_address,
        disable_hostname=cfg.telemetry.disable_hostname,
        interval=collection_interval,
        circonus_url=cfg.telemetry.circonus_submission_url,
    )
    # SIGUSR1 dumps recent telemetry to stderr (in-memory sink).
    try:
        metrics.install_signal_dump()
    except ValueError:
        pass  # not on the main thread (tests)

    scheduler_factories = {}
    if cfg.server.scheduler_factories:
        scheduler_factories = dict(cfg.server.scheduler_factories)
    if args.tpu:
        # CLI flags win over config files (the module's documented
        # precedence): -tpu overlays the dense factories on whatever
        # the HCL mapped.
        scheduler_factories.update({"service": "service-tpu",
                                    "batch": "batch-tpu",
                                    "system": "system-tpu"})
    if cfg.server.enabled and any(
            f.endswith("-tpu") for f in scheduler_factories.values()):
        # Eager jax import at agent boot: with dense factories
        # configured this SERVER will need the device backend, and a
        # broken device environment should fail loudly here — at
        # startup, on the operator's console — rather than as per-eval
        # scheduler errors in the middle of the first placement storm.
        # Client-only agents never schedule and skip the cost.
        import jax

        # Operator backend override: dense factories are correct on any
        # XLA backend (CPU/TPU parity is a test invariant), so agents
        # on TPU-less hosts can still run them — and some environments
        # pin jax_platforms in site config where JAX_PLATFORMS can't
        # override it.
        plat = os.environ.get("NOMAD_TPU_PLATFORM")
        if plat:
            try:
                jax.config.update("jax_platforms", plat)
            except Exception as e:  # noqa: BLE001 - backend already up
                print(f"warning: NOMAD_TPU_PLATFORM={plat!r} ignored: {e}",
                      file=sys.stderr)

    # Unique gossip identity per agent: two same-region agents with the
    # same member name would clobber each other in the serf pool.
    node_name = cfg.name or f"{_socket.gethostname()}-{cfg.ports.http}"

    # TLS contexts from the agent tls block: fail at boot with a clear
    # message, not mid-election (rpc.go:23-30 rpcTLS discipline).
    from ..utils.tlsutil import contexts_from_block

    tls_rpc_ctx, tls_http_ctx, tls_client_ctx = contexts_from_block(cfg.tls)

    server = http = raft_transport = None
    server_addr = None
    if cfg.server.enabled:
        server_cfg = ServerConfig(
            num_schedulers=(cfg.server.num_schedulers
                            if cfg.server.num_schedulers is not None else 2),
            scheduler_factories=scheduler_factories,
            region=cfg.region, datacenter=cfg.datacenter,
            node_name=node_name,
            bootstrap_expect=cfg.server.bootstrap_expect or 1,
            statsd_addr=cfg.telemetry.statsd_address,
        )
        if cfg.server.enabled_schedulers:
            server_cfg.enabled_schedulers = list(cfg.server.enabled_schedulers)
            if "_core" not in server_cfg.enabled_schedulers:
                server_cfg.enabled_schedulers.append("_core")
        if heartbeat_grace is not None:
            server_cfg.heartbeat_grace = heartbeat_grace
        if node_gc_threshold is not None:
            server_cfg.node_gc_threshold = node_gc_threshold
        if cfg.server.eval_batch_size is not None:
            server_cfg.eval_batch_size = cfg.server.eval_batch_size
        if cfg.server.dense_min_batch is not None:
            server_cfg.dense_min_batch = cfg.server.dense_min_batch
        if cfg.server.dispatch_pipeline is not None:
            server_cfg.dispatch_pipeline = cfg.server.dispatch_pipeline
        if cfg.server.dispatch_max_inflight is not None:
            server_cfg.dispatch_max_inflight = (
                cfg.server.dispatch_max_inflight)
        if cfg.server.dense_pre_resolve is not None:
            server_cfg.dense_pre_resolve = cfg.server.dense_pre_resolve
        # Scheduler executive (server/executive.py): batched cohort
        # scheduling instead of thread-per-eval workers. See the README
        # migration note — num_schedulers keeps sizing the host/system
        # worker pool; executive_threads is the dense knob here.
        if cfg.server.scheduler_executive is not None:
            server_cfg.scheduler_executive = cfg.server.scheduler_executive
        if cfg.server.executive_threads is not None:
            server_cfg.executive_threads = cfg.server.executive_threads
        # Device-resident node state (models/resident.py).
        if cfg.server.device_resident is not None:
            server_cfg.device_resident = cfg.server.device_resident
        if cfg.server.resident_rebuild_rows is not None:
            server_cfg.resident_rebuild_rows = (
                cfg.server.resident_rebuild_rows)
        # Placement kernel (nomad_tpu/kernels); Server init validates,
        # so a typo'd name aborts agent startup with the known list.
        if cfg.server.placement_kernel is not None:
            server_cfg.placement_kernel = cfg.server.placement_kernel
        # Churn control (nomad_tpu/migrate): migration budget +
        # preemption policy. CLI flags win over HCL, as everywhere.
        if args.migrate_max_parallel is not None:
            server_cfg.migrate_max_parallel = args.migrate_max_parallel
        elif cfg.server.migrate_max_parallel is not None:
            server_cfg.migrate_max_parallel = cfg.server.migrate_max_parallel
        if args.preemption:
            server_cfg.preemption_enabled = True
        elif cfg.server.preemption_enabled is not None:
            server_cfg.preemption_enabled = cfg.server.preemption_enabled
        if cfg.server.preempt_priority_threshold is not None:
            server_cfg.preempt_priority_threshold = (
                cfg.server.preempt_priority_threshold)
        # Continuous defragmentation (nomad_tpu/defrag): the CLI flag
        # only turns it ON (HCL can do either); tuning knobs are HCL.
        if args.defrag:
            server_cfg.defrag_enabled = True
        elif cfg.server.defrag_enabled is not None:
            server_cfg.defrag_enabled = cfg.server.defrag_enabled
        if cfg.server.defrag_interval is not None:
            server_cfg.defrag_interval = cfg.server.defrag_interval
        if cfg.server.defrag_min_gain is not None:
            server_cfg.defrag_min_gain = cfg.server.defrag_min_gain
        if cfg.server.defrag_max_moves_per_wave is not None:
            server_cfg.defrag_max_moves_per_wave = (
                cfg.server.defrag_max_moves_per_wave)
        # Overload protection (nomad_tpu/admission): bounded broker
        # queues, deadlines, intake gate, device-path breaker.
        if cfg.server.eval_ready_cap is not None:
            server_cfg.eval_ready_cap = cfg.server.eval_ready_cap
        if cfg.server.eval_deadline_ttl is not None:
            server_cfg.eval_deadline_ttl = cfg.server.eval_deadline_ttl
        if cfg.server.admission_enabled is not None:
            server_cfg.admission_enabled = cfg.server.admission_enabled
        if cfg.server.breaker_enabled is not None:
            server_cfg.breaker_enabled = cfg.server.breaker_enabled
        if cfg.server.breaker_failure_threshold is not None:
            server_cfg.breaker_failure_threshold = (
                cfg.server.breaker_failure_threshold)
        if cfg.server.breaker_cooldown is not None:
            server_cfg.breaker_cooldown = cfg.server.breaker_cooldown
        # Contention observatory (nomad_tpu/profile).
        if cfg.server.profile_enabled is not None:
            server_cfg.profile_enabled = cfg.server.profile_enabled
        if cfg.server.gil_sampler_interval is not None:
            server_cfg.gil_sampler_interval = (
                cfg.server.gil_sampler_interval)
        if cfg.server.admission_lock_wait_yellow_ms is not None:
            server_cfg.admission_lock_wait_yellow_ms = (
                cfg.server.admission_lock_wait_yellow_ms)
        if cfg.server.admission_lock_wait_red_ms is not None:
            server_cfg.admission_lock_wait_red_ms = (
                cfg.server.admission_lock_wait_red_ms)
        if "vault.enabled" in cfg.set_keys:
            server_cfg.vault_enabled = cfg.vault.enabled
        if cfg.vault.address:
            server_cfg.vault_addr = cfg.vault.address
            server_cfg.vault_token = cfg.vault.token
        server = Server(server_cfg)
        # TLS material for the server's own outbound/inbound channels:
        # the follower->leader HTTP forwards and cross-region proxying
        # must verify against the cluster CA, and gossip terminates
        # the same mTLS as raft (its member records carry the
        # addresses forwarding trusts).
        # Outbound contexts are passed UNGATED: the dial sites apply
        # them only to https:// targets, so a mixed rolling-TLS cluster
        # (this agent still plaintext, the leader already https) keeps
        # verifying peers against the cluster CA.
        server.tls_client_ctx = tls_client_ctx
        server.tls_rpc_server_ctx = tls_rpc_ctx
        server.tls_rpc_client_ctx = (
            tls_client_ctx if tls_rpc_ctx else None)
        # bootstrap_expect > 1: real raft consensus over TCP; the
        # cluster forms once enough servers gossip a raft address
        # (server.go bootstrap_expect). Otherwise single-server mode.
        multi_server = cfg.server.bootstrap_expect > 1
        raft_transport = None
        adv_raft = ""
        if multi_server:
            from ..server.transport import TCPTransport, fsm_payload_decoder

            raft_transport = TCPTransport(
                fsm_payload_decoder,
                ssl_server_ctx=tls_rpc_ctx,
                ssl_client_ctx=tls_client_ctx if tls_rpc_ctx else None)
            raft_bind = raft_transport.serve(cfg.bind_addr, cfg.ports.rpc)
            raft_port = int(raft_bind.rsplit(":", 1)[1])
            adv_raft = f"{_advertise_addr(cfg)}:{raft_port}"
            # Enter cluster mode (writes fail with no-leader) BEFORE the
            # HTTP API serves: an early write must never land in the
            # pre-raft dev log and silently diverge from the cluster.
            raft_dir = (os.path.join(cfg.data_dir, "raft")
                        if cfg.data_dir else "")
            server.setup_raft_cluster(
                raft_transport, adv_raft, cfg.server.bootstrap_expect,
                data_dir=raft_dir)
        else:
            server.start()
        http = HTTPServer(server, host=cfg.bind_addr, port=cfg.ports.http,
                          enable_debug=cfg.enable_debug,
                          ssl_context=tls_http_ctx,
                          forward_ssl_context=tls_client_ctx)
        http.start()
        server_addr = http.addr
        # Gossip peers and federated regions must receive a routable
        # address, not a wildcard bind (server.go setupSerf tags).
        scheme = "https" if tls_http_ctx is not None else "http"
        advertised_http = f"{scheme}://{_advertise_addr(cfg)}:{http.port}"
        serf_addr = server.setup_serf(host=cfg.bind_addr,
                                      port=cfg.ports.serf,
                                      http_addr=advertised_http,
                                      rpc_addr=adv_raft)
        if cfg.server.start_join:
            joined = server.serf_join(cfg.server.start_join)
            print(f"==> Joined {joined} gossip peers")
        if cfg.server.retry_join:
            # retry_join keeps trying until it lands (command.go
            # retryJoin loop) — that's its difference from start_join.
            import threading as _threading

            def _retry_join(srv=server, addrs=list(cfg.server.retry_join),
                            interval=3.0 if cfg.dev_mode else 15.0):
                while True:
                    try:
                        if srv.serf_join(addrs) > 0:
                            print(f"==> Retry-join succeeded: {addrs}")
                            return
                    except Exception:  # noqa: BLE001 - keep retrying
                        pass
                    time.sleep(interval)

            _threading.Thread(target=_retry_join, daemon=True,
                              name="retry-join").start()
        mode = "dev mode" if cfg.dev_mode else "server"
        print(f"==> nomad-tpu agent started ({mode})! HTTP: {http.addr}")
        print(f"    Gossip: {serf_addr} (region {cfg.region})")
        print(f"    Scheduler factories: {scheduler_factories or 'cpu defaults'}")

    client_agent = None
    if cfg.client.enabled:
        servers = list(cfg.client.servers)
        if server_addr and server_addr not in servers:
            servers.insert(0, server_addr)
        # Keyed on the HTTP context, not the client one: an rpc-only
        # TLS rollout (tls { rpc=true http=false }) leaves the HTTP API
        # plaintext, and bare addresses must keep dialing http://.
        default_scheme = "https" if tls_http_ctx is not None else "http"
        servers = [s if "://" in s else f"{default_scheme}://{s}"
                   for s in servers]
        client_cfg = ClientConfig(
            servers=servers,
            region=cfg.region, datacenter=cfg.datacenter,
            node_name=node_name if cfg.name else "",
            node_class=cfg.client.node_class,
            options=dict(cfg.client.options),
            meta=dict(cfg.client.meta),
            dev_mode=cfg.dev_mode,
            consul_addr=cfg.consul.address,
            consul_service=cfg.consul.server_service_name,
            network_speed=cfg.client.network_speed,
            ssl_context=tls_client_ctx,
            chroot_env=dict(cfg.client.chroot_env) or None,
        )
        if cfg.client.reserved:
            from ..structs import Resources

            res = cfg.client.reserved
            client_cfg.reserved = Resources(
                cpu=int(res.get("cpu", 0)),
                memory_mb=int(res.get("memory", 0)),
                disk_mb=int(res.get("disk", 0)),
                iops=int(res.get("iops", 0)),
            )
        if cfg.client.state_dir:
            client_cfg.state_dir = cfg.client.state_dir
        elif cfg.data_dir:
            client_cfg.state_dir = os.path.join(cfg.data_dir, "client")
        if cfg.client.alloc_dir:
            client_cfg.alloc_dir = cfg.client.alloc_dir
        elif cfg.data_dir:
            client_cfg.alloc_dir = os.path.join(cfg.data_dir, "alloc")
        for d in (client_cfg.state_dir, client_cfg.alloc_dir):
            if d:
                os.makedirs(d, exist_ok=True)
        client_only = http is None
        if client_only:
            # Every agent serves HTTP (agent.go): a client-only node
            # still exposes its fs/logs/stats endpoints. Started before
            # the agent so the advertised port is known at registration.
            http = HTTPServer(None, host=cfg.bind_addr,
                              port=cfg.ports.http,
                              enable_debug=cfg.enable_debug,
                              ssl_context=tls_http_ctx,
                              forward_ssl_context=tls_client_ctx)
            http.start()
        # The node must register with a routable HTTP endpoint: peer
        # clients GET /v1/client/allocation/<id>/snapshot from it for
        # sticky-disk migration (client.go:1441 migrateRemoteAllocDir);
        # an empty http_addr makes every remote migration a no-op.
        client_cfg.http_addr = (
            f"{'https' if tls_http_ctx is not None else 'http'}://"
            f"{_advertise_addr(cfg)}:{http.port}")
        try:
            client_agent = ClientAgent(client_cfg)
            client_agent.start()
        except (ValueError, APIError) as e:
            print(f"error starting client: {e}", file=sys.stderr)
            if client_agent is not None:
                client_agent.shutdown()
            if http is not None:
                http.stop()
            if server is not None:
                server.shutdown()
            return 1
        # fs/stats endpoints are served off the co-located client.
        http.client = client_agent
        if client_only:
            print(f"==> nomad-tpu agent started (client)! HTTP: {http.addr}")
        print(f"    Client node: {client_agent.node.id}")

    # Agent-level consul registration: advertise this agent's HTTP
    # endpoint under the configured catalog services so clients can
    # bootstrap through discovery (consul/syncer.go agent services).
    agent_syncer = None
    if cfg.consul.address and cfg.consul.auto_advertise:
        from ..consul import ConsulAPI, ConsulService, ConsulSyncer

        consul_api = ConsulAPI(cfg.consul.address)
        agent_syncer = ConsulSyncer(consul_api, address=cfg.consul.address,
                                    instance=node_name)
        services = []
        if server is not None:
            services.append(ConsulService(
                name=cfg.consul.server_service_name, tags=["http"],
                port=http.port, address=_advertise_addr(cfg)))
            # Advertise the gossip endpoint too, and bootstrap-join
            # through the catalog when we know no peers
            # (server.go:398 setupBootstrapHandler).
            serf_port = int(serf_addr.rsplit(":", 1)[1])
            services.append(ConsulService(
                name=cfg.consul.server_service_name, tags=["serf"],
                port=serf_port, address=_advertise_addr(cfg)))
            from ..consul import serf_bootstrap
            import threading as _threading

            _threading.Thread(
                target=serf_bootstrap,
                args=(server, consul_api, cfg.consul.server_service_name),
                kwargs={"interval": 3.0 if cfg.dev_mode else 15.0,
                        "self_addr": f"{_advertise_addr(cfg)}:{serf_port}"},
                daemon=True, name="consul-serf-bootstrap",
            ).start()
        if client_agent is not None:
            services.append(ConsulService(
                name=cfg.consul.client_service_name, tags=["http"],
                port=http.port, address=_advertise_addr(cfg)))
        agent_syncer.set_services("agent", services)
        agent_syncer.start()

    try:
        while True:
            time.sleep(1)
    except KeyboardInterrupt:
        print("\n==> Caught interrupt, shutting down...")
        if client_agent is not None:
            client_agent.shutdown(destroy_allocs=cfg.dev_mode)
        if agent_syncer is not None:
            agent_syncer.shutdown()
        if http is not None:
            http.stop()
        if server is not None:
            server.shutdown()
        if raft_transport is not None:
            raft_transport.close()
    return 0


# ---------------------------------------------------------------- main


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="nomad-tpu", description="TPU-native cluster scheduler"
    )
    parser.add_argument("--address", default=None, help="agent HTTP address")
    parser.add_argument("--region", default=None,
                        help="target region (forwarded by the agent)")
    sub = parser.add_subparsers(dest="command", required=True)

    p = sub.add_parser("agent", help="run an agent")
    p.add_argument("-dev", dest="dev", action="store_true")
    p.add_argument("-config", dest="config", action="append", default=[],
                   help="config file or directory (repeatable; merged in order)")
    p.add_argument("-statsd", dest="statsd", default="", help="statsd UDP addr host:port")
    p.add_argument("-bind", dest="bind", default="")
    p.add_argument("-port", dest="port", type=int, default=0)
    p.add_argument("-serf-port", dest="serf_port", type=int, default=0)
    p.add_argument("-num-schedulers", dest="num_schedulers", type=int,
                   default=None)
    p.add_argument("-region", dest="region", default="")
    p.add_argument("-node-name", dest="node_name", default="",
                   help="unique agent name (default hostname-port)")
    p.add_argument("-join", dest="join", default="",
                   help="comma-separated gossip addrs to join at start")
    p.add_argument("-tpu", dest="tpu", action="store_true",
                   help="route service/batch evals to the TPU backend")
    p.add_argument("-migrate-max-parallel", dest="migrate_max_parallel",
                   type=int, default=None,
                   help="in-flight migration budget for drain storms "
                        "(0 = unbounded)")
    p.add_argument("-preemption", dest="preemption", action="store_true",
                   help="allow red-pressure priority preemption")
    p.add_argument("-defrag", dest="defrag", action="store_true",
                   help="enable the leader-side continuous "
                        "defragmentation loop (nomad_tpu/defrag)")
    p.add_argument("-consul", dest="consul", default="",
                   help="consul agent addr for service sync + discovery")
    p.add_argument("-advertise", dest="advertise", default="",
                   help="address advertised to consul (default: bind addr)")
    p.add_argument("-log-level", dest="log_level", default="")
    p.set_defaults(fn=cmd_agent)

    p = sub.add_parser("version", help="print version")
    p.set_defaults(fn=cmd_version)

    p = sub.add_parser("init", help="create an example job file")
    p.set_defaults(fn=cmd_init)

    p = sub.add_parser("validate", help="validate a job file")
    p.add_argument("file")
    p.set_defaults(fn=cmd_validate)

    p = sub.add_parser("run", help="run a job")
    p.add_argument("file")
    p.add_argument("-detach", dest="detach", action="store_true")
    p.add_argument("-check-index", dest="check_index", type=int, default=None)
    p.set_defaults(fn=cmd_run)

    p = sub.add_parser("plan", help="dry-run a job update")
    p.add_argument("file")
    p.add_argument("-verbose", dest="verbose", action="store_true")
    p.set_defaults(fn=cmd_plan)

    p = sub.add_parser("status", help="display job status")
    p.add_argument("job", nargs="?")
    p.set_defaults(fn=cmd_status)

    p = sub.add_parser("stop", help="stop a job")
    p.add_argument("job")
    p.add_argument("-detach", dest="detach", action="store_true")
    p.set_defaults(fn=cmd_stop)

    p = sub.add_parser("inspect", help="dump a job's definition")
    p.add_argument("job")
    p.set_defaults(fn=cmd_inspect)

    p = sub.add_parser("node-status", help="display node status")
    p.add_argument("node", nargs="?")
    p.set_defaults(fn=cmd_node_status)

    p = sub.add_parser("node-drain", help="toggle node drain mode")
    p.add_argument("node")
    p.add_argument("-enable", dest="enable", action="store_true")
    p.add_argument("-disable", dest="disable", action="store_true")
    p.set_defaults(fn=cmd_node_drain)

    p = sub.add_parser("alloc-status", help="display allocation status")
    p.add_argument("alloc")
    p.add_argument("-verbose", dest="verbose", action="store_true")
    p.set_defaults(fn=cmd_alloc_status)

    p = sub.add_parser("eval-status", help="display evaluation status")
    p.add_argument("eval")
    p.set_defaults(fn=cmd_eval_status)

    p = sub.add_parser("fs", help="browse an allocation's filesystem")
    p.add_argument("alloc")
    p.add_argument("path", nargs="?", default="/")
    p.add_argument("-stat", dest="stat", action="store_true")
    p.set_defaults(fn=cmd_fs)

    p = sub.add_parser("logs", help="stream a task's logs")
    p.add_argument("alloc")
    p.add_argument("task", nargs="?", default="")
    p.add_argument("-stderr", dest="stderr", action="store_true")
    p.add_argument("-f", dest="follow", action="store_true")
    p.add_argument("-tail", dest="tail", action="store_true")
    p.add_argument("-n", dest="n", type=int, default=0)
    p.set_defaults(fn=cmd_logs)

    p = sub.add_parser("server-members", help="display gossip pool members")
    p.set_defaults(fn=cmd_server_members)

    p = sub.add_parser("server-join", help="join the agent to a gossip pool")
    p.add_argument("addrs", nargs="+", help="gossip addresses host:port")
    p.set_defaults(fn=cmd_server_join)

    p = sub.add_parser("server-force-leave", help="force a member to leave")
    p.add_argument("node", help="member name")
    p.set_defaults(fn=cmd_server_force_leave)

    p = sub.add_parser("agent-info", help="display agent stats")
    p.set_defaults(fn=cmd_agent_info)

    return parser


def main(argv: Optional[List[str]] = None) -> int:
    parser = build_parser()
    args = parser.parse_args(argv)
    try:
        return args.fn(args)
    except APIError as e:
        print(f"Error: {e}", file=sys.stderr)
        return 1
    except (OSError, ValueError) as e:
        # unreadable job files, parse errors, connection failures
        print(f"Error: {e}", file=sys.stderr)
        return 1


if __name__ == "__main__":
    sys.exit(main())
