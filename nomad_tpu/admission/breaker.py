"""Device-path circuit breaker.

The dense placement path has exactly one expensive shared dependency:
the batched device dispatch (scheduler/batcher.py -> ops/binpack.py).
PR 3 gave it a *per-eval* recovery — a failed ``place()`` falls back to
the host iterators for that eval — but a persistently sick device path
(runtime wedged, tunnel congested, device OOM-looping) then pays the
failure latency on EVERY eval before falling back: the cluster limps at
fault-detection speed instead of host speed. The breaker turns N
consecutive per-eval failures into one routing decision.

States::

    closed ──(K consecutive failures OR M consecutive slow batches)──▶ open
    open ──(cool-down elapses; next acquire())──▶ half-open
    half-open ──(fast probe success)──▶ closed
    half-open ──(probe failure or slow probe)──▶ open   (cool-down re-arms)

- ``acquire()`` is the consuming gate at the device-dispatch call site
  (scheduler/tpu.py): CLOSED always grants; OPEN grants nothing until
  the cool-down elapses, then transitions to HALF_OPEN and grants ONE
  probe; HALF_OPEN grants only while no probe is in flight. Every
  grant must be followed by exactly one ``record_success`` /
  ``record_failure``.
- ``should_route_host()`` is the non-consuming *routing hint* for the
  dispatch pipeline's launch prologue: True only while OPEN inside the
  cool-down, so whole batches skip matrix build + cohort announcement
  without burning the half-open probe budget.
- a *slow batch* (``record_success`` with ``duration_ms >= slow_ms``)
  counts toward its own consecutive-trip threshold: a device that
  still answers but at 10x latency is an overload signal, not a
  success. A slow HALF_OPEN probe re-opens.

The instance is process-global (``get_breaker()``) for the same reason
the placement batcher is: it guards the one shared device path, and
every scheduler thread must see the same verdict.
"""

from __future__ import annotations

import threading
import time
from typing import List, Optional

from ..utils import metrics

BREAKER_CLOSED = "closed"
BREAKER_OPEN = "open"
BREAKER_HALF_OPEN = "half-open"

_LEVELS = {BREAKER_CLOSED: 0, BREAKER_HALF_OPEN: 1, BREAKER_OPEN: 2}
_TRANSITION_CAP = 16  # bounded transition ring (drop-oldest)


class CircuitBreaker:
    def __init__(self, failure_threshold: int = 5, slow_ms: float = 0.0,
                 slow_batches: int = 8, cooldown: float = 5.0,
                 enabled: bool = True):
        # RLock: helper methods re-acquire so every guarded access is
        # lexically under the lock (ntalint guarded-by discipline).
        self._lock = threading.RLock()
        # Thresholds are written only by configure() (operator/boot
        # path) and read on the hot path; plain attributes like
        # chaos.enabled — a racing read sees old or new, either fine.
        self.enabled = enabled
        self.failure_threshold = max(1, failure_threshold)
        self.slow_ms = slow_ms  # 0 disables slow-batch trips
        self.slow_batches = max(1, slow_batches)
        self.cooldown = cooldown

        self._state = BREAKER_CLOSED  # guarded-by: _lock
        self._opened_at = 0.0  # guarded-by: _lock
        self._probe_inflight = False  # guarded-by: _lock
        self._consec_failures = 0  # guarded-by: _lock
        self._consec_slow = 0  # guarded-by: _lock
        self.trips = 0  # guarded-by: _lock
        self.half_opens = 0  # guarded-by: _lock
        self.recloses = 0  # guarded-by: _lock
        self.rejected = 0  # guarded-by: _lock (acquire() denials)
        self.successes = 0  # guarded-by: _lock
        self.failures = 0  # guarded-by: _lock
        self.slow = 0  # guarded-by: _lock
        # Bounded transition log (slot writes, drop-oldest): the soak
        # asserts the open -> half-open -> closed sequence from here.
        self._transitions: List[Optional[tuple]] = (
            [None] * _TRANSITION_CAP)  # guarded-by: _lock
        self._transition_idx = 0  # guarded-by: _lock

    # ----------------------------------------------------- transitions

    def _set_state_locked(self, new: str) -> None:
        """Record a state change; callers hold _lock (RLock re-entry
        keeps the guarded accesses lexically locked)."""
        with self._lock:
            old = self._state
            if old == new:
                return
            self._state = new
            self._transitions[self._transition_idx % _TRANSITION_CAP] = (
                time.time(), old, new)
            self._transition_idx += 1
        metrics.set_gauge(("admission", "breaker_state"), _LEVELS[new])

    def _trip_locked(self, reason: str) -> None:
        with self._lock:
            self.trips += 1
            self._opened_at = time.monotonic()
            self._probe_inflight = False
            self._consec_failures = 0
            self._consec_slow = 0
            self._set_state_locked(BREAKER_OPEN)
        metrics.incr_counter(("admission", "breaker_trip"))

    # ------------------------------------------------------------ gate

    def acquire(self) -> bool:
        """Consuming gate at the device-dispatch call site. A True
        return MUST be matched by exactly one record_success /
        record_failure (the half-open probe slot is held until then)."""
        if not self.enabled:
            return True
        with self._lock:
            if self._state == BREAKER_CLOSED:
                return True
            if self._state == BREAKER_OPEN:
                if time.monotonic() - self._opened_at < self.cooldown:
                    self.rejected += 1
                    return False
                # Cool-down over: half-open, this caller is the probe.
                self.half_opens += 1
                self._probe_inflight = True
                self._set_state_locked(BREAKER_HALF_OPEN)
                return True
            # HALF_OPEN: one probe at a time.
            if self._probe_inflight:
                self.rejected += 1
                return False
            self._probe_inflight = True
            return True

    def should_route_host(self) -> bool:
        """Non-consuming routing hint for the dispatch pipeline: True
        only while OPEN inside the cool-down. Once the cool-down
        elapses this returns False so dense-path traffic reaches the
        acquire() gate and one eval probes."""
        if not self.enabled:
            return False
        with self._lock:
            return (self._state == BREAKER_OPEN
                    and time.monotonic() - self._opened_at < self.cooldown)

    # --------------------------------------------------------- results

    def record_success(self, duration_ms: float = 0.0) -> None:
        if not self.enabled:
            return
        slow = bool(self.slow_ms and duration_ms >= self.slow_ms)
        with self._lock:
            self.successes += 1
            if self._state == BREAKER_HALF_OPEN:
                self._probe_inflight = False
                if slow:
                    # The device answered the probe but at overload
                    # latency: that is not recovery — re-open.
                    self.slow += 1
                    self._trip_locked("slow probe")
                    return
                self.recloses += 1
                self._consec_failures = 0
                self._consec_slow = 0
                self._set_state_locked(BREAKER_CLOSED)
                return
            self._consec_failures = 0
            if slow:
                self.slow += 1
                self._consec_slow += 1
                if self._consec_slow >= self.slow_batches:
                    self._trip_locked("consecutive slow batches")
            else:
                self._consec_slow = 0

    def record_failure(self) -> None:
        if not self.enabled:
            return
        with self._lock:
            self.failures += 1
            if self._state == BREAKER_HALF_OPEN:
                # The probe failed: straight back to open.
                self._probe_inflight = False
                self._trip_locked("probe failure")
                return
            self._consec_failures += 1
            if (self._state == BREAKER_CLOSED
                    and self._consec_failures >= self.failure_threshold):
                self._trip_locked("consecutive failures")

    # ----------------------------------------------------- observation

    def state(self) -> str:
        with self._lock:
            return self._state

    def transitions(self) -> List[tuple]:
        """(wall time, from, to) transitions, oldest first (bounded)."""
        with self._lock:
            n = min(self._transition_idx, _TRANSITION_CAP)
            start = self._transition_idx - n
            return [self._transitions[(start + k) % _TRANSITION_CAP]
                    for k in range(n)]

    def stats(self) -> dict:
        with self._lock:
            return {
                "enabled": self.enabled,
                "state": self._state,
                "failure_threshold": self.failure_threshold,
                "slow_ms": self.slow_ms,
                "slow_batches": self.slow_batches,
                "cooldown": self.cooldown,
                "consecutive_failures": self._consec_failures,
                "consecutive_slow": self._consec_slow,
                "probe_inflight": self._probe_inflight,
                "trips": self.trips,
                "half_opens": self.half_opens,
                "recloses": self.recloses,
                "rejected": self.rejected,
                "successes": self.successes,
                "failures": self.failures,
                "slow": self.slow,
                "transitions": [
                    {"at": round(t, 3), "from": a, "to": b}
                    for (t, a, b) in (
                        tr for tr in self._transitions if tr is not None)
                ],
            }

    # --------------------------------------------------------- control

    def configure(self, failure_threshold: Optional[int] = None,
                  slow_ms: Optional[float] = None,
                  slow_batches: Optional[int] = None,
                  cooldown: Optional[float] = None,
                  enabled: Optional[bool] = None) -> None:
        """Update thresholds in place (server boot / operator retune).
        Keeps current state and counters — reconfiguring a live breaker
        must not silently un-trip it; use reset() for that."""
        if failure_threshold is not None:
            self.failure_threshold = max(1, failure_threshold)
        if slow_ms is not None:
            self.slow_ms = slow_ms
        if slow_batches is not None:
            self.slow_batches = max(1, slow_batches)
        if cooldown is not None:
            self.cooldown = cooldown
        if enabled is not None:
            self.enabled = enabled

    def configure_defaults(self) -> None:
        """Restore the constructor-default thresholds (test-isolation
        helper for the process-global singleton: fixtures restoring
        the breaker must not hand-copy the defaults — a drifted copy
        silently reconfigures every later test)."""
        d = CircuitBreaker()
        self.configure(failure_threshold=d.failure_threshold,
                       slow_ms=d.slow_ms, slow_batches=d.slow_batches,
                       cooldown=d.cooldown, enabled=d.enabled)

    def reset(self) -> None:
        """Back to closed with zeroed counters (tests; operator
        override after a confirmed repair)."""
        with self._lock:
            self._state = BREAKER_CLOSED
            self._opened_at = 0.0
            self._probe_inflight = False
            self._consec_failures = 0
            self._consec_slow = 0
            self.trips = 0
            self.half_opens = 0
            self.recloses = 0
            self.rejected = 0
            self.successes = 0
            self.failures = 0
            self.slow = 0
            self._transitions = [None] * _TRANSITION_CAP
            self._transition_idx = 0


# Process-global instance: the breaker guards the ONE shared device
# path, so every scheduler/pipeline thread must see the same verdict
# (the placement batcher is global for the same reason).
_global = CircuitBreaker()


def get_breaker() -> CircuitBreaker:
    return _global
