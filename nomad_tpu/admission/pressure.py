"""Pressure monitor: one green/yellow/red overload level for the
control plane.

Inputs (all already maintained by other subsystems — the monitor only
reads):

- **broker depth** — per-queue ready + unacked + blocked counts from
  the EvalBroker. CAPPED queues are measured as a fraction of their
  summed budget (yellow at ``ready_frac_yellow``, red at
  ``ready_frac_red``); everything outside a cap — uncapped queues'
  ready, unacked, blocked — is judged by the absolute
  ``depth_yellow`` / ``depth_red`` thresholds, so a deliberately
  unbounded queue's backlog neither reads as false cap pressure nor
  hides from the monitor.
- **dispatch saturation** — the central pipeline's in-flight slots and
  pending accumulator depth: every slot busy AND a full batch already
  waiting is yellow; pending at 2x a full batch is red.
- **rolling e2e p99** — the flight recorder's end-to-end latency
  p99 (trace/recorder.py) against the ``p99_yellow_ms`` /
  ``p99_red_ms`` thresholds (0 disables this input — the default,
  since absolute latency is deployment-specific).
- **hot-lock wait p99** — the contention observatory's worst
  per-site contended acquire-wait p99 (nomad_tpu/profile) against
  ``admission_lock_wait_yellow_ms`` / ``_red_ms`` (0 disables, the
  default). When it fires, the reason NAMES the hottest lock site —
  "why are we shedding" can now answer "the broker lock convoys".

The level is the MAX of the inputs' contributions; ``reasons`` names
which input(s) drove it, so ``/v1/agent/self`` answers "why are we
shedding" directly. Snapshots are cached for ``CACHE_TTL`` so the
admission check on every HTTP request costs an attribute read + a
cache hit, not four stats() calls.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from .. import trace
from ..utils import metrics

LEVEL_GREEN = "green"
LEVEL_YELLOW = "yellow"
LEVEL_RED = "red"
LEVEL_NUM = {LEVEL_GREEN: 0, LEVEL_YELLOW: 1, LEVEL_RED: 2}


class PressureMonitor:
    CACHE_TTL = 0.25

    def __init__(self, server, config):
        self.server = server
        # Thresholds: read-mostly plain attributes (set at boot).
        self.ready_frac_yellow = 0.75
        self.ready_frac_red = 0.95
        self.depth_yellow = config.admission_depth_yellow
        self.depth_red = config.admission_depth_red
        self.p99_yellow_ms = config.admission_p99_yellow_ms
        self.p99_red_ms = config.admission_p99_red_ms
        self.lock_wait_yellow_ms = getattr(
            config, "admission_lock_wait_yellow_ms", 0.0)
        self.lock_wait_red_ms = getattr(
            config, "admission_lock_wait_red_ms", 0.0)
        self._lock = threading.RLock()
        self._cached: Optional[dict] = None  # guarded-by: _lock
        self._cached_at = 0.0  # guarded-by: _lock

    # ------------------------------------------------------------ read

    def level(self) -> str:
        return self.snapshot()["level"]

    def snapshot(self, refresh: bool = False) -> dict:
        now = time.monotonic()
        with self._lock:
            if (not refresh and self._cached is not None
                    and now - self._cached_at < self.CACHE_TTL):
                return self._cached
        # Compute OUTSIDE the lock: the inputs take the broker/pipeline
        # locks and holding ours across them would nest lock orders for
        # no benefit; a racing duplicate compute is harmless.
        snap = self._compute()
        with self._lock:
            self._cached = snap
            self._cached_at = time.monotonic()
        metrics.set_gauge(("admission", "pressure_level"),
                          snap["level_num"])
        return snap

    # --------------------------------------------------------- compute

    def _capped_depth(self, ready_by_queue: dict) -> tuple:
        """(capped_ready, cap_total): the summed depth of the CAPPED
        queues only, against their summed budget. An uncapped queue's
        backlog must not count against the capped budget — with e.g.
        only 'service' capped, a burst of deliberately-unbounded batch
        evals would otherwise read as >100% of a cap it never
        consumes, driving a false red that sheds healthy traffic.
        Uncapped queues are judged by the absolute depth thresholds
        instead."""
        cfg = self.server.config
        caps = cfg.eval_ready_caps
        default = cfg.eval_ready_cap
        capped_ready = 0
        cap_total = 0
        # Per-type overrides outside enabled_schedulers still bound
        # real queues; the union covers them.
        for sched in set(cfg.enabled_schedulers) | set(caps):
            cap = caps.get(sched, default)
            if cap > 0:
                cap_total += cap
                capped_ready += ready_by_queue.get(sched, 0)
        return capped_ready, cap_total

    def _compute(self) -> dict:
        broker = self.server.broker.stats()
        ready = broker["total_ready"]
        unacked = broker["total_unacked"]
        blocked = broker.get("total_blocked", 0)
        dispatch = self.server.dispatch.stats()
        p99_ms = trace.get_recorder().e2e_p99()

        level = LEVEL_GREEN
        reasons = []

        def bump(new_level: str, reason: str) -> None:
            nonlocal level
            reasons.append(reason)
            if LEVEL_NUM[new_level] > LEVEL_NUM[level]:
                level = new_level

        capped_ready, cap = self._capped_depth(
            broker.get("ready_by_queue", {}))
        if cap > 0:
            frac = capped_ready / cap
            if frac >= self.ready_frac_red:
                bump(LEVEL_RED, f"ready depth {capped_ready}/{cap} >= "
                                f"{self.ready_frac_red:.0%} of cap")
            elif frac >= self.ready_frac_yellow:
                bump(LEVEL_YELLOW,
                     f"ready depth {capped_ready}/{cap} >= "
                     f"{self.ready_frac_yellow:.0%} of cap")
        # Uncapped backlog (ready outside any cap, unacked, blocked)
        # is judged by the absolute thresholds — regardless of whether
        # caps exist elsewhere, so a mixed config can't hide depth in
        # its unbounded queues.
        depth = (ready - capped_ready) + unacked + blocked
        if self.depth_red and depth >= self.depth_red:
            bump(LEVEL_RED,
                 f"broker depth {depth} >= {self.depth_red}")
        elif self.depth_yellow and depth >= self.depth_yellow:
            bump(LEVEL_YELLOW,
                 f"broker depth {depth} >= {self.depth_yellow}")

        if dispatch.get("enabled"):
            in_flight = dispatch["in_flight"]
            pending = dispatch["pending"]
            max_batch = max(1, dispatch["max_batch"])
            saturated = (in_flight >= self.server.dispatch.max_inflight
                         and pending >= max_batch)
            if saturated and pending >= 2 * max_batch:
                bump(LEVEL_RED,
                     f"dispatch saturated: {in_flight} in flight, "
                     f"{pending} pending (>= 2x batch)")
            elif saturated:
                bump(LEVEL_YELLOW,
                     f"dispatch saturated: {in_flight} in flight, "
                     f"{pending} pending")

        if self.p99_red_ms and p99_ms >= self.p99_red_ms:
            bump(LEVEL_RED,
                 f"e2e p99 {p99_ms:.1f}ms >= {self.p99_red_ms:.1f}ms")
        elif self.p99_yellow_ms and p99_ms >= self.p99_yellow_ms:
            bump(LEVEL_YELLOW,
                 f"e2e p99 {p99_ms:.1f}ms >= {self.p99_yellow_ms:.1f}ms")

        # Hot-lock contention (nomad_tpu/profile): the worst per-site
        # contended acquire-wait p99. Always reported in inputs; only
        # drives the level when thresholds are configured — and then
        # the reason cites the SITE, so yellow/red explains itself.
        lock_p99, lock_site = self._hottest_lock()
        if self.lock_wait_red_ms and lock_p99 >= self.lock_wait_red_ms:
            bump(LEVEL_RED,
                 f"lock wait p99 {lock_p99:.1f}ms on {lock_site!r} >= "
                 f"{self.lock_wait_red_ms:.1f}ms")
        elif (self.lock_wait_yellow_ms
              and lock_p99 >= self.lock_wait_yellow_ms):
            bump(LEVEL_YELLOW,
                 f"lock wait p99 {lock_p99:.1f}ms on {lock_site!r} >= "
                 f"{self.lock_wait_yellow_ms:.1f}ms")

        return {
            "level": level,
            "level_num": LEVEL_NUM[level],
            "reasons": reasons,
            "inputs": {
                "ready": ready,
                "ready_capped": capped_ready,
                "ready_cap_total": cap,
                "unacked": unacked,
                "blocked": blocked,
                "shed": broker.get("shed", 0),
                "expired": broker.get("expired", 0),
                "dispatch_in_flight": dispatch.get("in_flight", 0),
                "dispatch_pending": dispatch.get("pending", 0),
                "e2e_p99_ms": round(p99_ms, 3),
                "lock_wait_p99_ms": round(lock_p99, 3),
                "lock_wait_site": lock_site,
            },
        }

    @staticmethod
    def _hottest_lock() -> tuple:
        """(worst contended acquire-wait p99 in ms, its site name)
        across every profiled lock site."""
        from ..profile import get_profiler
        from ..utils.metrics import hist_percentile

        worst, site = 0.0, ""
        buckets = get_profiler().lock_site_buckets("wait")
        for name, (count, _total, dense) in buckets.items():
            p99 = hist_percentile(dense, count, 0.99)
            if p99 > worst:
                worst, site = p99, name
        return worst, site
