"""Deadline derivation for evaluations.

An eval's deadline is stamped once, at creation time (the server's
eval_update funnel — every fresh pending eval passes through it before
the FSM commit that enqueues it), as an absolute wall-clock instant::

    deadline = now + ttl * priority_factor(priority)

The factor scales the configured base TTL by priority so that under
sustained overload the work that survives queueing longest is the work
the operator ranked highest: priority 50 (the default) gets exactly
the base TTL, priority 100 gets 1.5x, priority 1 about 0.5x, and core
jobs (priority 200) 2.5x. The floor keeps a pathological priority from
producing an already-expired stamp.

Consumers:

- the broker skips expired evals at dequeue (stamping
  ``EVAL_TRIGGER_EXPIRED`` onto the failed-queue copy, exactly once);
- the dispatch pipeline drops expired evals at batch launch, BEFORE
  any matrix build, so stale work never burns a device lane.

Wall clock (``time.time``), not monotonic: deadlines replicate through
raft to followers whose monotonic clocks share no epoch.
"""

from __future__ import annotations

import time
from typing import Optional

from ..structs import consts

_FACTOR_FLOOR = 0.25


def priority_factor(priority: int) -> float:
    """0.25..2.5 multiplier on the base TTL (1.0 at default priority).
    Linear in priority: factor = 0.5 + priority/100."""
    return max(_FACTOR_FLOOR, 0.5 + priority / 100.0)


def deadline_for(priority: int, ttl: float,
                 now: Optional[float] = None) -> float:
    """Absolute wall-clock deadline for a fresh eval; 0.0 when
    deadlines are disabled (ttl <= 0)."""
    if ttl <= 0:
        return 0.0
    if now is None:
        now = time.time()
    return now + ttl * priority_factor(priority)


def stamp(ev, ttl: float, now: Optional[float] = None) -> None:
    """Stamp `ev` if it is a fresh pending/blocked eval without a
    deadline. Terminal or already-stamped evals pass through untouched
    (status updates re-commit existing evals through the same
    funnel)."""
    if ttl <= 0 or ev.deadline:
        return
    if ev.status not in (consts.EVAL_STATUS_PENDING,
                         consts.EVAL_STATUS_BLOCKED):
        return
    ev.deadline = deadline_for(ev.priority, ttl, now)
