"""Overload protection for the control plane (see README.md here).

Four cooperating pieces:

- :mod:`pressure` — one green/yellow/red overload level computed from
  broker depth, dispatch saturation, and the flight recorder's rolling
  e2e p99;
- :mod:`limiter` — token-bucket admission control on the HTTP/RPC
  intake, thresholds driven by the pressure level;
- :mod:`breaker` — the device-path circuit breaker (closed/open/
  half-open) that trips the dense path to the host iterators after
  consecutive failures or slow batches;
- :mod:`deadline` — priority-scaled eval deadlines, enforced at broker
  dequeue and dispatch-pipeline launch.

The bounded-queue shed policy itself lives in the broker
(server/broker.py): shedding must happen under the broker lock, where
the queues are.
"""

from .breaker import (  # noqa: F401
    BREAKER_CLOSED,
    BREAKER_HALF_OPEN,
    BREAKER_OPEN,
    CircuitBreaker,
    get_breaker,
)
from .deadline import deadline_for, priority_factor, stamp  # noqa: F401
from .limiter import (  # noqa: F401
    ROUTE_EXEMPT,
    ROUTE_READ,
    ROUTE_WRITE,
    RPC_EXEMPT_KINDS,
    AdmissionController,
    AdmissionRejected,
    TokenBucket,
    classify_http,
)
from .pressure import (  # noqa: F401
    LEVEL_GREEN,
    LEVEL_NUM,
    LEVEL_RED,
    LEVEL_YELLOW,
    PressureMonitor,
)
