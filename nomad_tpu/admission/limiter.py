"""Token-bucket admission control for the API surfaces.

Route classes and policy (driven by the pressure level,
pressure.py)::

    class    green   yellow                  red
    exempt   pass    pass                    pass
    write    pass    write bucket (429)      shed outright (503)
    read     pass    pass                    read bucket (429)

- **exempt**: leader-forward internals (``/v1/internal/*``), raft RPC
  kinds, client control traffic (node register/heartbeat/status/alloc
  updates — shedding those converts overload into node-down cascades,
  which makes overload WORSE), and the observability surfaces
  (``/v1/agent/*``, ``/v1/metrics``, ``/v1/status/*``) an operator
  needs precisely while the server is melting.
- **write**: job submissions/evaluations and other mutations — the
  traffic that grows broker depth.
- **read**: everything else.

Rejections carry a machine-readable ``Retry-After`` (seconds): under
yellow it is the token-bucket refill deficit, under red the configured
back-off hint. 429 = rate-limited (retry at the hint), 503 = shed
(pressure red; the server is protecting goodput).
"""

from __future__ import annotations

import threading
import time
from typing import Optional, Tuple

from ..utils import metrics
from .breaker import get_breaker
from .pressure import LEVEL_GREEN, LEVEL_RED, LEVEL_YELLOW, PressureMonitor

ROUTE_EXEMPT = "exempt"
ROUTE_WRITE = "write"
ROUTE_READ = "read"

# Raft consensus + leader-forward RPC kinds on the TCP transport: the
# cluster's own control traffic is never shed (shedding append_entries
# would turn overload into leader loss).
RPC_EXEMPT_KINDS = frozenset({
    "request_vote", "append_entries", "install_snapshot", "forward_apply",
})

# HTTP handler names (api/http.py) that are client control traffic.
_CLIENT_CONTROL_HANDLERS = frozenset({
    "node_register", "node_heartbeat", "node_status", "node_update_allocs",
    "node_derive_vault", "vault_renew",
})

_EXEMPT_PREFIXES = ("/v1/internal/", "/v1/agent/", "/v1/status/",
                    "/debug/")
_EXEMPT_PATHS = ("/v1/metrics", "/v1/regions")

_WRITE_METHODS = frozenset({"PUT", "POST", "DELETE"})


class AdmissionRejected(Exception):
    """Raised by the admission checks; the HTTP layer converts it to a
    429/503 response with a Retry-After header, the RPC layer to a
    structured error frame."""

    def __init__(self, status: int, message: str, retry_after: float):
        super().__init__(message)
        self.status = status
        self.message = message
        self.retry_after = retry_after


class TokenBucket:
    """Classic token bucket: `rate` tokens/second refill up to `burst`.
    try_acquire never sleeps — it returns the refill deficit as a
    Retry-After hint instead, so no handler thread parks on admission."""

    def __init__(self, rate: float, burst: float):
        self._lock = threading.RLock()
        self.rate = float(rate)
        self.burst = float(burst)
        self._tokens = float(burst)  # guarded-by: _lock
        self._last = time.monotonic()  # guarded-by: _lock
        self.granted = 0  # guarded-by: _lock
        self.rejected = 0  # guarded-by: _lock

    def try_acquire(self, n: float = 1.0) -> Tuple[bool, float]:
        """(granted, retry_after_seconds)."""
        with self._lock:
            now = time.monotonic()
            self._tokens = min(
                self.burst, self._tokens + (now - self._last) * self.rate)
            self._last = now
            if self._tokens >= n:
                self._tokens -= n
                self.granted += 1
                return True, 0.0
            self.rejected += 1
            deficit = n - self._tokens
            return False, (deficit / self.rate if self.rate > 0 else 1.0)

    def stats(self) -> dict:
        with self._lock:
            return {
                "rate": self.rate,
                "burst": self.burst,
                "tokens": round(self._tokens, 3),
                "granted": self.granted,
                "rejected": self.rejected,
            }


def classify_http(method: str, path: str, handler_name: str = "") -> str:
    """Route class for one HTTP request (see module docstring)."""
    if path in _EXEMPT_PATHS or any(
            path.startswith(p) for p in _EXEMPT_PREFIXES):
        return ROUTE_EXEMPT
    if handler_name in _CLIENT_CONTROL_HANDLERS:
        return ROUTE_EXEMPT
    if method in _WRITE_METHODS:
        return ROUTE_WRITE
    return ROUTE_READ


class AdmissionController:
    """Glue object the Server owns: pressure monitor + per-class token
    buckets + the (global) device-path breaker, plus the check_* entry
    points the HTTP/RPC layers call."""

    def __init__(self, server, config):
        self.enabled = bool(config.admission_enabled)
        # Kept for the read-degradation probe: a red-limited read can
        # be downgraded to a stale local-replica serve instead of a 429
        # when this server has replica state to serve from.
        self._server = server
        self.pressure = PressureMonitor(server, config)
        self._write = TokenBucket(config.admission_write_rate,
                                  config.admission_write_burst)
        self._read = TokenBucket(config.admission_read_rate,
                                 config.admission_read_burst)
        self.red_retry_after = config.admission_red_retry_after
        self._lock = threading.RLock()
        self.http_rejected = 0  # guarded-by: _lock
        self.rpc_rejected = 0  # guarded-by: _lock
        # Operator/test override: force a level regardless of inputs
        # (the ops analog of a load-shedding kill switch).
        self._forced_level: Optional[str] = None  # guarded-by: _lock

    # --------------------------------------------------------- control

    def force_level(self, level: Optional[str]) -> None:
        with self._lock:
            self._forced_level = level

    def level(self) -> str:
        """The effective pressure level (forced override included) —
        the public probe the preemption policy (nomad_tpu/migrate)
        and operators read."""
        return self._level()

    def _level(self) -> str:
        with self._lock:
            forced = self._forced_level
        return forced if forced is not None else self.pressure.level()

    # ---------------------------------------------------------- checks

    def check_http(self, method: str, path: str,
                   handler_name: str = "") -> Optional[str]:
        """Admission gate for one HTTP request: returns None on admit,
        returns the verdict "stale" to degrade a red-pressure read to
        stale local-replica serving, raises AdmissionRejected on
        shed/limit."""
        if not self.enabled:
            return None
        route_class = classify_http(method, path, handler_name)
        if route_class == ROUTE_EXEMPT:
            return
        level = self._level()
        if level == LEVEL_GREEN:
            return
        if route_class == ROUTE_WRITE:
            if level == LEVEL_RED:
                self._reject_http()
                raise AdmissionRejected(
                    503,
                    "server overloaded (pressure red): write shed",
                    self.red_retry_after)
            ok, retry = self._write.try_acquire()
            if not ok:
                self._reject_http()
                raise AdmissionRejected(
                    429,
                    "write rate limited (pressure yellow)",
                    max(retry, 0.05))
            return
        # Reads are limited only under red.
        if level == LEVEL_RED:
            ok, retry = self._read.try_acquire()
            if not ok:
                if self._has_replica_state():
                    # Degrade, don't deny: over-budget red reads serve
                    # the local replica in stale mode (http.py injects
                    # ?stale and stamps X-Nomad-Degraded) — a bounded-
                    # staleness answer beats a 429 when state exists.
                    return "stale"
                self._reject_http()
                raise AdmissionRejected(
                    429, "read rate limited (pressure red)",
                    max(retry, 0.05))
        return None

    def _has_replica_state(self) -> bool:
        """True when this server holds a replica snapshot worth serving
        stale reads from. The getattr chain tolerates the stub servers
        tests hand to AdmissionController (no fsm → old 429 path)."""
        state = getattr(getattr(self._server, "fsm", None), "state", None)
        if state is None:
            return False
        try:
            return state.latest_index() > 0
        except Exception:  # noqa: BLE001
            return False

    def check_rpc(self, kind: str) -> None:
        """Admission gate for one transport RPC frame. Raft consensus
        and leader-forward kinds are exempt unconditionally."""
        if not self.enabled or kind in RPC_EXEMPT_KINDS:
            return
        level = self._level()
        if level == LEVEL_GREEN:
            return
        if level == LEVEL_RED:
            with self._lock:
                self.rpc_rejected += 1
            metrics.incr_counter(("admission", "rpc_rejected"))
            raise AdmissionRejected(
                503, f"server overloaded (pressure red): rpc "
                     f"{kind!r} shed", self.red_retry_after)
        ok, retry = self._write.try_acquire()
        if not ok:
            with self._lock:
                self.rpc_rejected += 1
            metrics.incr_counter(("admission", "rpc_rejected"))
            raise AdmissionRejected(
                429, f"rpc {kind!r} rate limited (pressure yellow)",
                max(retry, 0.05))

    def _reject_http(self) -> None:
        with self._lock:
            self.http_rejected += 1
        metrics.incr_counter(("admission", "http_rejected"))

    # ----------------------------------------------------- observation

    def snapshot(self) -> dict:
        with self._lock:
            http_rejected = self.http_rejected
            rpc_rejected = self.rpc_rejected
            forced = self._forced_level
        out = {
            "enabled": self.enabled,
            "pressure": self.pressure.snapshot(),
            "write_bucket": self._write.stats(),
            "read_bucket": self._read.stats(),
            "http_rejected": http_rejected,
            "rpc_rejected": rpc_rejected,
            "breaker": get_breaker().stats(),
        }
        if forced is not None:
            out["forced_level"] = forced
        return out
