"""Lock-order deadlock detector.

Rule ``deadlock-cycle``: build the whole-program lock-acquisition-
order graph and report every cycle between DISTINCT locks with a full
witness path.

An edge A -> B exists when code holding A acquires B, either

- lexically (``with self._a: ... with self._b:``), or
- through the call graph: ``with self._a:`` encloses a call whose
  whole-program closure (core.Program — the same "reachable from"
  every manifest rule uses) contains a ``with``-acquisition of B. The
  classic two-thread wrap-around needs no nesting in any single
  function: broker holds its lock and calls into the recorder; a
  recorder path holding its stripe lock calls back into the broker —
  each function looks innocent, the cycle only exists cross-module.

Lock identity is the DECLARATION site: ``(module, class, attr)`` for
``self._lock = threading.Lock()`` in ``__init__``, ``(module, '',
name)`` for module-level locks. ``Condition(self._lock)`` aliases the
condition to its backing lock (holding either is holding the same
lock), so a cond-vs-its-lock pair can never produce a spurious
two-node cycle.

Deliberate precision choices:

- Self-edges (re-acquiring the lock you hold) are NOT reported: the
  call graph over-approximates (a helper called both with and without
  the lock held would self-edge), and the codebase's RLocks make
  re-entry legal. Cycles require >= 2 distinct locks.
- Nested ``def`` bodies are excluded from both the held-walk and the
  acquisition summaries: they run on whatever thread calls them,
  under that thread's locks, not these.
- References handed to pools/``Thread(target=...)`` are not calls and
  are not followed (consistent with every other ntalint rule).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .core import Finding, FnKey, Module, Program
from .locks import _ctor_kind, _self_attr

RULE_DEADLOCK = "deadlock-cycle"

# (module rel, class name or "", attribute/name)
LockKey = Tuple[str, str, str]


def _display(lock: LockKey) -> str:
    rel, cls, attr = lock
    short = rel.rsplit("/", 1)[-1]
    return f"{short}::{cls}.{attr}" if cls else f"{short}::{attr}"


class _Registry:
    """Every lock declaration in the program, with cond->lock
    aliasing resolved at registration."""

    def __init__(self, program: Program):
        self.program = program
        self.module_locks: Dict[str, Dict[str, LockKey]] = {}
        self.class_locks: Dict[Tuple[str, str], Dict[str, LockKey]] = {}
        for mod in program.modules:
            self._scan(mod)

    def _scan(self, mod: Module) -> None:
        mlocks = self.module_locks.setdefault(mod.rel, {})
        for node in mod.tree.body:
            if isinstance(node, ast.Assign) and isinstance(
                    node.value, ast.Call):
                kind = _ctor_kind(node.value)
                if kind is None:
                    continue
                for tgt in node.targets:
                    if not isinstance(tgt, ast.Name):
                        continue
                    backing = None
                    if kind == "cond" and node.value.args:
                        arg = node.value.args[0]
                        if isinstance(arg, ast.Name):
                            backing = arg.id
                    mlocks[tgt.id] = (mod.rel, "", backing or tgt.id)
            elif isinstance(node, ast.ClassDef):
                self._scan_class(mod, node)

    def _scan_class(self, mod: Module, cls: ast.ClassDef) -> None:
        locks = self.class_locks.setdefault((mod.rel, cls.name), {})
        for sub in cls.body:
            if not isinstance(sub, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                continue
            if sub.name != "__init__":
                continue
            for stmt in ast.walk(sub):
                if not isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                    continue
                targets = (stmt.targets if isinstance(stmt, ast.Assign)
                           else [stmt.target])
                value = stmt.value
                if not isinstance(value, ast.Call):
                    continue
                kind = _ctor_kind(value)
                if kind is None:
                    continue
                for tgt in targets:
                    attr = _self_attr(tgt)
                    if attr is None:
                        continue
                    backing = None
                    if kind == "cond" and value.args:
                        backing = _self_attr(value.args[0])
                    locks[attr] = (mod.rel, cls.name, backing or attr)

    def resolve(self, rel: str, cls: Optional[str],
                expr: ast.AST) -> Optional[LockKey]:
        """LockKey for a with-item expression: self.X, module NAME,
        or self.<typed attr>.X through Program.attr_types."""
        attr = _self_attr(expr)
        if attr is not None and cls is not None:
            return self.class_locks.get((rel, cls), {}).get(attr)
        if isinstance(expr, ast.Name):
            return self.module_locks.get(rel, {}).get(expr.id)
        if (isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Attribute)):
            owner = _self_attr(expr.value)
            if owner is not None and cls is not None:
                t = self.program.attr_types.get((rel, cls), {}).get(owner)
                if t is not None:
                    return self.class_locks.get(t, {}).get(expr.attr)
        return None


class _Summary:
    __slots__ = ("acquires", "calls", "calls_under_lock")

    def __init__(self):
        # direct `with` acquisitions: (lock, line)
        self.acquires: List[Tuple[LockKey, int]] = []
        # resolved callees (nested defs excluded)
        self.calls: Set[FnKey] = set()
        # (callee, held locks, call line)
        self.calls_under_lock: List[
            Tuple[FnKey, frozenset, int]] = []


class _SummaryWalker:
    """One function: track held locks statement-wise (the same
    traversal shape as locks._FunctionWalker), recording acquisitions
    and calls-with-held-locks. Nested defs are skipped."""

    def __init__(self, registry: _Registry, key: FnKey, fn: ast.AST):
        self.registry = registry
        self.program = registry.program
        self.rel, qual = key
        self.cls = qual.split(".")[0] if "." in qual else None
        self.key = key
        self.fn = fn
        self.local_types = self.program._local_types(
            self.rel, self.cls, fn)
        self.out = _Summary()

    def run(self) -> _Summary:
        self._stmts(self.fn.body, frozenset())
        return self.out

    def _stmts(self, body, held) -> None:
        for stmt in body:
            self._stmt(stmt, held)

    def _stmt(self, stmt, held) -> None:
        if isinstance(stmt, ast.With):
            cur = set(held)
            for item in stmt.items:
                self._expr(item.context_expr, frozenset(cur))
                lock = self.registry.resolve(
                    self.rel, self.cls, item.context_expr)
                if lock is not None:
                    self.out.acquires.append((lock, stmt.lineno))
                    cur.add(lock)
            self._stmts(stmt.body, frozenset(cur))
        elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
            pass  # runs elsewhere, under that caller's locks
        elif isinstance(stmt, (ast.If, ast.While)):
            self._expr(stmt.test, held)
            self._stmts(stmt.body, held)
            self._stmts(stmt.orelse, held)
        elif isinstance(stmt, ast.For):
            self._expr(stmt.iter, held)
            self._expr(stmt.target, held)
            self._stmts(stmt.body, held)
            self._stmts(stmt.orelse, held)
        elif isinstance(stmt, ast.Try):
            self._stmts(stmt.body, held)
            for h in stmt.handlers:
                self._stmts(h.body, held)
            self._stmts(stmt.orelse, held)
            self._stmts(stmt.finalbody, held)
        else:
            for child in ast.iter_child_nodes(stmt):
                self._expr(child, held)

    def _expr(self, node, held) -> None:
        for sub in ast.walk(node):
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                continue
            if not isinstance(sub, ast.Call):
                continue
            target = self.program.resolve_call(
                self.rel, self.cls, sub.func, self.local_types)
            if target is None or target == self.key:
                continue
            self.out.calls.add(target)
            if held:
                self.out.calls_under_lock.append(
                    (target, held, sub.lineno))


class _Edge:
    __slots__ = ("src", "dst", "holder", "hold_line", "chain",
                 "acquire_site")

    def __init__(self, src, dst, holder, hold_line, chain,
                 acquire_site):
        self.src = src
        self.dst = dst
        self.holder = holder          # FnKey holding src
        self.hold_line = hold_line    # line of the call / nested with
        self.chain = chain            # [FnKey] from holder to acquirer
        self.acquire_site = acquire_site  # (rel, line) of `with dst`

    def describe(self) -> str:
        path = " -> ".join(q for (_r, q) in self.chain)
        return (f"{_display(self.src)} held at "
                f"{self.holder[0]}:{self.hold_line} "
                f"[{self.holder[1]}], then {_display(self.dst)} "
                f"acquired at {self.acquire_site[0]}:"
                f"{self.acquire_site[1]}"
                + (f" via {path}" if len(self.chain) > 1 else ""))


def program_check(program: Program) -> List[Finding]:
    registry = _Registry(program)
    summaries: Dict[FnKey, _Summary] = {}
    for key, fn in program.functions.items():
        summaries[key] = _SummaryWalker(registry, key, fn).run()

    # Transitive acquisition closure per function (over the nested-
    # def-free call sets the summaries recorded). Worklist fixpoint,
    # not memoized DFS: recursion cycles in the call graph would force
    # a DFS to cut a back-edge and cache the partial result, silently
    # dropping locks reachable through the cycle (and with them real
    # deadlock edges).
    trans: Dict[FnKey, Set[LockKey]] = {
        key: {lock for (lock, _line) in s.acquires}
        for key, s in summaries.items()
    }
    changed = True
    while changed:
        changed = False
        for key, s in summaries.items():
            cur = trans[key]
            before = len(cur)
            for callee in s.calls:
                callee_locks = trans.get(callee)
                if callee_locks:
                    cur |= callee_locks
            if len(cur) != before:
                changed = True

    def trans_acquires(key: FnKey) -> Set[LockKey]:
        return trans.get(key, set())

    def acquire_path(start: FnKey, lock: LockKey):
        """([FnKey] chain start..acquirer, (rel, line)) for the first
        function reachable from `start` that directly acquires
        `lock`."""
        seen = set()
        todo = [(start, [start])]
        while todo:
            cur, chain = todo.pop(0)
            if cur in seen:
                continue
            seen.add(cur)
            s = summaries.get(cur)
            if s is None:
                continue
            for (lk, line) in s.acquires:
                if lk == lock:
                    return chain, (cur[0], line)
            for callee in sorted(s.calls):
                if callee not in seen:
                    todo.append((callee, chain + [callee]))
        return [start], (start[0], 0)

    # Edge set over distinct locks.
    edges: Dict[Tuple[LockKey, LockKey], _Edge] = {}

    def add_edge(src, dst, holder, line, chain, site):
        if src == dst:
            return
        key = (src, dst)
        if key not in edges:
            edges[key] = _Edge(src, dst, holder, line, chain, site)

    for key in sorted(summaries):
        s = summaries[key]
        # lexical with-in-with nesting inside this function
        _LexicalEdges(registry, key, program.functions[key],
                      add_edge).run()
        for (callee, held, line) in s.calls_under_lock:
            reachable_locks = trans_acquires(callee)
            for dst in sorted(reachable_locks):
                for src in sorted(held):
                    if src == dst:
                        continue
                    if (src, dst) in edges:
                        continue
                    chain, site = acquire_path(callee, dst)
                    add_edge(src, dst, key, line, [key] + chain, site)

    # ---- cycle detection over the lock graph
    graph: Dict[LockKey, Set[LockKey]] = {}
    for (src, dst) in edges:
        graph.setdefault(src, set()).add(dst)
        graph.setdefault(dst, set())

    sccs = _tarjan(graph)
    findings: List[Finding] = []
    for scc in sccs:
        if len(scc) < 2:
            continue
        cycle = _find_cycle(graph, scc)
        if not cycle:
            continue
        cycle_edges = [edges[(cycle[i], cycle[(i + 1) % len(cycle)])]
                       for i in range(len(cycle))]
        first = cycle_edges[0]
        locks_str = " -> ".join(_display(l) for l in cycle
                                ) + f" -> {_display(cycle[0])}"
        witness = "; ".join(e.describe() for e in cycle_edges)
        related = []
        for e in cycle_edges:
            related.append(f"{e.holder[0]}:{e.hold_line}")
            related.append(f"{e.acquire_site[0]}:{e.acquire_site[1]}")
        findings.append(Finding(
            RULE_DEADLOCK, first.holder[0], first.hold_line, 0,
            f"lock-order cycle {locks_str}: two threads taking these "
            f"locks in opposite orders deadlock. Witness: {witness}",
            first.holder[1], related=related))
    return findings


class _LexicalEdges:
    """with-in-with edges inside one function (including multi-item
    `with a, b:` which acquires left to right)."""

    def __init__(self, registry: _Registry, key: FnKey, fn, add_edge):
        self.registry = registry
        self.rel, qual = key
        self.cls = qual.split(".")[0] if "." in qual else None
        self.key = key
        self.fn = fn
        self.add_edge = add_edge

    def run(self):
        self._stmts(self.fn.body, [])

    def _stmts(self, body, held):
        for stmt in body:
            self._stmt(stmt, held)

    def _stmt(self, stmt, held):
        if isinstance(stmt, ast.With):
            cur = list(held)
            for item in stmt.items:
                lock = self.registry.resolve(
                    self.rel, self.cls, item.context_expr)
                if lock is None:
                    continue
                for (src, src_line) in cur:
                    self.add_edge(
                        src, lock, self.key, src_line,
                        [self.key], (self.rel, stmt.lineno))
                cur.append((lock, stmt.lineno))
            self._stmts(stmt.body, cur)
        elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
            pass
        elif isinstance(stmt, (ast.If, ast.While)):
            self._stmts(stmt.body, held)
            self._stmts(stmt.orelse, held)
        elif isinstance(stmt, ast.For):
            self._stmts(stmt.body, held)
            self._stmts(stmt.orelse, held)
        elif isinstance(stmt, ast.Try):
            self._stmts(stmt.body, held)
            for h in stmt.handlers:
                self._stmts(h.body, held)
            self._stmts(stmt.orelse, held)
            self._stmts(stmt.finalbody, held)


def _tarjan(graph: Dict[LockKey, Set[LockKey]]) -> List[List[LockKey]]:
    index: Dict[LockKey, int] = {}
    lowlink: Dict[LockKey, int] = {}
    on_stack: Set[LockKey] = set()
    stack: List[LockKey] = []
    counter = [0]
    out: List[List[LockKey]] = []

    def strongconnect(v, depth=0):
        index[v] = lowlink[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on_stack.add(v)
        for w in sorted(graph.get(v, ())):
            if w not in index:
                strongconnect(w, depth + 1)
                lowlink[v] = min(lowlink[v], lowlink[w])
            elif w in on_stack:
                lowlink[v] = min(lowlink[v], index[w])
        if lowlink[v] == index[v]:
            comp = []
            while True:
                w = stack.pop()
                on_stack.discard(w)
                comp.append(w)
                if w == v:
                    break
            out.append(sorted(comp))

    for v in sorted(graph):
        if v not in index:
            strongconnect(v)
    return out


def _find_cycle(graph: Dict[LockKey, Set[LockKey]],
                scc: List[LockKey]) -> Optional[List[LockKey]]:
    """An elementary cycle within one SCC (DFS from its smallest
    node), as an ordered lock list [a, b, c] meaning a->b->c->a."""
    members = set(scc)
    start = scc[0]
    stack = [(start, [start])]
    seen_paths = set()
    while stack:
        node, path = stack.pop()
        for nxt in sorted(graph.get(node, ())):
            if nxt not in members:
                continue
            if nxt == start and len(path) >= 2:
                return path
            if nxt in path:
                continue
            key = (nxt, tuple(path))
            if key in seen_paths:
                continue
            seen_paths.add(key)
            stack.append((nxt, path + [nxt]))
    # 2-cycles: a->b->a
    for a in scc:
        for b in graph.get(a, ()):
            if b in members and a in graph.get(b, ()):
                return [a, b]
    return None
