"""Snapshot-discipline checker.

Scheduler and dispatch code must plan against an immutable
``StateStore.snapshot()`` handle, never the live store: the live store
mutates under the FSM apply thread mid-eval, so reads through it tear
across raft indexes (a placement computed half-before, half-after an
apply is exactly the inconsistency optimistic concurrency exists to
catch — but only if every eval's reads come from ONE snapshot).

Rule ``live-state-read`` (modules under ``scheduler/`` and
``dispatch/`` only):

- calling any read method on ``<...>.fsm.state`` other than
  ``snapshot()`` / ``latest_index()`` (the index probe does not read
  table state and the catch-up loops need it);
- binding ``<...>.fsm.state`` itself to a name / argument / container —
  aliasing the live store smuggles it past the call-site check.
"""

from __future__ import annotations

import ast
from typing import List

from .core import Finding, Module

RULE_LIVE_READ = "live-state-read"

SCOPE_DIR_MARKERS = ("/scheduler/", "/dispatch/")
ALLOWED_METHODS = {"snapshot", "latest_index"}


def _is_fsm_state(node: ast.AST) -> bool:
    """True for an Attribute chain ending ``.fsm.state``."""
    return (isinstance(node, ast.Attribute) and node.attr == "state"
            and isinstance(node.value, ast.Attribute)
            and node.value.attr == "fsm")


def in_scope(rel_path: str) -> bool:
    p = "/" + rel_path
    return any(m in p for m in SCOPE_DIR_MARKERS)


def check(mod: Module) -> List[Finding]:
    if not in_scope(mod.rel):
        return []
    findings: List[Finding] = []
    for node in ast.walk(mod.tree):
        if not _is_fsm_state(node):
            continue
        parent = mod.parents.get(node)
        # Allowed shape: Call(func=Attribute(value=<fsm.state>,
        # attr in ALLOWED_METHODS))
        if isinstance(parent, ast.Attribute):
            grand = mod.parents.get(parent)
            if (parent.attr in ALLOWED_METHODS
                    and isinstance(grand, ast.Call)
                    and grand.func is parent):
                continue
            findings.append(Finding(
                RULE_LIVE_READ, mod.rel, node.lineno, node.col_offset,
                f"live-store read '.fsm.state.{parent.attr}' — "
                f"scheduler/dispatch code must read through a "
                f"StateStore.snapshot() handle",
                mod.symbol_of(node)))
        else:
            findings.append(Finding(
                RULE_LIVE_READ, mod.rel, node.lineno, node.col_offset,
                "aliasing the live store ('.fsm.state') — take a "
                ".snapshot() and pass that instead",
                mod.symbol_of(node)))
    return findings
