"""Robustness checkers: waits that can hang forever and exception
handlers that hide faults.

Two rules, both scoped to the control-plane dirs where fault injection
(nomad_tpu/chaos) hunts — an unbounded wait turns an injected fault
into a hung thread instead of a recovered one, and a swallowed
exception is exactly how injection findings hide:

- ``unbounded-wait`` (``server/`` and ``dispatch/``): a no-argument
  ``.wait()`` / ``.get()`` / ``.join()`` call blocks forever with no
  shutdown re-check; every such wait must be bounded (pass a timeout
  and re-check stop/shutdown in a loop). ``dict.get`` is untouched —
  it always takes at least one argument.

- ``swallowed-exception`` (``server/``, ``dispatch/``, ``client/``):
  an ``except Exception:`` / ``except BaseException:`` / bare
  ``except:`` whose entire body is ``pass`` (or ``...``). Either
  narrow the exception type, log it, or suppress explicitly with
  ``# nta: disable=swallowed-exception`` and a justification. Handlers
  for SPECIFIC exception types (``except ValueError: pass``) are a
  deliberate protocol and stay quiet.
"""

from __future__ import annotations

import ast
from typing import List

from .core import Finding, Module

RULE_UNBOUNDED_WAIT = "unbounded-wait"
RULE_SWALLOWED = "swallowed-exception"

WAIT_SCOPE_MARKERS = ("/server/", "/dispatch/")
SWALLOW_SCOPE_MARKERS = ("/server/", "/dispatch/", "/client/")

# Attribute calls that block forever when called with no timeout.
UNBOUNDED_WAIT_ATTRS = {"wait", "get", "join"}
BROAD_EXC_NAMES = {"Exception", "BaseException"}


def _in_scope(rel_path: str, markers) -> bool:
    p = "/" + rel_path
    return any(m in p for m in markers)


def _check_unbounded_waits(mod: Module, findings: List[Finding]) -> None:
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        if not isinstance(func, ast.Attribute):
            continue
        if func.attr not in UNBOUNDED_WAIT_ATTRS:
            continue
        if node.args or node.keywords:
            continue  # a timeout (or any bound) was passed
        findings.append(Finding(
            RULE_UNBOUNDED_WAIT, mod.rel, node.lineno, node.col_offset,
            f"unbounded '.{func.attr}()' — pass a timeout and re-check "
            f"shutdown in a loop (a wedged peer pins this thread forever)",
            mod.symbol_of(node)))


def _is_silent_body(body: List[ast.stmt]) -> bool:
    """True when the handler body does nothing: a single `pass`, or a
    single bare `...` expression."""
    if len(body) != 1:
        return False
    stmt = body[0]
    if isinstance(stmt, ast.Pass):
        return True
    return (isinstance(stmt, ast.Expr)
            and isinstance(stmt.value, ast.Constant)
            and stmt.value.value is Ellipsis)


def _is_broad(handler: ast.ExceptHandler) -> bool:
    typ = handler.type
    if typ is None:
        return True  # bare except:
    if isinstance(typ, ast.Name):
        return typ.id in BROAD_EXC_NAMES
    if isinstance(typ, ast.Tuple):
        return any(isinstance(el, ast.Name) and el.id in BROAD_EXC_NAMES
                   for el in typ.elts)
    return False


def _check_swallowed(mod: Module, findings: List[Finding]) -> None:
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if not _is_broad(node) or not _is_silent_body(node.body):
            continue
        findings.append(Finding(
            RULE_SWALLOWED, mod.rel, node.lineno, node.col_offset,
            "broad exception silently swallowed — narrow the type, log "
            "it, or '# nta: disable=swallowed-exception' with a reason",
            mod.symbol_of(node)))


def check(mod: Module) -> List[Finding]:
    findings: List[Finding] = []
    if _in_scope(mod.rel, WAIT_SCOPE_MARKERS):
        _check_unbounded_waits(mod, findings)
    if _in_scope(mod.rel, SWALLOW_SCOPE_MARKERS):
        _check_swallowed(mod, findings)
    return findings
