"""Robustness checkers: waits that can hang forever, exception
handlers that hide faults, and blocking/unbounded work on the flight
recorder's record path.

Three rules. The first two are scoped to the control-plane dirs where
fault injection (nomad_tpu/chaos) hunts — an unbounded wait turns an
injected fault into a hung thread instead of a recovered one, and a
swallowed exception is exactly how injection findings hide:

- ``unbounded-wait`` (``server/``, ``dispatch/``, ``trace/``,
  ``admission/``): a
  no-argument ``.wait()`` / ``.get()`` / ``.join()`` call blocks
  forever with no shutdown re-check; every such wait must be bounded
  (pass a timeout and re-check stop/shutdown in a loop). ``dict.get``
  is untouched — it always takes at least one argument.

- ``swallowed-exception`` (``server/``, ``dispatch/``, ``client/``,
  ``trace/``, ``admission/``): an ``except Exception:`` /
  ``except BaseException:`` /
  bare ``except:`` whose entire body is ``pass`` (or ``...``). Either
  narrow the exception type, log it, or suppress explicitly with
  ``# nta: disable=swallowed-exception`` and a justification. Handlers
  for SPECIFIC exception types (``except ValueError: pass``) are a
  deliberate protocol and stay quiet.

- ``record-path-blocking`` — a module that declares a flight-recorder
  record-path manifest::

      NTA_RECORD_PATH = ("FlightRecorder.record_span", ...)

  gets every function reachable from those entrypoints (direct
  intra-module calls, the same reachability the dispatcher rule uses —
  these are the functions the broker lock and the dispatcher thread's
  ``NTA_DISPATCHER_ENTRYPOINTS`` chain run) checked for:

  * blocking calls — ``sleep``/``wait``/``join``/``acquire``/
    ``result``/``urlopen``/socket sends — with or WITHOUT a timeout:
    the record path may not park at all, bounded or not (a ``with
    lock:`` around constant work is the only sanctioned
    synchronization);
  * unbounded container growth — ``.append``/``.extend``/``.insert``/
    ``.setdefault``/``.add`` on an attribute-rooted container
    (``self.ring.append``, ``entry.spans.append``). Fixed-memory
    storage writes into PREALLOCATED slots by index; growth calls on
    locals (bounded scratch) stay quiet.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set

from .core import (
    Finding,
    Module,
    direct_calls,
    module_functions,
    reachable_from,
)

RULE_UNBOUNDED_WAIT = "unbounded-wait"
RULE_SWALLOWED = "swallowed-exception"
RULE_RECORD_PATH = "record-path-blocking"

WAIT_SCOPE_MARKERS = ("/server/", "/dispatch/", "/trace/", "/admission/")
SWALLOW_SCOPE_MARKERS = ("/server/", "/dispatch/", "/client/", "/trace/",
                         "/admission/")

# Attribute calls that block forever when called with no timeout.
UNBOUNDED_WAIT_ATTRS = {"wait", "get", "join"}
BROAD_EXC_NAMES = {"Exception", "BaseException"}

RECORD_MANIFEST = "NTA_RECORD_PATH"
# Blocking regardless of arguments: the record path may not park.
RECORD_BLOCKING_ATTRS = {"sleep", "wait", "join", "acquire", "result",
                         "urlopen", "recv", "send", "sendall", "sendto",
                         "block_until_ready", "submit_plan"}
RECORD_BLOCKING_NAMES = {"sleep", "urlopen"}
# Container growth calls; fine on locals, flagged on attribute-rooted
# receivers (an attribute outlives the call — that is where unbounded
# memory hides).
RECORD_GROWTH_ATTRS = {"append", "extend", "insert", "setdefault", "add"}


def _in_scope(rel_path: str, markers) -> bool:
    p = "/" + rel_path
    return any(m in p for m in markers)


def _check_unbounded_waits(mod: Module, findings: List[Finding]) -> None:
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        if not isinstance(func, ast.Attribute):
            continue
        if func.attr not in UNBOUNDED_WAIT_ATTRS:
            continue
        if node.args or node.keywords:
            continue  # a timeout (or any bound) was passed
        findings.append(Finding(
            RULE_UNBOUNDED_WAIT, mod.rel, node.lineno, node.col_offset,
            f"unbounded '.{func.attr}()' — pass a timeout and re-check "
            f"shutdown in a loop (a wedged peer pins this thread forever)",
            mod.symbol_of(node)))


def _is_silent_body(body: List[ast.stmt]) -> bool:
    """True when the handler body does nothing: a single `pass`, or a
    single bare `...` expression."""
    if len(body) != 1:
        return False
    stmt = body[0]
    if isinstance(stmt, ast.Pass):
        return True
    return (isinstance(stmt, ast.Expr)
            and isinstance(stmt.value, ast.Constant)
            and stmt.value.value is Ellipsis)


def _is_broad(handler: ast.ExceptHandler) -> bool:
    typ = handler.type
    if typ is None:
        return True  # bare except:
    if isinstance(typ, ast.Name):
        return typ.id in BROAD_EXC_NAMES
    if isinstance(typ, ast.Tuple):
        return any(isinstance(el, ast.Name) and el.id in BROAD_EXC_NAMES
                   for el in typ.elts)
    return False


def _check_swallowed(mod: Module, findings: List[Finding]) -> None:
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if not _is_broad(node) or not _is_silent_body(node.body):
            continue
        findings.append(Finding(
            RULE_SWALLOWED, mod.rel, node.lineno, node.col_offset,
            "broad exception silently swallowed — narrow the type, log "
            "it, or '# nta: disable=swallowed-exception' with a reason",
            mod.symbol_of(node)))


# ------------------------------------------------- record-path rule


def _functions_and_calls(mod: Module):
    """(qualname -> FunctionDef, qualname -> direct callee qualnames):
    THE intra-module call graph (core.module_functions/direct_calls) —
    shared with the dispatcher rule so the two manifests' notions of
    "reachable" cannot drift. References handed to pools/threads are
    not followed (they run on other threads; for the RECORD path there
    is no such escape hatch — handing work off would itself be an
    allocation per record)."""
    functions = module_functions(mod.tree)
    calls: Dict[str, Set[str]] = {
        qual: direct_calls(qual, fn, functions)
        for qual, fn in functions.items()
    }
    return functions, calls


def _record_manifest(mod: Module) -> List[str]:
    out: List[str] = []
    for node in mod.tree.body:
        if not isinstance(node, ast.Assign):
            continue
        for tgt in node.targets:
            if isinstance(tgt, ast.Name) and tgt.id == RECORD_MANIFEST:
                if isinstance(node.value, (ast.Tuple, ast.List)):
                    for el in node.value.elts:
                        if isinstance(el, ast.Constant) and isinstance(
                                el.value, str):
                            out.append(el.value)
    return out


def _attribute_rooted(expr: ast.AST) -> bool:
    """True when the receiver chain goes through an attribute access —
    i.e. the container outlives the call (self.x, entry.spans,
    self.a[i].b); plain locals/params are bounded scratch."""
    return any(isinstance(n, ast.Attribute) for n in ast.walk(expr))


def _check_record_path(mod: Module, findings: List[Finding]) -> None:
    entries = _record_manifest(mod)
    if not entries:
        return
    functions, calls = _functions_and_calls(mod)
    reachable = reachable_from(entries, functions, calls)
    for qual in sorted(reachable):
        for node in ast.walk(functions[qual]):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if isinstance(func, ast.Name):
                if func.id in RECORD_BLOCKING_NAMES:
                    findings.append(Finding(
                        RULE_RECORD_PATH, mod.rel, node.lineno,
                        node.col_offset,
                        f"blocking call '{func.id}' on the flight-"
                        f"recorder record path (manifest "
                        f"{RECORD_MANIFEST}); the record path must "
                        f"never park", qual))
                continue
            if not isinstance(func, ast.Attribute):
                continue
            if func.attr in RECORD_BLOCKING_ATTRS:
                findings.append(Finding(
                    RULE_RECORD_PATH, mod.rel, node.lineno,
                    node.col_offset,
                    f"blocking call '.{func.attr}()' on the flight-"
                    f"recorder record path (manifest "
                    f"{RECORD_MANIFEST}); the record path must never "
                    f"park, bounded or not", qual))
            elif (func.attr in RECORD_GROWTH_ATTRS
                    and _attribute_rooted(func.value)):
                findings.append(Finding(
                    RULE_RECORD_PATH, mod.rel, node.lineno,
                    node.col_offset,
                    f"unbounded growth '.{func.attr}()' on an "
                    f"attribute-rooted container on the record path — "
                    f"write into preallocated slots by index "
                    f"(drop-oldest ring), never grow", qual))


def check(mod: Module) -> List[Finding]:
    findings: List[Finding] = []
    if _in_scope(mod.rel, WAIT_SCOPE_MARKERS):
        _check_unbounded_waits(mod, findings)
    if _in_scope(mod.rel, SWALLOW_SCOPE_MARKERS):
        _check_swallowed(mod, findings)
    _check_record_path(mod, findings)
    return findings
