"""Robustness checkers: waits that can hang forever, exception
handlers that hide faults, and blocking/unbounded work on the flight
recorder's record path.

Three rules. The first two are scoped to the control-plane dirs where
fault injection (nomad_tpu/chaos) hunts — an unbounded wait turns an
injected fault into a hung thread instead of a recovered one, and a
swallowed exception is exactly how injection findings hide:

- ``unbounded-wait`` (``server/``, ``dispatch/``, ``trace/``,
  ``admission/``, ``scheduler/``, ``profile/`` — the dense path parks
  worker threads in scheduler/ code, so it gets the same discipline;
  the profiler wraps those very locks, so it gets it too): a
  no-argument ``.wait()`` / ``.get()`` / ``.join()`` call blocks
  forever with no shutdown re-check; every such wait must be bounded
  (pass a timeout and re-check stop/shutdown in a loop). ``dict.get``
  is untouched — it always takes at least one argument.
  Whole-program extension (PR 7): an unbounded wait OUTSIDE the scope
  dirs is still flagged when it is reachable (core.Program, cross-
  module) from a function defined IN them — `worker.process` calling
  into a scheduler/ helper that parks on a bare ``event.wait()`` hangs
  the same worker thread the in-scope rule protects. References
  handed to pools/threads are not followed: a daemon worker loop that
  parks on its queue by design stays quiet.

- ``swallowed-exception`` (``server/``, ``dispatch/``, ``client/``,
  ``trace/``, ``admission/``, ``profile/``): an ``except Exception:`` /
  ``except BaseException:`` /
  bare ``except:`` whose entire body is ``pass`` (or ``...``). Either
  narrow the exception type, log it, or suppress explicitly with
  ``# nta: disable=swallowed-exception`` and a justification. Handlers
  for SPECIFIC exception types (``except ValueError: pass``) are a
  deliberate protocol and stay quiet.

- ``record-path-blocking`` — a module that declares a flight-recorder
  record-path manifest::

      NTA_RECORD_PATH = ("FlightRecorder.record_span", ...)

  gets every function reachable from those entrypoints (whole-program
  core.Program reachability, the same graph the dispatcher rule uses —
  these are the functions the broker lock and the dispatcher thread's
  ``NTA_DISPATCHER_ENTRYPOINTS`` chain run) checked for:

  * blocking calls — ``sleep``/``wait``/``join``/``acquire``/
    ``result``/``urlopen``/socket sends — with or WITHOUT a timeout:
    the record path may not park at all, bounded or not (a ``with
    lock:`` around constant work is the only sanctioned
    synchronization);
  * unbounded container growth — ``.append``/``.extend``/``.insert``/
    ``.setdefault``/``.add`` on an attribute-rooted container
    (``self.ring.append``, ``entry.spans.append``). Fixed-memory
    storage writes into PREALLOCATED slots by index; growth calls on
    locals (bounded scratch) stay quiet.
"""

from __future__ import annotations

import ast
from typing import List

from .core import Finding, Module, Program

RULE_UNBOUNDED_WAIT = "unbounded-wait"
RULE_SWALLOWED = "swallowed-exception"
RULE_RECORD_PATH = "record-path-blocking"

WAIT_SCOPE_MARKERS = ("/server/", "/dispatch/", "/trace/",
                      "/admission/", "/scheduler/", "/migrate/",
                      "/profile/", "/defrag/", "/gang/", "/readplane/",
                      "/models/classes", "/parallel/shard")
SWALLOW_SCOPE_MARKERS = ("/server/", "/dispatch/", "/client/", "/trace/",
                         "/admission/", "/migrate/", "/profile/",
                         "/defrag/", "/gang/", "/readplane/",
                         "/models/classes", "/parallel/shard")

# Attribute calls that block forever when called with no timeout.
UNBOUNDED_WAIT_ATTRS = {"wait", "get", "join"}
BROAD_EXC_NAMES = {"Exception", "BaseException"}

RECORD_MANIFEST = "NTA_RECORD_PATH"
# Blocking regardless of arguments: the record path may not park.
RECORD_BLOCKING_ATTRS = {"sleep", "wait", "join", "acquire", "result",
                         "urlopen", "recv", "send", "sendall", "sendto",
                         "block_until_ready", "submit_plan"}
RECORD_BLOCKING_NAMES = {"sleep", "urlopen"}
# Container growth calls; fine on locals, flagged on attribute-rooted
# receivers (an attribute outlives the call — that is where unbounded
# memory hides).
RECORD_GROWTH_ATTRS = {"append", "extend", "insert", "setdefault", "add"}


def _in_scope(rel_path: str, markers) -> bool:
    p = "/" + rel_path
    return any(m in p for m in markers)


def _check_unbounded_waits(mod: Module, findings: List[Finding]) -> None:
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        if not isinstance(func, ast.Attribute):
            continue
        if func.attr not in UNBOUNDED_WAIT_ATTRS:
            continue
        if node.args or node.keywords:
            continue  # a timeout (or any bound) was passed
        findings.append(Finding(
            RULE_UNBOUNDED_WAIT, mod.rel, node.lineno, node.col_offset,
            f"unbounded '.{func.attr}()' — pass a timeout and re-check "
            f"shutdown in a loop (a wedged peer pins this thread forever)",
            mod.symbol_of(node)))


def _is_silent_body(body: List[ast.stmt]) -> bool:
    """True when the handler body does nothing: a single `pass`, or a
    single bare `...` expression."""
    if len(body) != 1:
        return False
    stmt = body[0]
    if isinstance(stmt, ast.Pass):
        return True
    return (isinstance(stmt, ast.Expr)
            and isinstance(stmt.value, ast.Constant)
            and stmt.value.value is Ellipsis)


def _is_broad(handler: ast.ExceptHandler) -> bool:
    typ = handler.type
    if typ is None:
        return True  # bare except:
    if isinstance(typ, ast.Name):
        return typ.id in BROAD_EXC_NAMES
    if isinstance(typ, ast.Tuple):
        return any(isinstance(el, ast.Name) and el.id in BROAD_EXC_NAMES
                   for el in typ.elts)
    return False


def _check_swallowed(mod: Module, findings: List[Finding]) -> None:
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if not _is_broad(node) or not _is_silent_body(node.body):
            continue
        findings.append(Finding(
            RULE_SWALLOWED, mod.rel, node.lineno, node.col_offset,
            "broad exception silently swallowed — narrow the type, log "
            "it, or '# nta: disable=swallowed-exception' with a reason",
            mod.symbol_of(node)))


# ------------------------------------------------- record-path rule


def _attribute_rooted(expr: ast.AST) -> bool:
    """True when the receiver chain goes through an attribute access —
    i.e. the container outlives the call (self.x, entry.spans,
    self.a[i].b); plain locals/params are bounded scratch."""
    return any(isinstance(n, ast.Attribute) for n in ast.walk(expr))


def _check_record_fn(mod: Module, qual: str, fn: ast.AST,
                     note: str, related,
                     findings: List[Finding]) -> None:
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        if isinstance(func, ast.Name):
            if func.id in RECORD_BLOCKING_NAMES:
                findings.append(Finding(
                    RULE_RECORD_PATH, mod.rel, node.lineno,
                    node.col_offset,
                    f"blocking call '{func.id}' on the flight-"
                    f"recorder record path (manifest "
                    f"{RECORD_MANIFEST}{note}); the record path must "
                    f"never park", qual, related=related))
            continue
        if not isinstance(func, ast.Attribute):
            continue
        if func.attr in RECORD_BLOCKING_ATTRS:
            findings.append(Finding(
                RULE_RECORD_PATH, mod.rel, node.lineno,
                node.col_offset,
                f"blocking call '.{func.attr}()' on the flight-"
                f"recorder record path (manifest "
                f"{RECORD_MANIFEST}{note}); the record path must never "
                f"park, bounded or not", qual, related=related))
        elif (func.attr in RECORD_GROWTH_ATTRS
                and _attribute_rooted(func.value)):
            findings.append(Finding(
                RULE_RECORD_PATH, mod.rel, node.lineno,
                node.col_offset,
                f"unbounded growth '.{func.attr}()' on an "
                f"attribute-rooted container on the record path — "
                f"write into preallocated slots by index "
                f"(drop-oldest ring), never grow", qual,
                related=related))


def check(mod: Module) -> List[Finding]:
    """Local rules: in-scope unbounded waits + swallowed exceptions.
    The record-path and cross-module wait rules are whole-program —
    see program_check."""
    findings: List[Finding] = []
    if _in_scope(mod.rel, WAIT_SCOPE_MARKERS):
        _check_unbounded_waits(mod, findings)
    if _in_scope(mod.rel, SWALLOW_SCOPE_MARKERS):
        _check_swallowed(mod, findings)
    return findings


def program_check(program: Program) -> List[Finding]:
    """Whole-program robustness rules.

    - record-path-blocking: every function reachable from any module's
      NTA_RECORD_PATH manifest, across modules, is held to the
      never-park / never-grow contract.
    - unbounded-wait (cross-module leg): no-arg wait/get/join in an
      OUT-of-scope module, reachable from a function defined in a
      wait-scope dir. In-scope sites are reported by the local pass;
      this leg only adds the helpers those dirs call into.
    """
    findings: List[Finding] = []

    entries = program.manifest_entries(RECORD_MANIFEST)
    if entries:
        via = program.reachable_with_paths(entries)
        for key in sorted(via):
            rel, qual = key
            mod = program.by_rel.get(rel)
            if mod is None:
                continue
            note, related = program.witness_info(via, key)
            _check_record_fn(mod, qual, program.functions[key], note,
                             related, findings)

    origins = [key for key in program.functions
               if _in_scope(key[0], WAIT_SCOPE_MARKERS)]
    if origins:
        via = program.reachable_with_paths(origins)
        for key in sorted(via):
            rel, qual = key
            if _in_scope(rel, WAIT_SCOPE_MARKERS):
                continue  # local pass owns in-scope sites
            mod = program.by_rel.get(rel)
            if mod is None:
                continue
            entry = via[key][0]
            _note, related = program.witness_info(via, key)
            for node in ast.walk(program.functions[key]):
                if not isinstance(node, ast.Call):
                    continue
                func = node.func
                if not isinstance(func, ast.Attribute):
                    continue
                if func.attr not in UNBOUNDED_WAIT_ATTRS:
                    continue
                if node.args or node.keywords:
                    continue
                findings.append(Finding(
                    RULE_UNBOUNDED_WAIT, mod.rel, node.lineno,
                    node.col_offset,
                    f"unbounded '.{func.attr}()' reachable from "
                    f"'{entry[1]}' ({entry[0]}) — pass a timeout and "
                    f"re-check shutdown in a loop (a wedged peer pins "
                    f"that thread forever)",
                    qual, related=related))
    return findings
