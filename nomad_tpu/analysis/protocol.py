"""Raft-funnel protocol checker.

Rule ``raft-funnel`` — the static half of the exactly-once-terminal
guarantee the chaos soaks assert dynamically: **nothing commits
cluster state outside the raft apply path, and no terminal outcome is
stamped without routing through it.**

Sanctioned funnels are declared in ``NTA_RAFT_FUNNELS`` manifests::

    NTA_RAFT_FUNNELS = ("FSM._apply_eval_update", ...)

(`server/fsm.py` declares the FSM apply handlers + restore;
`scheduler/testing.py` declares the CPU-oracle harness's apply — the
Harness IS the raft stand-in for differential tests.) The checker
computes the whole-program closure of those entrypoints
(core.Program) and enforces two sub-rules over every module in scope:

1. **Commit calls**: a call to a ``StateStore`` mutator
   (``upsert_evals`` / ``upsert_allocs`` /
   ``update_allocs_from_client`` / ``delete_*`` / ``update_node_*`` /
   ...) may only appear inside a funnel-reachable function. Anything
   else is a write to replicated state that raft never saw — followers
   diverge silently.

2. **Terminal stamps**: an assignment of a terminal constant —
   ``.status = EVAL_STATUS_COMPLETE/FAILED/CANCELLED``,
   ``.client_status = ALLOC_CLIENT_LOST``, or the failed-queue park
   triggers ``.triggered_by = EVAL_TRIGGER_SHED/EXPIRED/DEAD_LETTER``
   — must either sit inside a funnel-reachable function, or the
   stamped object must flow into a funnel call in the SAME function
   (the codebase's stamp-a-copy-then-``eval_update([upd])`` idiom;
   ``cancelled.append(upd)`` followed by ``eval_update(cancelled)``
   also counts — one container hop is tracked). A terminal stamped on
   a shared eval and never submitted is exactly the double-terminal /
   lost-terminal bug class.

Precision notes: values must be terminal CONSTANT names (a helper
stamping a status passed as a parameter is invisible — call sites
passing the constant as an argument are the reference idiom and commit
through the funnel anyway); ``client/`` is out of scope (the client
owns its local status lifecycle and reports through the
``alloc_client_update`` RPC, which IS the funnel). Escape hatch, as
everywhere: ``# nta: disable=raft-funnel`` with a justification.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from .core import Finding, Program

RULE_FUNNEL = "raft-funnel"

FUNNEL_MANIFEST = "NTA_RAFT_FUNNELS"

# StateStore's mutating surface. Matched by attribute NAME on any
# receiver: the store is reached through self.state / snapshot
# restores / harness fields, and a name this distinctive appearing
# outside the funnel is wrong no matter what the receiver turns out
# to be at runtime.
STORE_MUTATORS = {
    "upsert_node", "delete_node", "update_node_status",
    "update_node_drain", "upsert_job", "delete_job", "upsert_evals",
    "delete_evals", "upsert_allocs", "update_allocs_from_client",
    "upsert_periodic_launch", "delete_periodic_launch",
    "upsert_vault_accessors", "delete_vault_accessors",
}

# Submit funnels: calling one of these WITH the stamped object is the
# sanctioned way to commit a terminal outcome from outside the apply
# path (the call routes through raft; the fsm handler re-applies the
# status on every replica).
SUBMIT_FUNNELS = {"eval_update", "upsert_evals", "upsert_allocs",
                  "update_allocs_from_client", "alloc_client_update"}

TERMINAL_BY_FIELD = {
    "status": {"EVAL_STATUS_COMPLETE", "EVAL_STATUS_FAILED",
               "EVAL_STATUS_CANCELLED"},
    "client_status": {"ALLOC_CLIENT_LOST"},
    # Eviction terminal: an alloc stamped evict (preemption) is
    # terminal to every scheduler pass — stamping it outside the
    # funnel is exactly a double-evict / phantom-evict. The sanctioned
    # path passes the constant as a Plan.append_preemption ARGUMENT
    # (parameter stamps are the reference idiom and invisible here by
    # design) and commits through plan-apply.
    "desired_status": {"ALLOC_DESIRED_EVICT"},
    "triggered_by": {"EVAL_TRIGGER_SHED", "EVAL_TRIGGER_EXPIRED",
                     "EVAL_TRIGGER_DEAD_LETTER",
                     # Churn follow-ups (nomad_tpu/migrate): minting a
                     # migration/preemption eval is a commitment to
                     # future work — a stamp that never reaches
                     # eval_update is displaced work silently dropped.
                     "EVAL_TRIGGER_MIGRATION", "EVAL_TRIGGER_PREEMPTION"},
}

# The client owns its local status lifecycle (pending->running->
# complete/failed) and commits through the alloc_client_update RPC.
EXCLUDE_MARKERS = ("/client/",)


def _in_scope(rel: str) -> bool:
    p = "/" + rel
    return not any(m in p for m in EXCLUDE_MARKERS)


def _const_name(node: ast.AST) -> Optional[str]:
    """Trailing name of a constant reference: `EVAL_STATUS_FAILED` or
    `consts.EVAL_STATUS_FAILED`."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _root_name(node: ast.AST) -> Optional[str]:
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        node = node.value
    if isinstance(node, ast.Name):
        return node.id
    return None


def _call_name(func: ast.AST) -> Optional[str]:
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None


class _FlowScan:
    """Names that flow into a funnel call within one function —
    ORDER-SENSITIVE on the submit: a stamp is only covered by a funnel
    call at or below it (a terminal stamped AFTER the submit mutates
    the shared object without committing — the lost-terminal bug
    class). One container hop is tracked, and the append may sit on
    EITHER side of the stamp: the container holds a reference, so
    `out.append(upd); upd.status = ...; eval_update(out)` commits the
    stamp exactly like stamp-then-append does.

    What counts as a funnel call is decided by `is_funnel(node)`:
    RESOLUTION against the declared funnel entries (plus the
    fixed SUBMIT_FUNNELS name set) — matching manifest entries by
    bare method name would let `FSM.apply` sanction every call
    spelled `.apply()` anywhere in the tree."""

    def __init__(self, fn: ast.AST, is_funnel):
        # name -> latest line where it appears inside a funnel call's
        # arguments
        self.flows: Dict[str, int] = {}
        # (container, member) pairs with at least one append
        self.hops: Set[tuple] = set()
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            name = _call_name(node.func)
            if is_funnel(node):
                for arg in list(node.args) + [
                        kw.value for kw in node.keywords]:
                    for sub in ast.walk(arg):
                        if isinstance(sub, ast.Name):
                            self.flows[sub.id] = max(
                                self.flows.get(sub.id, 0), node.lineno)
            elif (name in ("append", "extend", "insert", "add")
                    and isinstance(node.func, ast.Attribute)):
                container = _root_name(node.func.value)
                if container is not None:
                    for arg in node.args:
                        r = _root_name(arg)
                        if r is not None:
                            self.hops.add((container, r))

    def covers(self, name: Optional[str], stamp_line: int) -> bool:
        if name is None:
            return False
        if self.flows.get(name, 0) >= stamp_line:
            return True
        for (container, member) in self.hops:
            if (member == name
                    and self.flows.get(container, 0) >= stamp_line):
                return True
        return False


def program_check(program: Program) -> List[Finding]:
    entries = program.manifest_entries(FUNNEL_MANIFEST)
    reachable = set(program.reachable_with_paths(entries)) if entries \
        else set()
    funnel_entries = set(entries)
    # Witness: the manifest declaration sites. The sanctioned set is a
    # function of the manifests, so an edit to any manifest module can
    # surface findings in OTHERWISE-unchanged files — `related` is how
    # ntalint --diff attributes those to the edit.
    manifest_sites = [
        f"{rel}:{line}" for rel, line in sorted(
            program.manifest_lines.get(FUNNEL_MANIFEST, {}).items())]
    findings: List[Finding] = []

    for key in sorted(program.functions):
        rel, qual = key
        if not _in_scope(rel):
            continue
        if key in reachable:
            continue  # inside the funnel: sanctioned by construction
        fn = program.functions[key]
        mod = program.by_rel.get(rel)
        if mod is None:
            continue
        cls = qual.split(".")[0] if "." in qual else None
        flow: Optional[_FlowScan] = None

        def make_flow(rel=rel, cls=cls, fn=fn):
            local_types = program._local_types(rel, cls, fn)

            def is_funnel(node: ast.Call) -> bool:
                if _call_name(node.func) in SUBMIT_FUNNELS:
                    return True
                target = program.resolve_call(rel, cls, node.func,
                                              local_types)
                return target is not None and target in funnel_entries

            return _FlowScan(fn, is_funnel)

        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                name = _call_name(node.func)
                if (name in STORE_MUTATORS
                        and isinstance(node.func, ast.Attribute)):
                    findings.append(Finding(
                        RULE_FUNNEL, rel, node.lineno, node.col_offset,
                        f"state-store mutator '.{name}()' outside the "
                        f"raft funnel ({FUNNEL_MANIFEST}): only the "
                        f"fsm/apply path may commit replicated state — "
                        f"submit through raft (eval_update / "
                        f"alloc_update RPCs) instead",
                        qual, related=manifest_sites or None))
            elif isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = (node.targets
                           if isinstance(node, ast.Assign)
                           else [node.target])
                value_name = _const_name(getattr(node, "value", None))
                if value_name is None:
                    continue
                for tgt in targets:
                    if not isinstance(tgt, ast.Attribute):
                        continue
                    terminals = TERMINAL_BY_FIELD.get(tgt.attr)
                    if terminals is None or value_name not in terminals:
                        continue
                    if flow is None:
                        flow = make_flow()
                    if flow.covers(_root_name(tgt.value), node.lineno):
                        continue
                    findings.append(Finding(
                        RULE_FUNNEL, rel, node.lineno,
                        node.col_offset,
                        f"terminal stamp '.{tgt.attr} = {value_name}' "
                        f"outside the raft funnel and never submitted "
                        f"through it: a terminal outcome that does not "
                        f"flow into eval_update/upsert_allocs (or a "
                        f"{FUNNEL_MANIFEST} funnel) in this function "
                        f"either never commits or commits twice",
                        qual, related=manifest_sites or None))
    return findings
