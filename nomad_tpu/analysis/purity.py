"""JAX trace-purity checker.

A function is TRACED when it is jitted (``@jax.jit``,
``@functools.partial(jax.jit, ...)``, ``jax.jit(f)``), passed to a
transform (``vmap``/``pmap``) or a control-flow primitive
(``lax.scan``/``while_loop``/``cond``/``fori_loop``/``map``) — directly,
as a nested def, or as a lambda — or called (direct intra-module call)
from another traced function.

Rules inside traced code:

- ``trace-impure-call`` — Python RNG (``random.*``, ``np.random.*``,
  ``os.urandom``, ``uuid.*``), wall clocks (``time.*``,
  ``datetime.*``), ``print``/``input``/``open``: all run at TRACE time
  only, baking one draw/timestamp into the compiled program — the
  classic silent-staleness bug.

- ``trace-host-sync`` — ``float()``/``int()``/``bool()`` on traced
  values, ``.item()``/``.tolist()``, and any call through a numpy
  import alias (``np.asarray(...)`` etc.): forces device→host
  materialization, which either errors under trace or silently falls
  back to host, the 100-1000x cliff the dense path exists to avoid.

- ``trace-closure-mutation`` — assigning ``self.X``/globals/nonlocals
  or calling a mutating method (``append``/``update``/...) on a
  closed-over name: runs once at trace time, not per call.

- ``trace-python-branch`` — ``if``/``while``/``assert`` whose test
  depends on traced values (concretization error / silent recompile
  per shape). Tests over STATIC parameters (``static_argnames``),
  shape/dtype queries (``x.shape``, ``len()``, ``np.shape``), module
  globals, and constants are fine and common (``if config.pre_resolve``).

Call-site rule (applies everywhere, not just traced code):

- ``jit-unhashable-static`` — a call to a known-jitted function passing
  a list/dict/set literal (or ``list()``/``dict()``/``set()``/numpy
  array call) in a static-arg position: unhashable statics raise at
  call time, and a fresh mutable object per call would defeat the jit
  cache even if it hashed.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .core import Finding, Module

RULE_IMPURE = "trace-impure-call"
RULE_HOST_SYNC = "trace-host-sync"
RULE_CLOSURE_MUT = "trace-closure-mutation"
RULE_BRANCH = "trace-python-branch"
RULE_STATIC = "jit-unhashable-static"

IMPURE_ROOTS = {"random", "time", "datetime", "os", "uuid"}
IMPURE_NAMES = {"print", "input", "open"}
HOST_CAST_NAMES = {"float", "int", "bool"}
HOST_SYNC_ATTRS = {"item", "tolist", "block_until_ready"}
MUTATING_ATTRS = {"append", "extend", "update", "add", "pop", "remove",
                  "insert", "setdefault", "clear", "popitem"}
TRANSFORM_NAMES = {"vmap", "pmap"}
CONTROL_FLOW = {"scan", "while_loop", "cond", "fori_loop", "map",
                "switch"}
SHAPE_ATTRS = {"shape", "ndim", "dtype", "size"}
SAFE_BUILTINS = {"len", "range", "min", "max", "abs", "sorted", "sum",
                 "isinstance", "tuple", "enumerate", "zip"}


class JitInfo:
    """One jitted function's signature, for call-site checks."""

    def __init__(self, name: str, params: List[str],
                 static_names: Set[str]):
        self.name = name
        self.params = params
        self.static_names = static_names


def _call_name(func: ast.AST) -> Optional[str]:
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None


def _root_name(node: ast.AST) -> Optional[str]:
    while isinstance(node, (ast.Attribute, ast.Subscript, ast.Call)):
        if isinstance(node, ast.Call):
            node = node.func
        else:
            node = node.value
    if isinstance(node, ast.Name):
        return node.id
    return None


def _is_lax_call(func: ast.AST) -> bool:
    """True when a CONTROL_FLOW-named call goes through ``lax`` —
    ``jax.lax.scan``/``lax.map``/bare ``while_loop``. Guards against
    host-side namesakes: ``jax.tree.map`` and builtin ``map`` run their
    function argument on the HOST, so marking it traced would
    false-positive every numpy call inside."""
    if isinstance(func, ast.Name):
        return func.id in ("while_loop", "fori_loop", "scan")
    node = func.value if isinstance(func, ast.Attribute) else None
    while isinstance(node, ast.Attribute):
        if node.attr == "lax":
            return True
        node = node.value
    return isinstance(node, ast.Name) and node.id == "lax"


def _is_jit_expr(node: ast.AST) -> Optional[Set[str]]:
    """When `node` is a jit-wrapping expression (``jax.jit``,
    ``functools.partial(jax.jit, static_argnames=...)``), return its
    static argnames (possibly empty); else None."""
    # bare jax.jit / jit
    if _call_name(node) in ("jit",) or (
            isinstance(node, ast.Name) and node.id == "jit"):
        return set()
    if isinstance(node, ast.Attribute) and node.attr == "jit":
        return set()
    if isinstance(node, ast.Call):
        fname = _call_name(node.func)
        if fname == "jit":
            return _static_from_kwargs(node)
        if fname == "partial":
            if node.args and _is_jit_expr(node.args[0]) is not None:
                return _static_from_kwargs(node)
    return None


def _static_from_kwargs(call: ast.Call) -> Set[str]:
    out: Set[str] = set()
    for kw in call.keywords:
        if kw.arg in ("static_argnames", "static_argnums"):
            if isinstance(kw.value, (ast.Tuple, ast.List)):
                for el in kw.value.elts:
                    if isinstance(el, ast.Constant) and isinstance(
                            el.value, str):
                        out.add(el.value)
            elif isinstance(kw.value, ast.Constant) and isinstance(
                    kw.value.value, str):
                out.add(kw.value.value)
    return out


def _numpy_aliases(mod: Module) -> Set[str]:
    out: Set[str] = set()
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name == "numpy":
                    out.add(alias.asname or "numpy")
    return out


def build_jit_registry(modules: List[Module]) -> Dict[str, JitInfo]:
    """Cross-module registry of jitted defs: called-name -> signature.
    Keyed on the bare function name — call sites import these directly
    and the names are unique in this codebase."""
    registry: Dict[str, JitInfo] = {}
    for mod in modules:
        for node in ast.walk(mod.tree):
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            statics: Optional[Set[str]] = None
            for dec in node.decorator_list:
                s = _is_jit_expr(dec)
                if s is not None:
                    statics = s
                    break
            if statics is None:
                continue
            params = [a.arg for a in node.args.posonlyargs
                      + node.args.args]
            registry[node.name] = JitInfo(node.name, params, statics)
    return registry


class _TracedCollector:
    """Find every traced function in a module: jit-decorated defs,
    defs/lambdas passed to transforms, and the transitive closure over
    direct intra-module calls."""

    def __init__(self, mod: Module):
        self.mod = mod
        # id(funcdef/lambda) -> static param-name set
        self.traced: Dict[int, Tuple[ast.AST, Set[str]]] = {}
        # name -> [def nodes] (several nested fns may share a name,
        # e.g. the `body` passed to each lax.scan).
        self.defs_by_name: Dict[str, List[ast.AST]] = {}
        self.global_statics: Set[str] = set()
        self._collect_defs(mod.tree)
        self._seed()
        self._closure()

    def _collect_defs(self, tree: ast.AST) -> None:
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.defs_by_name.setdefault(node.name, []).append(node)

    def _resolve_def(self, name: str, site: ast.AST) -> Optional[ast.AST]:
        """The def `name` refers to at `site`: prefer the candidate
        whose enclosing scope is an ancestor of the reference (nested
        fns shadow same-named siblings in other scopes)."""
        cands = self.defs_by_name.get(name)
        if not cands:
            return None
        if len(cands) == 1:
            return cands[0]
        # Rank the reference's ancestor chain innermost-first; a def
        # whose enclosing scope sits earliest in that chain is the one
        # Python's scoping resolves to.
        rank: Dict[int, int] = {}
        cur = site
        i = 0
        while cur is not None:
            rank.setdefault(id(cur), i)
            i += 1
            cur = self.mod.parents.get(cur)
        best = None
        best_rank = None
        for d in cands:
            scope = self.mod.parents.get(d)
            r = rank.get(id(scope))
            if r is not None and (best_rank is None or r < best_rank):
                best, best_rank = d, r
        return best or cands[0]

    def _seed(self) -> None:
        for node in ast.walk(self.mod.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    statics = _is_jit_expr(dec)
                    if statics is not None:
                        self._mark(node, statics)
                        self.global_statics |= statics
            elif isinstance(node, ast.Call):
                fname = _call_name(node.func)
                if fname == "jit" and node.args:
                    self._mark_arg(node.args[0],
                                   _static_from_kwargs(node))
                elif fname in TRANSFORM_NAMES and node.args:
                    self._mark_arg(node.args[0], set())
                elif fname in CONTROL_FLOW and node.args \
                        and _is_lax_call(node.func):
                    self._mark_arg(node.args[0], set())

    def _mark_arg(self, arg: ast.AST, statics: Set[str]) -> None:
        if isinstance(arg, ast.Lambda):
            self._mark(arg, statics)
        elif isinstance(arg, ast.Name):
            target = self._resolve_def(arg.id, arg)
            if target is not None:
                self._mark(target, statics)

    def _mark(self, fn: ast.AST, statics: Set[str]) -> None:
        cur = self.traced.get(id(fn))
        if cur is None:
            self.traced[id(fn)] = (fn, set(statics))
        else:
            cur[1].update(statics)

    def _closure(self) -> None:
        # Functions called directly from traced bodies are traced too.
        # Their own statics are unknown; params sharing a name with any
        # jit static (e.g. 'config') are treated static — pragmatic,
        # and exactly how this codebase threads statics through.
        changed = True
        while changed:
            changed = False
            for _fid, (fn, _statics) in list(self.traced.items()):
                for node in ast.walk(fn):
                    if not isinstance(node, ast.Call):
                        continue
                    if not isinstance(node.func, ast.Name):
                        continue
                    target = self._resolve_def(node.func.id, node)
                    if target is not None and id(target) not in \
                            self.traced:
                        self._mark(target, set())
                        changed = True

    def statics_for(self, fn: ast.AST) -> Set[str]:
        explicit = self.traced[id(fn)][1]
        if explicit:
            return explicit
        # transitively-traced: inherit global static names that match
        # a param.
        params = set()
        args = fn.args
        for a in args.posonlyargs + args.args + args.kwonlyargs:
            params.add(a.arg)
        return params & self.global_statics


def _local_bindings(fn: ast.AST) -> Set[str]:
    """Names bound inside `fn` (params + assignments) — everything else
    referenced is closed-over or global."""
    out: Set[str] = set()
    args = fn.args
    for a in (args.posonlyargs + args.args + args.kwonlyargs
              + ([args.vararg] if args.vararg else [])
              + ([args.kwarg] if args.kwarg else [])):
        out.add(a.arg)
    if isinstance(fn, ast.Lambda):
        return out
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                out.update(_target_names(t))
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign, ast.For)):
            tgt = node.target
            out.update(_target_names(tgt))
        elif isinstance(node, ast.withitem) and node.optional_vars:
            out.update(_target_names(node.optional_vars))
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if node is not fn:
                out.add(node.name)
    return out


def _target_names(t: ast.AST) -> Set[str]:
    out: Set[str] = set()
    for node in ast.walk(t):
        if isinstance(node, ast.Name):
            out.add(node.id)
    return out


class _TracedChecker:
    def __init__(self, mod: Module, collector: _TracedCollector,
                 np_aliases: Set[str], findings: List[Finding]):
        self.mod = mod
        self.collector = collector
        self.np_aliases = np_aliases
        self.findings = findings
        # set per checked function (_check_fn): names closed over from
        # enclosing scopes that carry traced values.
        self._closure_unsafe: Set[str] = set()

    def run(self) -> None:
        for _fid, (fn, _s) in self.collector.traced.items():
            self._check_fn(fn)

    def _emit(self, rule: str, node: ast.AST, msg: str,
              fn: ast.AST) -> None:
        symbol = self.mod.symbol_of(fn if not isinstance(fn, ast.Lambda)
                                    else node)
        self.findings.append(Finding(
            rule, self.mod.rel, node.lineno, node.col_offset, msg,
            symbol))

    def _check_fn(self, fn: ast.AST) -> None:
        statics = self.collector.statics_for(fn)
        locals_ = _local_bindings(fn)
        # Names closed over from ENCLOSING functions are traced values
        # unless the enclosing scope declares them static: a nested
        # scan/vmap body branching on its outer jitted function's array
        # is the flagship bug, and treating those names as "module
        # globals" would silence it. Enclosing statics (config threaded
        # into a lambda) stay safe.
        closure_unsafe: Set[str] = set()
        anc = self.mod.parents.get(fn)
        while anc is not None:
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                closure_unsafe |= _local_bindings(anc)
                if id(anc) in self.collector.traced:
                    closure_unsafe -= self.collector.statics_for(anc)
            anc = self.mod.parents.get(anc)
        closure_unsafe -= locals_ | statics
        # Kept SEPARATE from locals_: the mutation rules use locals_ to
        # detect closed-over receivers, which these names still are.
        self._closure_unsafe = closure_unsafe
        safe = set(statics)  # statics + shape-derived locals
        # Nested traced functions are checked on their own; skip their
        # bodies here to avoid double reports.
        nested_traced = {
            id(n) for n in ast.walk(fn)
            if id(n) in self.collector.traced and n is not fn
        }

        def walk(stmts):
            for stmt in stmts:
                if id(stmt) in nested_traced:
                    continue
                if isinstance(stmt, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    if id(stmt) not in self.collector.traced:
                        walk(stmt.body)
                    continue
                self._check_stmt(stmt, fn, statics, locals_, safe)
                # recurse into compound bodies
                for field in ("body", "orelse", "finalbody"):
                    inner = getattr(stmt, field, None)
                    if isinstance(inner, list) and inner and isinstance(
                            inner[0], ast.stmt):
                        walk(inner)
                for h in getattr(stmt, "handlers", []) or []:
                    walk(h.body)

        if isinstance(fn, ast.Lambda):
            self._check_exprs(fn.body, fn, statics, locals_, safe)
        else:
            walk(fn.body)

    # ------------------------------------------------------ statements

    def _check_stmt(self, stmt, fn, statics, locals_, safe) -> None:
        if isinstance(stmt, ast.Assign):
            for t in stmt.targets:
                self._check_store(t, fn, locals_)
            if self._expr_safe(stmt.value, safe, locals_):
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        safe.add(t.id)
            else:
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        safe.discard(t.id)
            self._check_exprs(stmt.value, fn, statics, locals_, safe)
        elif isinstance(stmt, ast.AugAssign):
            self._check_store(stmt.target, fn, locals_)
            self._check_exprs(stmt.value, fn, statics, locals_, safe)
        elif isinstance(stmt, (ast.Global, ast.Nonlocal)):
            self._emit(RULE_CLOSURE_MUT, stmt,
                       "global/nonlocal rebinding inside a traced "
                       "function runs at trace time only", fn)
        elif isinstance(stmt, (ast.If, ast.While)):
            if not self._expr_safe(stmt.test, safe, locals_):
                self._emit(
                    RULE_BRANCH, stmt,
                    "Python branch on a traced value (concretization "
                    "error or silent per-shape recompile); use "
                    "jnp.where/lax.cond, or derive the test from "
                    "static args / shapes", fn)
            self._check_exprs(stmt.test, fn, statics, locals_, safe)
        elif isinstance(stmt, ast.Assert):
            if not self._expr_safe(stmt.test, safe, locals_):
                self._emit(
                    RULE_BRANCH, stmt,
                    "assert on a traced value concretizes under trace",
                    fn)
        else:
            for child in ast.iter_child_nodes(stmt):
                if not isinstance(child, ast.stmt):
                    self._check_exprs(child, fn, statics, locals_, safe)

    def _check_store(self, target: ast.AST, fn, locals_) -> None:
        if isinstance(target, ast.Attribute):
            root = _root_name(target)
            if root == "self" or (root is not None
                                  and root not in locals_):
                self._emit(RULE_CLOSURE_MUT, target,
                           f"mutating closed-over state "
                           f"'{ast.unparse(target)}' inside a traced "
                           f"function runs at trace time only", fn)
        elif isinstance(target, ast.Subscript):
            root = _root_name(target)
            if root is not None and root not in locals_ and root != "_":
                self._emit(RULE_CLOSURE_MUT, target,
                           f"item-assigning closed-over '{root}' "
                           f"inside a traced function", fn)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for el in target.elts:
                self._check_store(el, fn, locals_)

    # ----------------------------------------------------- expressions

    def _check_exprs(self, node: ast.AST, fn, statics, locals_,
                     safe) -> None:
        # Manual stack so nested function/lambda subtrees are PRUNED —
        # they execute in their own traced context (checked separately
        # when traced) and their bodies must not double-report here.
        stack = [node]
        while stack:
            sub = stack.pop()
            for child in ast.iter_child_nodes(sub):
                if not isinstance(child, (ast.Lambda, ast.FunctionDef,
                                          ast.AsyncFunctionDef)):
                    stack.append(child)
            if not isinstance(sub, ast.Call):
                continue
            fname = _call_name(sub.func)
            root = _root_name(sub.func)
            if isinstance(sub.func, ast.Attribute):
                # jax.random / jnp are the sanctioned namespaces.
                if root in ("jax", "jnp", "lax"):
                    continue
                if root in IMPURE_ROOTS:
                    self._emit(
                        RULE_IMPURE, sub,
                        f"impure call '{root}.{fname}' in traced code "
                        f"executes at trace time only", fn)
                elif root in self.np_aliases:
                    self._emit(
                        RULE_HOST_SYNC, sub,
                        f"numpy call '{root}.{fname}' in traced code "
                        f"forces host materialization; use jnp", fn)
                elif fname in HOST_SYNC_ATTRS:
                    self._emit(
                        RULE_HOST_SYNC, sub,
                        f"'.{fname}()' in traced code forces a host "
                        f"sync", fn)
                elif fname in MUTATING_ATTRS and root is not None \
                        and root not in locals_:
                    self._emit(
                        RULE_CLOSURE_MUT, sub,
                        f"mutating closed-over '{root}.{fname}(...)' "
                        f"inside a traced function", fn)
            elif isinstance(sub.func, ast.Name):
                if fname in IMPURE_NAMES:
                    self._emit(
                        RULE_IMPURE, sub,
                        f"impure call '{fname}' in traced code", fn)
                elif fname in HOST_CAST_NAMES:
                    if any(not self._expr_safe(a, safe, locals_)
                           for a in sub.args):
                        self._emit(
                            RULE_HOST_SYNC, sub,
                            f"'{fname}()' on a traced value forces "
                            f"concretization; keep it an array or "
                            f"derive from statics", fn)

    def _expr_safe(self, node: ast.AST, safe, locals_) -> bool:
        """True when every root of `node` is trace-static: static
        params, shape queries, constants, module globals (names never
        bound locally)."""
        if isinstance(node, ast.Constant):
            return True
        if isinstance(node, ast.Name):
            if node.id in safe:
                return True
            if node.id in self._closure_unsafe:
                return False  # closed-over traced value
            if node.id not in locals_:
                return True  # module global / builtin: static object
            return False
        if isinstance(node, ast.Attribute):
            if node.attr in SHAPE_ATTRS:
                return True
            return self._expr_safe(node.value, safe, locals_)
        if isinstance(node, ast.Subscript):
            return (self._expr_safe(node.value, safe, locals_)
                    and self._expr_safe(node.slice, safe, locals_))
        if isinstance(node, ast.Call):
            fname = _call_name(node.func)
            if fname in SAFE_BUILTINS or fname in ("shape",):
                return all(self._expr_safe(a, safe, locals_)
                           for a in node.args)
            if isinstance(node.func, ast.Attribute):
                # x.bit_length(), np.shape(x): safe iff receiver safe
                return self._expr_safe(node.func.value, safe, locals_) \
                    and all(self._expr_safe(a, safe, locals_)
                            for a in node.args)
            return False
        if isinstance(node, (ast.BoolOp, ast.BinOp, ast.UnaryOp,
                             ast.Compare)):
            return all(self._expr_safe(c, safe, locals_)
                       for c in ast.iter_child_nodes(node)
                       if not isinstance(c, (ast.operator, ast.boolop,
                                             ast.unaryop, ast.cmpop)))
        if isinstance(node, (ast.Tuple, ast.List)):
            return all(self._expr_safe(e, safe, locals_)
                       for e in node.elts)
        return False


def _check_static_call_sites(mod: Module, registry: Dict[str, JitInfo],
                             findings: List[Finding]) -> None:
    np_aliases = _numpy_aliases(mod)
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        fname = _call_name(node.func)
        info = registry.get(fname or "")
        if info is None or not info.static_names:
            continue
        # positional
        for i, arg in enumerate(node.args):
            if i < len(info.params) and info.params[i] in \
                    info.static_names:
                self_msg = _unhashable_reason(arg, np_aliases)
                if self_msg:
                    findings.append(Finding(
                        RULE_STATIC, mod.rel, arg.lineno,
                        arg.col_offset,
                        f"static arg '{info.params[i]}' of jitted "
                        f"'{fname}' is {self_msg}: statics must be "
                        f"hashable (and stable across calls)",
                        mod.symbol_of(node)))
        for kw in node.keywords:
            if kw.arg in info.static_names:
                self_msg = _unhashable_reason(kw.value, np_aliases)
                if self_msg:
                    findings.append(Finding(
                        RULE_STATIC, mod.rel, kw.value.lineno,
                        kw.value.col_offset,
                        f"static arg '{kw.arg}' of jitted '{fname}' "
                        f"is {self_msg}: statics must be hashable",
                        mod.symbol_of(node)))


def _unhashable_reason(node: ast.AST, np_aliases) -> Optional[str]:
    if isinstance(node, (ast.List, ast.ListComp)):
        return "a list"
    if isinstance(node, (ast.Dict, ast.DictComp)):
        return "a dict"
    if isinstance(node, (ast.Set, ast.SetComp)):
        return "a set"
    if isinstance(node, ast.Call):
        fname = _call_name(node.func)
        if fname in ("list", "dict", "set", "bytearray"):
            return f"a {fname}()"
        root = _root_name(node.func)
        if root in np_aliases and fname in ("array", "asarray", "zeros",
                                            "ones", "full", "arange"):
            return "a numpy array"
    return None


def check(mod: Module, registry: Dict[str, JitInfo]) -> List[Finding]:
    findings: List[Finding] = []
    collector = _TracedCollector(mod)
    if collector.traced:
        _TracedChecker(mod, collector, _numpy_aliases(mod),
                       findings).run()
    _check_static_call_sites(mod, registry, findings)
    return findings
