"""ntalint driver: module parsing, suppressions, baseline machinery.

Pure stdlib (`ast` + `tokenize`-free line scans): the suite must run in
the tier-1 path on any box the tests run on, with zero dependencies
beyond the interpreter.

Baseline entries match findings by (rule, path, symbol) — line numbers
drift with every edit, while the enclosing def/class is stable across
reformatting. An entry carries a ``count`` so N pre-existing findings
in one function stay N: an N+1th is a NEW finding, and an entry whose
symbol no longer produces a finding is STALE (the non-growing-baseline
test fails on it — fixed findings must leave the baseline).
"""

from __future__ import annotations

import ast
import json
import os
import re
from typing import Dict, List, Optional, Tuple

_DISABLE_RE = re.compile(r"#\s*nta:\s*disable=([A-Za-z0-9_,\- ]+)")
_GUARDED_RE = re.compile(r"#\s*guarded-by:\s*([A-Za-z_][A-Za-z0-9_]*)")


class Finding:
    """One rule violation at one site."""

    __slots__ = ("rule", "path", "line", "col", "message", "symbol")

    def __init__(self, rule: str, path: str, line: int, col: int,
                 message: str, symbol: str = ""):
        self.rule = rule
        self.path = path
        self.line = line
        self.col = col
        self.message = message
        self.symbol = symbol  # enclosing Class.method / function

    def key(self) -> Tuple[str, str, str]:
        return (self.rule, self.path, self.symbol)

    def to_dict(self) -> dict:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "symbol": self.symbol,
            "message": self.message,
        }

    def render(self) -> str:
        sym = f" [{self.symbol}]" if self.symbol else ""
        return f"{self.path}:{self.line}:{self.col}: {self.rule}: " \
               f"{self.message}{sym}"

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"<Finding {self.render()}>"


class Module:
    """One parsed source file plus the per-line metadata every checker
    needs: raw lines (for `# guarded-by:` / `# nta: disable=` comment
    scans — ast drops comments) and a child->parent node map."""

    def __init__(self, path: str, rel: str, source: str):
        self.path = path
        self.rel = rel  # repo-relative, forward slashes (baseline key)
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        self.parents: Dict[ast.AST, ast.AST] = {}
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                self.parents[child] = node

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""

    def guarded_comment(self, lineno: int) -> Optional[str]:
        m = _GUARDED_RE.search(self.line_text(lineno))
        return m.group(1) if m else None

    def disabled_rules(self, lineno: int) -> set:
        """Rules disabled on this line (or 'all')."""
        m = _DISABLE_RE.search(self.line_text(lineno))
        if not m:
            return set()
        return {r.strip() for r in m.group(1).split(",") if r.strip()}

    def statement_line(self, node: ast.AST) -> int:
        """Line of the statement enclosing `node` (suppressions placed
        on a multi-line statement's first line cover the whole
        statement)."""
        cur = node
        while cur is not None and not isinstance(cur, ast.stmt):
            cur = self.parents.get(cur)
        return getattr(cur, "lineno", getattr(node, "lineno", 0))

    def symbol_of(self, node: ast.AST) -> str:
        """Dotted Class.method / function name enclosing `node`."""
        parts: List[str] = []
        cur = node
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.ClassDef)):
                parts.append(cur.name)
            cur = self.parents.get(cur)
        return ".".join(reversed(parts)) if parts else "<module>"


# ----------------------------------------------- intra-module call graph
#
# Shared by every manifest-reachability rule (locks.py
# NTA_DISPATCHER_ENTRYPOINTS, robustness.py NTA_RECORD_PATH): ONE
# definition of "reachable from" so the rules' notions of the call
# graph cannot drift. Direct calls only — `self.m()` within a class,
# bare `f()` at module level; references handed to pools/threads are
# not followed (they run on other threads, which is exactly the
# sanctioned fix for a dispatcher finding).


def module_functions(tree: ast.Module) -> Dict[str, "ast.FunctionDef"]:
    """qualname -> FunctionDef for every def: methods as Class.method,
    module-level functions bare."""
    functions: Dict[str, ast.FunctionDef] = {}
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            functions[node.name] = node
        elif isinstance(node, ast.ClassDef):
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                    functions[f"{node.name}.{sub.name}"] = sub
    return functions


def direct_calls(qual: str, fn: "ast.FunctionDef",
                 functions: Dict[str, "ast.FunctionDef"]) -> set:
    """The qualnames `fn` calls directly."""
    cls = qual.split(".")[0] if "." in qual else None
    out = set()
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        if (isinstance(func, ast.Attribute)
                and isinstance(func.value, ast.Name)
                and func.value.id == "self" and cls is not None):
            cand = f"{cls}.{func.attr}"
            if cand in functions:
                out.add(cand)
        elif isinstance(func, ast.Name) and func.id in functions:
            out.add(func.id)
    return out


def reachable_from(entries, functions: Dict[str, "ast.FunctionDef"],
                   calls: Dict[str, set]) -> set:
    """Transitive closure of `entries` over the direct-call graph."""
    seen = set()
    todo = [e for e in entries if e in functions]
    while todo:
        cur = todo.pop()
        if cur in seen:
            continue
        seen.add(cur)
        todo.extend(calls.get(cur, ()))
    return seen


def _iter_py_files(paths: List[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py"):
                out.append(p)
        else:
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(
                    d for d in dirs
                    if d != "__pycache__" and not d.startswith(".")
                )
                for f in sorted(files):
                    if f.endswith(".py"):
                        out.append(os.path.join(root, f))
    # de-dup, stable order
    seen = set()
    uniq = []
    for f in out:
        a = os.path.abspath(f)
        if a not in seen:
            seen.add(a)
            uniq.append(f)
    return uniq


def repo_root() -> str:
    """The repository root (two levels above this package)."""
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def _rel_path(path: str) -> str:
    root = repo_root()
    ap = os.path.abspath(path)
    if ap.startswith(root + os.sep):
        ap = ap[len(root) + 1:]
    return ap.replace(os.sep, "/")


def load_modules(
    paths: List[str],
) -> Tuple[List[Module], List[Finding]]:
    """(parsed modules, parse-error findings). A file that does not
    parse — common for --diff against a mid-edit working tree — is
    reported as a `parse-error` finding, not a crash: scripted
    consumers must be able to tell "findings" from "tool blew up"."""
    mods: List[Module] = []
    errors: List[Finding] = []
    for f in _iter_py_files(paths):
        with open(f, "r", encoding="utf-8") as fh:
            source = fh.read()
        try:
            mods.append(Module(f, _rel_path(f), source))
        except SyntaxError as e:
            errors.append(Finding(
                "parse-error", _rel_path(f), e.lineno or 0,
                (e.offset or 1) - 1,
                f"file does not parse: {e.msg}", "<module>"))
    return mods, errors


def analyze_paths(paths: List[str],
                  rules: Optional[set] = None) -> List[Finding]:
    """Run every checker over `paths`; returns findings with inline
    `# nta: disable=` suppressions already applied, sorted by
    (path, line, rule)."""
    from . import locks, purity, residency, robustness, snapshot

    modules, parse_errors = load_modules(paths)
    registry = purity.build_jit_registry(modules)
    findings: List[Finding] = list(parse_errors)
    for mod in modules:
        findings.extend(locks.check(mod))
        findings.extend(purity.check(mod, registry))
        findings.extend(snapshot.check(mod))
        findings.extend(robustness.check(mod))
        findings.extend(residency.check(mod))
    by_rel = {m.rel: m for m in modules}
    kept = []
    for f in findings:
        if rules is not None and f.rule not in rules:
            continue
        mod = by_rel.get(f.path)
        if mod is not None:
            # Union, not fallback: a suppression on the opening line of
            # a multi-line simple statement covers findings anywhere
            # inside it, even when an inner line carries its own
            # (different-rule) disable comment.
            disabled = mod.disabled_rules(f.line) | mod.disabled_rules(
                _enclosing_stmt_line(mod, f.line))
            if "all" in disabled or f.rule in disabled:
                continue
        kept.append(f)
    kept.sort(key=lambda f: (f.path, f.line, f.rule))
    return kept


def _enclosing_stmt_line(mod: Module, lineno: int) -> int:
    """Opening line of the innermost SIMPLE statement spanning
    `lineno`. Compound statements (with/if/for/def...) are excluded on
    purpose: a suppression on `with lock:` must not blanket the whole
    body."""
    best = None
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.stmt) or isinstance(
                node, (ast.With, ast.If, ast.For, ast.While, ast.Try,
                       ast.FunctionDef, ast.AsyncFunctionDef,
                       ast.ClassDef)):
            continue
        start = getattr(node, "lineno", None)
        end = getattr(node, "end_lineno", None)
        if start is None or end is None:
            continue
        # Innermost span wins = the latest opening line that still
        # covers the finding.
        if start <= lineno <= end and (best is None or start > best):
            best = start
    return best if best is not None else lineno


# ---------------------------------------------------------------- baseline

def default_baseline_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "baseline.json")


def load_baseline(path: Optional[str] = None) -> List[dict]:
    path = path or default_baseline_path()
    if not os.path.exists(path):
        return []
    with open(path, "r", encoding="utf-8") as fh:
        data = json.load(fh)
    return list(data.get("findings", []))


def apply_baseline(
    findings: List[Finding], baseline: List[dict]
) -> Tuple[List[Finding], List[dict]]:
    """Split `findings` against the baseline. Returns
    (new_findings, stale_entries): a baseline entry absorbs up to
    `count` (default 1) findings with its (rule, path, symbol); entries
    that absorb nothing are STALE — the finding they recorded was fixed
    and the entry must be deleted (non-growing baseline)."""
    budget: Dict[Tuple[str, str, str], int] = {}
    for ent in baseline:
        key = (ent["rule"], ent["path"], ent.get("symbol", ""))
        budget[key] = budget.get(key, 0) + int(ent.get("count", 1))
    used: Dict[Tuple[str, str, str], int] = {k: 0 for k in budget}
    new: List[Finding] = []
    for f in findings:
        k = f.key()
        if budget.get(k, 0) > used.get(k, 0):
            used[k] += 1
        else:
            new.append(f)
    # Staleness is judged per KEY (entries sharing a key pooled their
    # counts above), reported once on the key's first entry — judging
    # per entry would call a sibling stale when the first one already
    # accounted for the key's findings.
    stale: List[dict] = []
    reported = set()
    for ent in baseline:
        key = (ent["rule"], ent["path"], ent.get("symbol", ""))
        if key in reported:
            continue
        reported.add(key)
        have = used.get(key, 0)
        want = budget.get(key, 0)
        if have == 0:
            stale.append(ent)
        elif want > have:
            # partial staleness: more budget than findings
            over = dict(ent)
            over["stale_count"] = want - have
            stale.append(over)
    return new, stale


def write_baseline(findings: List[Finding],
                   path: Optional[str] = None) -> str:
    """Serialize current findings as the new baseline (counts folded
    per (rule, path, symbol))."""
    path = path or default_baseline_path()
    counts: Dict[Tuple[str, str, str], int] = {}
    for f in findings:
        counts[f.key()] = counts.get(f.key(), 0) + 1
    entries = [
        {"rule": r, "path": p, "symbol": s, "count": c}
        for (r, p, s), c in sorted(counts.items())
    ]
    with open(path, "w", encoding="utf-8") as fh:
        json.dump({"findings": entries}, fh, indent=2, sort_keys=True)
        fh.write("\n")
    return path
