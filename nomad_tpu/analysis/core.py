"""ntalint driver: module parsing, whole-program call graph,
suppressions, caching, baseline machinery.

Pure stdlib (`ast` + `hashlib` + line scans): the suite must run in
the tier-1 path on any box the tests run on, with zero dependencies
beyond the interpreter.

Baseline entries match findings by (rule, path, symbol) — line numbers
drift with every edit, while the enclosing def/class is stable across
reformatting. An entry carries a ``count`` so N pre-existing findings
in one function stay N: an N+1th is a NEW finding, and an entry whose
symbol no longer produces a finding is STALE (the non-growing-baseline
test fails on it — fixed findings must leave the baseline).

PR 7 split the suite into two passes:

- **local rules** run one module at a time (guarded-by, lock-blocking,
  purity, snapshot, unbounded-wait-in-scope, swallowed-exception,
  full-matrix-reship). Their findings are cached per file, keyed on
  (file sha, jit-registry digest, RULESET_VERSION).
- **program rules** run over the whole-program call graph built here
  (dispatcher-blocking-call, record-path-blocking, cross-module
  unbounded-wait, deadlock-cycle, raft-funnel). Their findings are
  cached on the digest of every analyzed (path, sha) pair — any edit
  anywhere re-runs them, which is the only sound invalidation for
  cross-module reachability.

The `Program` class is THE definition of "reachable from" for every
manifest rule: `from x import y` / `module.attr` / `self.method` /
constructor / typed-attribute calls resolve across `nomad_tpu/`;
dynamic dispatch (dict-of-handlers, references handed to pools or
`Thread(target=...)`) is deliberately NOT followed — handing work to
another thread is exactly the sanctioned fix for a dispatcher/record-
path finding, and guessing at dynamic targets would drown the rules
in false paths.
"""

from __future__ import annotations

import ast
import hashlib
import json
import os
import re
from typing import Dict, List, Optional, Set, Tuple

_DISABLE_RE = re.compile(r"#\s*nta:\s*disable=([A-Za-z0-9_,\- ]+)")
_GUARDED_RE = re.compile(r"#\s*guarded-by:\s*([A-Za-z_][A-Za-z0-9_]*)")

# Bump whenever any rule's behavior changes: every cache key includes
# it, so a stale on-disk cache from an older rule set can never mask a
# new finding (or resurrect a fixed one).
RULESET_VERSION = "9.0-compile-surface"


class Finding:
    """One rule violation at one site. `related` optionally carries
    the witness chain ("path:line" strings) for program-rule findings —
    the call path from the manifest entrypoint (or lock-cycle edges)
    to this site."""

    __slots__ = ("rule", "path", "line", "col", "message", "symbol",
                 "related")

    def __init__(self, rule: str, path: str, line: int, col: int,
                 message: str, symbol: str = "",
                 related: Optional[List[str]] = None):
        self.rule = rule
        self.path = path
        self.line = line
        self.col = col
        self.message = message
        self.symbol = symbol  # enclosing Class.method / function
        self.related = related

    def key(self) -> Tuple[str, str, str]:
        return (self.rule, self.path, self.symbol)

    def to_dict(self) -> dict:
        d = {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "symbol": self.symbol,
            "message": self.message,
        }
        if self.related:
            d["related"] = list(self.related)
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "Finding":
        return cls(d["rule"], d["path"], d["line"], d["col"],
                   d["message"], d.get("symbol", ""),
                   list(d["related"]) if d.get("related") else None)

    def render(self) -> str:
        sym = f" [{self.symbol}]" if self.symbol else ""
        return f"{self.path}:{self.line}:{self.col}: {self.rule}: " \
               f"{self.message}{sym}"

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"<Finding {self.render()}>"


class Module:
    """One parsed source file plus the per-line metadata every checker
    needs: raw lines (for `# guarded-by:` / `# nta: disable=` comment
    scans — ast drops comments) and a child->parent node map."""

    def __init__(self, path: str, rel: str, source: str):
        self.path = path
        self.rel = rel  # repo-relative, forward slashes (baseline key)
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        self.parents: Dict[ast.AST, ast.AST] = {}
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                self.parents[child] = node

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""

    def guarded_comment(self, lineno: int) -> Optional[str]:
        m = _GUARDED_RE.search(self.line_text(lineno))
        return m.group(1) if m else None

    def disabled_rules(self, lineno: int) -> set:
        """Rules disabled on this line (or 'all')."""
        m = _DISABLE_RE.search(self.line_text(lineno))
        if not m:
            return set()
        return {r.strip() for r in m.group(1).split(",") if r.strip()}

    def statement_line(self, node: ast.AST) -> int:
        """Line of the statement enclosing `node` (suppressions placed
        on a multi-line statement's first line cover the whole
        statement)."""
        cur = node
        while cur is not None and not isinstance(cur, ast.stmt):
            cur = self.parents.get(cur)
        return getattr(cur, "lineno", getattr(node, "lineno", 0))

    def symbol_of(self, node: ast.AST) -> str:
        """Dotted Class.method / function name enclosing `node`."""
        parts: List[str] = []
        cur = node
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.ClassDef)):
                parts.append(cur.name)
            cur = self.parents.get(cur)
        return ".".join(reversed(parts)) if parts else "<module>"


# -------------------------------------------- whole-program call graph

# A function's global identity: (module rel path, qualname).
FnKey = Tuple[str, str]
# A class's global identity: (module rel path, class name).
ClsKey = Tuple[str, str]


def _flatten_attr_chain(node: ast.AST) -> Optional[List[str]]:
    """["a", "b", "c"] for `a.b.c`; None when the chain roots in
    anything but a bare Name (calls, subscripts: dynamic, give up)."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        parts.reverse()
        return parts
    return None


class _ModInfo:
    """Per-module raw facts gathered in pass 1 of the Program build."""

    __slots__ = ("mod", "dotted", "is_pkg", "bindings", "plain_imports",
                 "classes", "class_base_exprs", "init_attr_calls")

    def __init__(self, mod: Module, dotted: str, is_pkg: bool):
        self.mod = mod
        self.dotted = dotted
        self.is_pkg = is_pkg
        # local name -> ("mod", dotted) | ("sym", dotted, origname)
        self.bindings: Dict[str, tuple] = {}
        self.plain_imports: Set[str] = set()  # `import a.b.c` dotted names
        self.classes: Dict[str, ast.ClassDef] = {}
        self.class_base_exprs: Dict[str, List[ast.expr]] = {}
        # cls -> [(attr, ctor-call func expr)] from __init__ bodies
        self.init_attr_calls: Dict[str, List[Tuple[str, ast.AST]]] = {}


class Program:
    """Whole-program symbol table + call graph over one analyzed set
    of modules. Conservative on dynamic dispatch: a call is an edge
    only when its target resolves statically through

    - same-module defs (bare ``f()``) and ``self.method()`` (including
      inherited methods through resolvable base classes),
    - ``from x import y`` symbols (functions, classes -> ``__init__``,
      ``Class.method`` classmethod-style calls),
    - ``import x`` / ``from pkg import submod`` module-attribute calls
      (``mod.f()``, chasing re-exports through ``__init__`` modules),
    - attributes typed by construction (``self.state = StateStore()``
      in ``__init__`` makes ``self.state.upsert_evals()`` an edge), and
    - locals typed by construction (``h = Harness(); h.submit_plan()``).

    References handed to pools/threads/handler dicts are not followed.
    """

    def __init__(self, modules: List[Module]):
        self.modules = [m for m in modules]
        self.by_rel: Dict[str, Module] = {m.rel: m for m in modules}
        self._infos: Dict[str, _ModInfo] = {}
        self._by_dotted: Dict[str, str] = {}  # dotted -> rel
        self.functions: Dict[FnKey, ast.AST] = {}
        self.classes: Dict[ClsKey, ast.ClassDef] = {}
        self.class_bases: Dict[ClsKey, List[ClsKey]] = {}
        # ClsKey -> attr -> ClsKey (types inferred from __init__ ctors)
        self.attr_types: Dict[ClsKey, Dict[str, ClsKey]] = {}
        self.calls: Dict[FnKey, Set[FnKey]] = {}
        # FnKey -> ClsKey (factory return types, from annotations or
        # ctor-returning bodies: `def get_batcher() -> PlacementBatcher`)
        self.return_types: Dict[FnKey, ClsKey] = {}
        # manifest var name -> {rel: [entries]}
        self.manifests: Dict[str, Dict[str, List[str]]] = {}
        # manifest var name -> {rel: assignment line}
        self.manifest_lines: Dict[str, Dict[str, int]] = {}
        self._build()

    # ------------------------------------------------------- pass 1

    @staticmethod
    def module_dotted(rel: str) -> str:
        p = rel[:-3] if rel.endswith(".py") else rel
        p = p.lstrip("/")
        if p.endswith("/__init__"):
            p = p[: -len("/__init__")]
        return p.replace("/", ".")

    def _build(self) -> None:
        for mod in self.modules:
            info = _ModInfo(mod, self.module_dotted(mod.rel),
                            mod.rel.endswith("/__init__.py"))
            self._infos[mod.rel] = info
            self._by_dotted[info.dotted] = mod.rel
            self._scan_module(info)
        for rel, info in self._infos.items():
            self._resolve_bases(rel, info)
        for rel, info in self._infos.items():
            self._resolve_attr_types(rel, info)
        for key, fn in self.functions.items():
            self._infer_return_type(key, fn)
        for key, fn in self.functions.items():
            self.calls[key] = self._function_calls(key, fn)

    def _scan_module(self, info: _ModInfo) -> None:
        mod = info.mod
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.asname:
                        info.bindings[alias.asname] = ("mod", alias.name)
                    else:
                        info.plain_imports.add(alias.name)
            elif isinstance(node, ast.ImportFrom):
                target = self._import_from_target(info, node)
                if target is None:
                    continue
                for alias in node.names:
                    if alias.name == "*":
                        continue
                    info.bindings[alias.asname or alias.name] = (
                        "sym", target, alias.name)
        for node in mod.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.functions[(mod.rel, node.name)] = node
            elif isinstance(node, ast.ClassDef):
                info.classes[node.name] = node
                self.classes[(mod.rel, node.name)] = node
                info.class_base_exprs[node.name] = list(node.bases)
                for sub in node.body:
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        self.functions[
                            (mod.rel, f"{node.name}.{sub.name}")] = sub
                        if sub.name == "__init__":
                            self._scan_init_attrs(info, node.name, sub)
            elif isinstance(node, ast.Assign):
                for tgt in node.targets:
                    if (isinstance(tgt, ast.Name)
                            and tgt.id.startswith("NTA_")):
                        vals = _string_elems(node.value)
                        if vals:
                            self.manifests.setdefault(
                                tgt.id, {})[mod.rel] = vals
                            self.manifest_lines.setdefault(
                                tgt.id, {})[mod.rel] = node.lineno

    def _import_from_target(self, info: _ModInfo,
                            node: ast.ImportFrom) -> Optional[str]:
        if node.level == 0:
            return node.module
        # Relative: level 1 = this module's package, each extra level
        # pops one more component. For an __init__ module the dotted
        # name (which dropped the "__init__" segment) IS the package.
        parts = info.dotted.split(".")
        base = parts if info.is_pkg else parts[:-1]
        for _ in range(node.level - 1):
            if not base:
                return None
            base = base[:-1]
        if node.module:
            base = base + node.module.split(".")
        return ".".join(base) if base else None

    def _scan_init_attrs(self, info: _ModInfo, cls: str,
                         init: ast.AST) -> None:
        rows = info.init_attr_calls.setdefault(cls, [])
        for stmt in ast.walk(init):
            if not isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                continue
            targets = (stmt.targets if isinstance(stmt, ast.Assign)
                       else [stmt.target])
            value = stmt.value
            for tgt in targets:
                if not (isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "self"):
                    continue
                for call in _ctor_candidates(value):
                    rows.append((tgt.attr, call.func))

    # ------------------------------------------------------- pass 2

    def resolve_module(self, importer_rel: str,
                       dotted: Optional[str]) -> Optional[str]:
        """rel path of the module named `dotted`, preferring an exact
        match, falling back to a unique dotted-suffix match (fixture
        trees are not importable packages — `from helper import nap`
        in a tmp dir must still resolve to the sibling).

        The suffix fallback is ONLY for out-of-repo importers (their
        rel paths are absolute): inside the repo package every import
        resolves exactly (relative imports expand to exact dotted
        names), and suffix-matching there would misresolve stdlib
        imports onto same-named repo modules (`import select` in
        utils/httppool.py must NOT become scheduler/select.py — a
        phantom edge from server-reachable code into scheduler/)."""
        if not dotted:
            return None
        rel = self._by_dotted.get(dotted)
        if rel is not None:
            return rel
        if not importer_rel.startswith("/"):
            return None  # in-repo importer: exact matches only
        suffix = "." + dotted
        cands = [r for d, r in self._by_dotted.items()
                 if d.endswith(suffix)]
        if len(cands) == 1:
            return cands[0]
        if len(cands) > 1:
            # prefer a sibling of the importer
            base = os.path.dirname(importer_rel)
            sibs = [r for r in cands if os.path.dirname(r) == base]
            if len(sibs) == 1:
                return sibs[0]
        return None

    def _resolve_symbol(self, importer_rel: str, mod_dotted: str,
                        name: str, seen: Optional[set] = None):
        """('fn', FnKey) | ('cls', ClsKey) | ('modref', dotted) | None
        for symbol `name` in module `mod_dotted`, chasing re-export
        chains (`from .recorder import record_span` in __init__)."""
        if seen is None:
            seen = set()
        if (mod_dotted, name) in seen:
            return None
        seen.add((mod_dotted, name))
        rel = self.resolve_module(importer_rel, mod_dotted)
        if rel is not None:
            if (rel, name) in self.functions:
                return ("fn", (rel, name))
            if (rel, name) in self.classes:
                return ("cls", (rel, name))
            binding = self._infos[rel].bindings.get(name)
            if binding is not None:
                if binding[0] == "sym":
                    res = self._resolve_symbol(rel, binding[1],
                                               binding[2], seen)
                    if res is not None:
                        return res
                elif binding[0] == "mod":
                    return ("modref", binding[1])
        # `from pkg import submod`: the symbol IS a module
        sub = f"{mod_dotted}.{name}"
        if self.resolve_module(importer_rel, sub) is not None:
            return ("modref", sub)
        return None

    def _resolve_bases(self, rel: str, info: _ModInfo) -> None:
        for cls, base_exprs in info.class_base_exprs.items():
            out: List[ClsKey] = []
            for expr in base_exprs:
                res = self._resolve_class_expr(rel, expr)
                if res is not None:
                    out.append(res)
            self.class_bases[(rel, cls)] = out

    def _resolve_class_expr(self, rel: str,
                            expr: ast.AST) -> Optional[ClsKey]:
        parts = _flatten_attr_chain(expr)
        if not parts:
            return None
        info = self._infos[rel]
        if len(parts) == 1:
            name = parts[0]
            if name in info.classes:
                return (rel, name)
            binding = info.bindings.get(name)
            if binding and binding[0] == "sym":
                res = self._resolve_symbol(rel, binding[1], binding[2])
                if res and res[0] == "cls":
                    return res[1]
            return None
        # module.Class chains
        res = self._resolve_dotted_value(rel, parts)
        if res and res[0] == "cls":
            return res[1]
        return None

    def _resolve_dotted_value(self, rel: str, parts: List[str]):
        """Resolve `a.b.c` value chains through import bindings."""
        info = self._infos[rel]
        binding = info.bindings.get(parts[0])
        if binding is None:
            # plain `import a.b.c` usage: longest module prefix wins
            for k in range(len(parts) - 1, 0, -1):
                dotted = ".".join(parts[:k])
                if any(p == dotted or p.startswith(dotted + ".")
                       for p in info.plain_imports):
                    if self.resolve_module(rel, dotted) is not None:
                        return self._chase_modref(rel, dotted, parts[k:])
            return None
        if binding[0] == "mod":
            return self._chase_modref(rel, binding[1], parts[1:])
        # ("sym", M, orig)
        res = self._resolve_symbol(rel, binding[1], binding[2])
        if res is None:
            return None
        if res[0] == "modref":
            return self._chase_modref(rel, res[1], parts[1:])
        if res[0] == "cls" and len(parts) == 2:
            # ImportedClass.method / ImportedClass.classmethod
            m = self.lookup_method(res[1], parts[1])
            if m is not None:
                return ("fn", m)
            return ("cls_attr", res[1])
        if len(parts) == 1:
            return res
        return None

    def _chase_modref(self, importer_rel: str, dotted: str,
                      rest: List[str]):
        """Walk remaining attribute parts down from a module ref."""
        while len(rest) > 1:
            nxt = f"{dotted}.{rest[0]}"
            if self.resolve_module(importer_rel, nxt) is not None:
                dotted, rest = nxt, rest[1:]
                continue
            break
        if not rest:
            return ("modref", dotted)
        if len(rest) == 1:
            res = self._resolve_symbol(importer_rel, dotted, rest[0])
            return res
        # module.Class.method
        res = self._resolve_symbol(importer_rel, dotted, rest[0])
        if res and res[0] == "cls" and len(rest) == 2:
            m = self.lookup_method(res[1], rest[1])
            if m is not None:
                return ("fn", m)
        return None

    def lookup_method(self, clskey: ClsKey, name: str,
                      seen: Optional[set] = None) -> Optional[FnKey]:
        if seen is None:
            seen = set()
        if clskey in seen:
            return None
        seen.add(clskey)
        rel, cls = clskey
        key = (rel, f"{cls}.{name}")
        if key in self.functions:
            return key
        for base in self.class_bases.get(clskey, ()):
            found = self.lookup_method(base, name, seen)
            if found is not None:
                return found
        return None

    def _resolve_attr_types(self, rel: str, info: _ModInfo) -> None:
        for cls, rows in info.init_attr_calls.items():
            out = self.attr_types.setdefault((rel, cls), {})
            for attr, func_expr in rows:
                res = None
                parts = _flatten_attr_chain(func_expr)
                if parts:
                    if len(parts) == 1 and parts[0] in info.classes:
                        res = (rel, parts[0])
                    else:
                        r = self._resolve_dotted_value(rel, parts)
                        if r and r[0] == "cls":
                            res = r[1]
                if res is not None:
                    out[attr] = res

    # ------------------------------------------------------- pass 3

    def _infer_return_type(self, key: FnKey, fn: ast.AST) -> None:
        """Factory return types: a resolvable `-> Cls` annotation, or
        every-return-is-a-ctor bodies. Lets `get_batcher().place(...)`
        resolve through the singleton accessor."""
        rel, _qual = key
        ann = getattr(fn, "returns", None)
        if ann is not None:
            res = self._resolve_class_expr_or_value(rel, ann)
            if res is not None:
                self.return_types[key] = res
                return
        for node in ast.walk(fn):
            if isinstance(node, ast.Return) and isinstance(
                    node.value, ast.Call):
                res = self._resolve_class_expr_or_value(
                    rel, node.value.func)
                if res is not None:
                    self.return_types[key] = res
                    return

    def _local_types(self, rel: str, cls: Optional[str],
                     fn: ast.AST) -> Dict[str, ClsKey]:
        """Locals typed by construction: `x = Ctor(...)` — or by a
        typed factory: `b = get_batcher()`."""
        out: Dict[str, ClsKey] = {}
        for stmt in ast.walk(fn):
            if not isinstance(stmt, ast.Assign):
                continue
            for call in _ctor_candidates(stmt.value):
                res = self._resolve_class_expr_or_value(rel, call.func)
                if res is None:
                    target = self.resolve_call(rel, cls, call.func)
                    if target is not None:
                        res = self.return_types.get(target)
                if res is None:
                    continue
                for tgt in stmt.targets:
                    if isinstance(tgt, ast.Name):
                        out[tgt.id] = res
        return out

    def _resolve_class_expr_or_value(self, rel: str,
                                     expr: ast.AST) -> Optional[ClsKey]:
        parts = _flatten_attr_chain(expr)
        if not parts:
            return None
        info = self._infos[rel]
        if len(parts) == 1 and parts[0] in info.classes:
            return (rel, parts[0])
        res = (self._resolve_dotted_value(rel, parts)
               if len(parts) > 1 or parts[0] in info.bindings else None)
        if res and res[0] == "cls":
            return res[1]
        return None

    def resolve_call(self, rel: str, cls: Optional[str],
                     func: ast.AST,
                     local_types: Optional[Dict[str, ClsKey]] = None,
                     ) -> Optional[FnKey]:
        """FnKey the call expression `func` targets, or None."""
        if (isinstance(func, ast.Attribute)
                and isinstance(func.value, ast.Call)):
            # factory().method(): resolve through the factory's
            # inferred return type
            inner = self.resolve_call(rel, cls, func.value.func,
                                      local_types)
            if inner is not None:
                t = self.return_types.get(inner)
                if t is not None:
                    return self.lookup_method(t, func.attr)
            return None
        parts = _flatten_attr_chain(func)
        if not parts:
            return None
        info = self._infos.get(rel)
        if info is None:
            return None
        if parts[0] == "self" and cls is not None:
            if len(parts) == 2:
                return self.lookup_method((rel, cls), parts[1])
            if len(parts) == 3:
                t = self.attr_types.get((rel, cls), {}).get(parts[1])
                if t is not None:
                    return self.lookup_method(t, parts[2])
            return None
        if local_types and parts[0] in local_types and len(parts) == 2:
            return self.lookup_method(local_types[parts[0]], parts[1])
        if len(parts) == 1:
            name = parts[0]
            if (rel, name) in self.functions:
                return (rel, name)
            if name in info.classes:
                return self.lookup_method((rel, name), "__init__")
            binding = info.bindings.get(name)
            if binding and binding[0] == "sym":
                res = self._resolve_symbol(rel, binding[1], binding[2])
                if res is not None:
                    if res[0] == "fn":
                        return res[1]
                    if res[0] == "cls":
                        return self.lookup_method(res[1], "__init__")
            return None
        res = self._resolve_dotted_value(rel, parts)
        if res is not None:
            if res[0] == "fn":
                return res[1]
            if res[0] == "cls":
                return self.lookup_method(res[1], "__init__")
        return None

    def _function_calls(self, key: FnKey, fn: ast.AST) -> Set[FnKey]:
        rel, qual = key
        cls = qual.split(".")[0] if "." in qual else None
        local_types = self._local_types(rel, cls, fn)
        out: Set[FnKey] = set()
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            target = self.resolve_call(rel, cls, node.func, local_types)
            if target is not None and target != key:
                out.add(target)
        return out

    # ------------------------------------------------- reachability

    def manifest_entries(self, var: str) -> List[FnKey]:
        out: List[FnKey] = []
        for rel, quals in self.manifests.get(var, {}).items():
            for q in quals:
                if (rel, q) in self.functions:
                    out.append((rel, q))
        return out

    def reachable_with_paths(
        self, entries: List[FnKey],
    ) -> Dict[FnKey, Tuple[FnKey, Optional[FnKey]]]:
        """BFS closure: fn -> (entry it is reachable from, calling fn
        one step back toward the entry, or None for the entry itself).
        First discovery wins, so chains are shortest-path witnesses."""
        via: Dict[FnKey, Tuple[FnKey, Optional[FnKey]]] = {}
        todo = []
        for e in entries:
            if e in self.functions and e not in via:
                via[e] = (e, None)
                todo.append(e)
        while todo:
            cur = todo.pop(0)
            entry = via[cur][0]
            for nxt in sorted(self.calls.get(cur, ())):
                if nxt not in via:
                    via[nxt] = (entry, cur)
                    todo.append(nxt)
        return via

    def witness_chain(self, via, key: FnKey) -> List[FnKey]:
        """entry -> ... -> key, reconstructed from `via`."""
        chain = [key]
        seen = {key}
        while True:
            parent = via[chain[-1]][1]
            if parent is None or parent in seen:
                break
            chain.append(parent)
            seen.add(parent)
        chain.reverse()
        return chain

    def witness_info(self, via, key: FnKey) -> Tuple[str, List[str]]:
        """(note, related) for a program-rule finding at `key`: the
        entry/chain suffix for the message, and the "path:line"
        witness locations for `Finding.related` — ONE formatting for
        every manifest rule, so --diff region attribution and SARIF
        relatedLocations cannot drift between rules."""
        chain = self.witness_chain(via, key)
        entry = via[key][0]
        note = f": entry '{entry[1]}' ({entry[0]})"
        if len(chain) > 1:
            note += " via " + " -> ".join(q for (_r, q) in chain)
        related = [
            f"{r}:{getattr(self.functions[(r, q)], 'lineno', 0)}"
            for (r, q) in chain]
        return note, related


def _string_elems(node: ast.AST) -> List[str]:
    out = []
    if isinstance(node, (ast.Tuple, ast.List)):
        for el in node.elts:
            if isinstance(el, ast.Constant) and isinstance(el.value, str):
                out.append(el.value)
    return out


def _ctor_candidates(value: Optional[ast.AST]) -> List[ast.Call]:
    """Call nodes that may type an assignment target: a direct call,
    or the operands of `x or Ctor()` defaulting idioms."""
    if isinstance(value, ast.Call):
        return [value]
    if isinstance(value, ast.BoolOp):
        return [v for v in value.values if isinstance(v, ast.Call)]
    if isinstance(value, ast.IfExp):
        return [v for v in (value.body, value.orelse)
                if isinstance(v, ast.Call)]
    return []


# ------------------------------------------------------- file loading

def _iter_py_files(paths: List[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py"):
                out.append(p)
        else:
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(
                    d for d in dirs
                    if d != "__pycache__" and not d.startswith(".")
                )
                for f in sorted(files):
                    if f.endswith(".py"):
                        out.append(os.path.join(root, f))
    # de-dup, stable order
    seen = set()
    uniq = []
    for f in out:
        a = os.path.abspath(f)
        if a not in seen:
            seen.add(a)
            uniq.append(f)
    return uniq


def repo_root() -> str:
    """The repository root (two levels above this package)."""
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def _rel_path(path: str) -> str:
    root = repo_root()
    ap = os.path.abspath(path)
    if ap.startswith(root + os.sep):
        ap = ap[len(root) + 1:]
    return ap.replace(os.sep, "/")


def _sha1(data: bytes) -> str:
    return hashlib.sha1(data).hexdigest()


# In-process caches. Keyed on content hashes + RULESET_VERSION, never
# on mtimes: the tier-1 test analyzes the tree several times per
# process (gate + non-growing-baseline + per-dir self-checks) and must
# pay the whole-program pass once.
_PARSE_CACHE: Dict[str, tuple] = {}  # abspath -> (sha, Module|None, err)
_LOCAL_CACHE: Dict[tuple, List[Finding]] = {}
_PROGRAM_CACHE: Dict[tuple, List[Finding]] = {}
_REGISTRY_CACHE: Dict[str, tuple] = {}  # tree digest -> (registry, digest)


def clear_caches() -> None:
    _PARSE_CACHE.clear()
    _LOCAL_CACHE.clear()
    _PROGRAM_CACHE.clear()
    _REGISTRY_CACHE.clear()


def _load_file(path: str) -> tuple:
    """(sha, Module|None, parse_error Finding|None), parse-cached."""
    ap = os.path.abspath(path)
    with open(path, "r", encoding="utf-8") as fh:
        source = fh.read()
    sha = _sha1(source.encode("utf-8"))
    hit = _PARSE_CACHE.get(ap)
    if hit is not None and hit[0] == sha:
        return hit
    rel = _rel_path(path)
    try:
        entry = (sha, Module(path, rel, source), None)
    except SyntaxError as e:
        entry = (sha, None, Finding(
            "parse-error", rel, e.lineno or 0, (e.offset or 1) - 1,
            f"file does not parse: {e.msg}", "<module>"))
    _PARSE_CACHE[ap] = entry
    return entry


def load_modules(
    paths: List[str],
) -> Tuple[List[Module], List[Finding]]:
    """(parsed modules, parse-error findings). A file that does not
    parse — common for --diff against a mid-edit working tree — is
    reported as a `parse-error` finding, not a crash: scripted
    consumers must be able to tell "findings" from "tool blew up"."""
    mods: List[Module] = []
    errors: List[Finding] = []
    for f in _iter_py_files(paths):
        sha, mod, err = _load_file(f)
        if mod is not None:
            mods.append(mod)
        if err is not None:
            errors.append(err)
    return mods, errors


def _registry_digest(registry) -> str:
    rows = sorted(
        (name, tuple(info.params), tuple(sorted(info.static_names)))
        for name, info in registry.items())
    return _sha1(repr(rows).encode("utf-8"))


def _suppressed(mod: Optional[Module], f: Finding) -> bool:
    if mod is None:
        return False
    # Union, not fallback: a suppression on the opening line of a
    # multi-line simple statement covers findings anywhere inside it,
    # even when an inner line carries its own (different-rule) disable
    # comment.
    disabled = mod.disabled_rules(f.line) | mod.disabled_rules(
        _enclosing_stmt_line(mod, f.line))
    return "all" in disabled or f.rule in disabled


def analyze_paths(paths: List[str],
                  rules: Optional[set] = None) -> List[Finding]:
    """Run every checker over `paths`; returns findings with inline
    `# nta: disable=` suppressions already applied, sorted by
    (path, line, rule).

    Local rules come from the per-file cache when (sha, registry
    digest) match; program rules from the tree-digest cache when no
    analyzed file changed."""
    from . import (compile_surface, deadlock, locks, protocol, purity,
                   residency, robustness, snapshot)

    files = _iter_py_files(paths)
    loaded = [(_load_file(f)) for f in files]
    modules = [m for (_sha, m, _e) in loaded if m is not None]
    parse_errors = [e for (_sha, _m, e) in loaded if e is not None]
    by_rel = {m.rel: m for m in modules}

    tree_digest = _sha1("\n".join(
        f"{m.rel}:{sha}" for (sha, m, _e) in loaded
        if m is not None).encode("utf-8"))
    reg_hit = _REGISTRY_CACHE.get(tree_digest)
    if reg_hit is None:
        registry = purity.build_jit_registry(modules)
        reg_hit = (registry, _registry_digest(registry))
        _REGISTRY_CACHE[tree_digest] = reg_hit
    registry, reg_digest = reg_hit

    findings: List[Finding] = list(parse_errors)

    # ---- local pass (per-file cache)
    for (sha, mod, _err), path in zip(loaded, files):
        if mod is None:
            continue
        key = (os.path.abspath(path), sha, reg_digest, RULESET_VERSION)
        cached = _LOCAL_CACHE.get(key)
        if cached is None:
            local: List[Finding] = []
            local.extend(locks.check(mod))
            local.extend(purity.check(mod, registry))
            local.extend(snapshot.check(mod))
            local.extend(robustness.check(mod))
            local.extend(residency.check(mod))
            cached = [f for f in local if not _suppressed(mod, f)]
            _LOCAL_CACHE[key] = cached
        findings.extend(cached)

    # ---- program pass (tree-digest cache). Skipped outright when the
    # rules filter excludes every program rule (bench's purity gate):
    # building the cross-module graph to discard its findings is the
    # most expensive no-op in the suite.
    program_rules = {"dispatcher-blocking-call", "record-path-blocking",
                     "unbounded-wait", "deadlock-cycle", "raft-funnel",
                     "unbucketed-shape", "static-key-drift",
                     "unregistered-jit", "donation-unsafe-read"}
    if rules is not None and not (rules & program_rules):
        findings = [f for f in findings if f.rule in rules]
        findings.sort(key=lambda f: (f.path, f.line, f.rule))
        return findings
    pkey = (tree_digest, RULESET_VERSION)
    prog_findings = _PROGRAM_CACHE.get(pkey)
    if prog_findings is None:
        program = Program(modules)
        raw: List[Finding] = []
        raw.extend(locks.program_check(program))
        raw.extend(robustness.program_check(program))
        raw.extend(deadlock.program_check(program))
        raw.extend(protocol.program_check(program))
        raw.extend(compile_surface.program_check(program))
        prog_findings = [f for f in raw
                         if not _suppressed(by_rel.get(f.path), f)]
        _PROGRAM_CACHE[pkey] = prog_findings
    findings.extend(prog_findings)

    if rules is not None:
        findings = [f for f in findings if f.rule in rules]
    findings = list(findings)
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings


def _enclosing_stmt_line(mod: Module, lineno: int) -> int:
    """Opening line of the innermost SIMPLE statement spanning
    `lineno`. Compound statements (with/if/for/def...) are excluded on
    purpose: a suppression on `with lock:` must not blanket the whole
    body."""
    best = None
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.stmt) or isinstance(
                node, (ast.With, ast.If, ast.For, ast.While, ast.Try,
                       ast.FunctionDef, ast.AsyncFunctionDef,
                       ast.ClassDef)):
            continue
        start = getattr(node, "lineno", None)
        end = getattr(node, "end_lineno", None)
        if start is None or end is None:
            continue
        # Innermost span wins = the latest opening line that still
        # covers the finding.
        if start <= lineno <= end and (best is None or start > best):
            best = start
    return best if best is not None else lineno


# ------------------------------------------------------ disk cache
#
# Cross-process reuse for the CLI (`tools/ntalint.py`): local findings
# per (rel, sha, registry digest), program findings per tree digest.
# The cache can only SKIP work whose inputs hash identically under the
# same RULESET_VERSION — a version bump or any content change falls
# back to a full compute, so a poisoned cache at worst costs time.

def load_disk_cache(path: str) -> None:
    """Prime the in-process caches from a cache file. Best-effort in
    the strongest sense: a missing, truncated, corrupted or
    old-schema cache primes nothing (and at worst costs a recompute)
    — it must never crash the CLI."""
    try:
        with open(path, "r", encoding="utf-8") as fh:
            data = json.load(fh)
        if data.get("version") != RULESET_VERSION:
            return
        root = repo_root()
        for rel, ent in data.get("local", {}).items():
            ap = os.path.join(root, rel.replace("/", os.sep))
            key = (os.path.abspath(ap), ent["sha"], ent["registry"],
                   RULESET_VERSION)
            _LOCAL_CACHE.setdefault(key, [
                Finding.from_dict(d) for d in ent["findings"]])
        prog = data.get("program")
        if isinstance(prog, dict):
            for digest, ent in prog.items():
                if not isinstance(ent, list):
                    continue  # pre-PR-review schema: skip
                _PROGRAM_CACHE.setdefault(
                    (digest, RULESET_VERSION),
                    [Finding.from_dict(d) for d in ent])
    except (OSError, ValueError, KeyError, TypeError, AttributeError):
        clear_caches()  # half-primed state is worse than cold


def save_disk_cache(path: str) -> None:
    """Serialize the in-process caches for the next CLI run."""
    root = os.path.abspath(repo_root())
    local = {}
    for (ap, sha, reg, _ver), fnds in _LOCAL_CACHE.items():
        if not ap.startswith(root + os.sep):
            continue  # fixture/tmp files: not worth persisting
        rel = ap[len(root) + 1:].replace(os.sep, "/")
        local[rel] = {"sha": sha, "registry": reg,
                      "findings": [f.to_dict() for f in fnds]}
    # Every digest entry survives: one CLI process may analyze several
    # path subsets (a loaded full-tree entry plus this run's ops/
    # subset), and keeping only the last would evict the expensive
    # full-tree entry. Entries are digest-keyed, so extras are inert.
    program = {
        digest: [f.to_dict() for f in fnds]
        for (digest, _ver), fnds in _PROGRAM_CACHE.items()
    }
    data = {"version": RULESET_VERSION, "local": local,
            "program": program}
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(data, fh)
    os.replace(tmp, path)


# ---------------------------------------------------------------- baseline

def default_baseline_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "baseline.json")


def load_baseline(path: Optional[str] = None) -> List[dict]:
    path = path or default_baseline_path()
    if not os.path.exists(path):
        return []
    with open(path, "r", encoding="utf-8") as fh:
        data = json.load(fh)
    return list(data.get("findings", []))


def apply_baseline(
    findings: List[Finding], baseline: List[dict]
) -> Tuple[List[Finding], List[dict]]:
    """Split `findings` against the baseline. Returns
    (new_findings, stale_entries): a baseline entry absorbs up to
    `count` (default 1) findings with its (rule, path, symbol); entries
    that absorb nothing are STALE — the finding they recorded was fixed
    and the entry must be deleted (non-growing baseline)."""
    budget: Dict[Tuple[str, str, str], int] = {}
    for ent in baseline:
        key = (ent["rule"], ent["path"], ent.get("symbol", ""))
        budget[key] = budget.get(key, 0) + int(ent.get("count", 1))
    used: Dict[Tuple[str, str, str], int] = {k: 0 for k in budget}
    new: List[Finding] = []
    for f in findings:
        k = f.key()
        if budget.get(k, 0) > used.get(k, 0):
            used[k] += 1
        else:
            new.append(f)
    # Staleness is judged per KEY (entries sharing a key pooled their
    # counts above), reported once on the key's first entry — judging
    # per entry would call a sibling stale when the first one already
    # accounted for the key's findings.
    stale: List[dict] = []
    reported = set()
    for ent in baseline:
        key = (ent["rule"], ent["path"], ent.get("symbol", ""))
        if key in reported:
            continue
        reported.add(key)
        have = used.get(key, 0)
        want = budget.get(key, 0)
        if have == 0:
            stale.append(ent)
        elif want > have:
            # partial staleness: more budget than findings
            over = dict(ent)
            over["stale_count"] = want - have
            stale.append(over)
    return new, stale


def write_baseline(findings: List[Finding],
                   path: Optional[str] = None) -> str:
    """Serialize current findings as the new baseline (counts folded
    per (rule, path, symbol))."""
    path = path or default_baseline_path()
    counts: Dict[Tuple[str, str, str], int] = {}
    for f in findings:
        counts[f.key()] = counts.get(f.key(), 0) + 1
    entries = [
        {"rule": r, "path": p, "symbol": s, "count": c}
        for (r, p, s), c in sorted(counts.items())
    ]
    with open(path, "w", encoding="utf-8") as fh:
        json.dump({"findings": entries}, fh, indent=2, sort_keys=True)
        fh.write("\n")
    return path
