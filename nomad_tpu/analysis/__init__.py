"""ntalint: AST-based static analysis specialized to this codebase's
concurrency and JAX-purity invariants (see analysis/README.md).

Checker families, run over `nomad_tpu/` as a tier-1 test
(tests/test_static_analysis.py) and from the CLI (tools/ntalint.py):

- ``locks``    — lock-discipline: `# guarded-by:` attributes, blocking
  calls under locks, and never-block dispatcher-thread entrypoints
  (whole-program reachability from `NTA_DISPATCHER_ENTRYPOINTS`).
- ``purity``   — JAX trace-purity: impure/host calls, closure
  mutation, Python branching on traced values, unhashable static args.
- ``snapshot`` — scheduler/dispatch modules read cluster state only
  through StateStore.snapshot() handles, never the live store.
- ``robustness`` — no unbounded waits in (or cross-module reachable
  from) server//dispatch//trace//admission/, no silently-swallowed
  broad exceptions in server//dispatch//client//trace//admission/
  (the failure classes nomad_tpu/chaos fault injection hunts),
  and no blocking call or unbounded container growth on the flight
  recorder's record path (`NTA_RECORD_PATH` manifest — the functions
  the broker lock and the dispatcher thread run).
- ``residency`` — no host->device transfer on the steady-state
  dispatch/scheduler/models paths outside `NTA_REBUILD_ENTRYPOINTS`.
- ``deadlock`` — whole-program lock-acquisition-order graph (lexical
  nesting + lock-held call reachability); any cycle between distinct
  locks is reported with a full witness path.
- ``protocol`` — the raft funnel: state-store mutators and terminal
  status/trigger stamps only inside (or flowing into) the funnels an
  `NTA_RAFT_FUNNELS` manifest declares.

All manifest rules share ONE definition of "reachable from":
`core.Program`, the cross-module call graph (imports, module-attr
calls, self-methods through inheritance, constructor-typed
attributes; dynamic dispatch and pool/thread handoffs deliberately
not followed).
"""

from .core import (  # noqa: F401
    Finding,
    Program,
    RULESET_VERSION,
    analyze_paths,
    apply_baseline,
    clear_caches,
    load_baseline,
    load_disk_cache,
    save_disk_cache,
    write_baseline,
)

ALL_RULES = (
    "parse-error",
    "guarded-by",
    "lock-blocking-call",
    "dispatcher-blocking-call",
    "trace-impure-call",
    "trace-host-sync",
    "trace-closure-mutation",
    "trace-python-branch",
    "jit-unhashable-static",
    "live-state-read",
    "unbounded-wait",
    "swallowed-exception",
    "record-path-blocking",
    "full-matrix-reship",
    "deadlock-cycle",
    "raft-funnel",
)
