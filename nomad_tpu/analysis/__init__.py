"""ntalint: AST-based static analysis specialized to this codebase's
concurrency and JAX-purity invariants (see analysis/README.md).

Three checker families, run over `nomad_tpu/` as a tier-1 test
(tests/test_static_analysis.py) and from the CLI (tools/ntalint.py):

- ``locks``    — lock-discipline: `# guarded-by:` attributes, blocking
  calls under locks, and never-block dispatcher-thread entrypoints.
- ``purity``   — JAX trace-purity: impure/host calls, closure
  mutation, Python branching on traced values, unhashable static args.
- ``snapshot`` — scheduler/dispatch modules read cluster state only
  through StateStore.snapshot() handles, never the live store.
- ``robustness`` — no unbounded waits in server//dispatch//trace/, no
  silently-swallowed broad exceptions in server//dispatch//client//
  trace/ (the failure classes nomad_tpu/chaos fault injection hunts),
  and no blocking call or unbounded container growth on the flight
  recorder's record path (`NTA_RECORD_PATH` manifest — the functions
  the broker lock and the dispatcher thread run).
"""

from .core import (  # noqa: F401
    Finding,
    analyze_paths,
    apply_baseline,
    load_baseline,
    write_baseline,
)

ALL_RULES = (
    "parse-error",
    "guarded-by",
    "lock-blocking-call",
    "dispatcher-blocking-call",
    "trace-impure-call",
    "trace-host-sync",
    "trace-closure-mutation",
    "trace-python-branch",
    "jit-unhashable-static",
    "live-state-read",
    "unbounded-wait",
    "swallowed-exception",
    "record-path-blocking",
)
