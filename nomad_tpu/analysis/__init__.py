"""ntalint: AST-based static analysis specialized to this codebase's
concurrency and JAX-purity invariants (see analysis/README.md).

Checker families, run over `nomad_tpu/` as a tier-1 test
(tests/test_static_analysis.py) and from the CLI (tools/ntalint.py):

- ``locks``    — lock-discipline: `# guarded-by:` attributes, blocking
  calls under locks, and never-block dispatcher-thread entrypoints
  (whole-program reachability from `NTA_DISPATCHER_ENTRYPOINTS`).
- ``purity``   — JAX trace-purity: impure/host calls, closure
  mutation, Python branching on traced values, unhashable static args.
- ``snapshot`` — scheduler/dispatch modules read cluster state only
  through StateStore.snapshot() handles, never the live store.
- ``robustness`` — no unbounded waits in (or cross-module reachable
  from) server//dispatch//trace//admission/, no silently-swallowed
  broad exceptions in server//dispatch//client//trace//admission/
  (the failure classes nomad_tpu/chaos fault injection hunts),
  and no blocking call or unbounded container growth on the flight
  recorder's record path (`NTA_RECORD_PATH` manifest — the functions
  the broker lock and the dispatcher thread run).
- ``residency`` — no host->device transfer on the steady-state
  dispatch/scheduler/models paths outside `NTA_REBUILD_ENTRYPOINTS`.
- ``deadlock`` — whole-program lock-acquisition-order graph (lexical
  nesting + lock-held call reachability); any cycle between distinct
  locks is reported with a full witness path.
- ``protocol`` — the raft funnel: state-store mutators and terminal
  status/trigger stamps only inside (or flowing into) the funnels an
  `NTA_RAFT_FUNNELS` manifest declares.
- ``compile_surface`` — the jit cache is statically bounded:
  data-dependent shapes route through registered bucket functions
  before they can reach a jitted entry point (`unbucketed-shape`),
  static args at jitted call sites are stable keys, not per-eval
  builds (`static-key-drift`), every compiled entry point in
  ops//kernels//models//parallel/ is accounted by
  `ops/binpack.py jit_cache_size()` via the `NTA_JIT_ACCOUNTED`
  manifest (`unregistered-jit`), and no buffer is read after being
  passed in a donated position (`donation-unsafe-read` — the rail
  for ROADMAP item 3's donated cohort programs).

All manifest rules share ONE definition of "reachable from":
`core.Program`, the cross-module call graph (imports, module-attr
calls, self-methods through inheritance, constructor-typed
attributes; dynamic dispatch and pool/thread handoffs deliberately
not followed).
"""

from .core import (  # noqa: F401
    Finding,
    Program,
    RULESET_VERSION,
    analyze_paths,
    apply_baseline,
    clear_caches,
    load_baseline,
    load_disk_cache,
    save_disk_cache,
    write_baseline,
)

ALL_RULES = (
    "parse-error",
    "guarded-by",
    "lock-blocking-call",
    "dispatcher-blocking-call",
    "trace-impure-call",
    "trace-host-sync",
    "trace-closure-mutation",
    "trace-python-branch",
    "jit-unhashable-static",
    "live-state-read",
    "unbounded-wait",
    "swallowed-exception",
    "record-path-blocking",
    "full-matrix-reship",
    "deadlock-cycle",
    "raft-funnel",
    "unbucketed-shape",
    "static-key-drift",
    "unregistered-jit",
    "donation-unsafe-read",
)

# One-line docs per rule, emitted as SARIF driver rule metadata by
# tools/ntalint.py. tests/test_static_analysis.py asserts this table
# covers ALL_RULES exactly — a new rule that forgets its entry fails
# tier-1 (the generalized fix for the PR 7 full-matrix-reship SARIF
# omission).
RULE_DOCS = {
    "parse-error": "file does not parse (mid-edit tree, --diff)",
    "guarded-by": "attribute with a '# guarded-by:' contract accessed "
                  "outside its lock",
    "lock-blocking-call": "blocking call while holding a hot lock",
    "dispatcher-blocking-call": "blocking call reachable from an "
                                "NTA_DISPATCHER_ENTRYPOINTS entry",
    "trace-impure-call": "RNG/clock/IO inside traced code runs at "
                         "trace time only",
    "trace-host-sync": "device->host materialization inside traced "
                       "code",
    "trace-closure-mutation": "closed-over state mutated inside "
                              "traced code",
    "trace-python-branch": "Python branch on a traced value",
    "jit-unhashable-static": "unhashable literal in a jitted static "
                             "position",
    "live-state-read": "scheduler/dispatch read of live state instead "
                       "of a snapshot handle",
    "unbounded-wait": "no-timeout wait/get/join on a control-plane "
                      "path",
    "swallowed-exception": "broad exception handler with an empty "
                           "body",
    "record-path-blocking": "blocking call or unbounded growth on the "
                            "flight-recorder record path",
    "full-matrix-reship": "full-matrix device reship outside "
                          "NTA_REBUILD_ENTRYPOINTS",
    "deadlock-cycle": "cycle in the whole-program lock acquisition "
                      "order",
    "raft-funnel": "state mutation outside the NTA_RAFT_FUNNELS "
                   "funnels",
    "unbucketed-shape": "data-dependent array shape escapes toward a "
                        "jitted entry point without a bucket function",
    "static-key-drift": "per-eval static arg (f-string/computed "
                        "value/fresh tuple) at a jitted call site",
    "unregistered-jit": "compiled entry point absent from the "
                        "NTA_JIT_ACCOUNTED jit_cache_size() manifest",
    "donation-unsafe-read": "buffer read after being passed in a "
                            "donated argument position",
}
